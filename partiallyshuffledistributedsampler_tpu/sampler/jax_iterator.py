"""JAX-native epoch iteration: indices never leave the device.

The torch shim streams indices to the host because torch Datasets live
there.  A JAX input pipeline doesn't need that: the epoch index tensor stays
in HBM and per-step batches are sliced/gathered inside the jitted train step
(models/train.py does exactly this).  This module packages that pattern for
standalone use, with double-buffered epoch prefetch.
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp

from ..ops import core
from ..ops.xla import build_evaluator, epoch_indices_jax


def batch_index_window(epoch_idx: jax.Array, step, batch: int) -> jax.Array:
    """The step's index window as a device array — usable inside jit.
    ``epoch_idx`` is [num_samples] (one rank) or [dp, num_samples]."""
    if epoch_idx.ndim == 1:
        return jax.lax.dynamic_slice(epoch_idx, (step * batch,), (batch,))
    dp = epoch_idx.shape[0]
    return jax.lax.dynamic_slice(epoch_idx, (0, step * batch), (dp, batch))


class DeviceEpochIterator:
    """Per-epoch, per-step index windows with next-epoch prefetch.

        it = DeviceEpochIterator(n=1_000_000, window=8192, batch=512,
                                 seed=0, rank=0, world=8)
        for epoch in range(E):
            for idx_batch in it.epoch(epoch):   # device int32[batch]
                loss = train_step(params, data, idx_batch)

    ``epoch()`` dispatches epoch e+1's regen before yielding e's first batch,
    so the next epoch's permutation is computed while this epoch trains —
    regen latency is fully hidden, which is how the "<1 ms" budget becomes
    "0 ms observed" in a real loop.

    ``epoch()`` costs one eager slice dispatch per step (microseconds on
    real hardware).  Loops whose body is jittable should prefer
    :meth:`run_epoch` (whole epoch, one dispatch) or :meth:`run_epochs`
    (whole run, one dispatch, regen in-program) — same values, no
    per-step dispatches at all; the noise-subtracted stall harness
    (benchmarks/stall_native.py) measures exactly this difference.
    """

    def __init__(
        self,
        n: int,
        window: int,
        batch: int,
        *,
        seed: int = 0,
        rank: int = 0,
        world: int = 1,
        drop_last_batch: bool = True,
        prefetch_next_epoch: bool = True,
        **kwargs,
    ) -> None:
        if not 0 <= rank < world:
            raise ValueError(f"rank must be in [0, {world}), got {rank}")
        self.n, self.window, self.batch = n, window, batch
        self.seed, self.rank, self.world = seed, rank, world
        self.kwargs = kwargs
        self.num_samples, _ = core.shard_sizes(
            n, world, kwargs.get("drop_last", False)
        )
        if drop_last_batch:
            self.steps_per_epoch = self.num_samples // batch
        else:
            self.steps_per_epoch = -(-self.num_samples // batch)
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"batch={batch} exceeds the rank's {self.num_samples} samples"
            )
        self.prefetch_next_epoch = prefetch_next_epoch
        self._cache: dict[int, jax.Array] = {}
        self._runners: dict = {}

    def _regen(self, epoch: int) -> jax.Array:
        return epoch_indices_jax(
            self.n, self.window, self.seed, epoch, self.rank, self.world,
            **self.kwargs,
        )

    def epoch_array(self, epoch: int) -> jax.Array:
        arr = self._cache.pop(epoch, None)
        if arr is None:
            arr = self._regen(epoch)
        return arr

    def _prefetch(self, epoch: int) -> None:
        # async dispatch — device works on it behind this epoch's steps
        self._cache[epoch + 1] = self._regen(epoch + 1)
        if len(self._cache) > 2:  # bound memory if epochs are skipped
            for k in sorted(self._cache)[:-2]:
                del self._cache[k]

    def epoch(self, epoch: int) -> Iterator[jax.Array]:
        idx = self.epoch_array(epoch)
        if self.prefetch_next_epoch:
            self._prefetch(epoch)
        for s in range(self.steps_per_epoch):
            start = s * self.batch
            size = min(self.batch, self.num_samples - start)
            if size == self.batch:
                yield jax.lax.dynamic_slice(idx, (start,), (self.batch,))
            else:
                yield idx[start:start + size]

    def _cached_runner(self, key, build):
        """LRU (bound 4) over compiled runners: refresh recency on hit,
        evict the least recently USED on miss — a hot step_fn must never
        be evicted and silently recompiled."""
        runner = self._runners.pop(key, None)
        if runner is None:
            if len(self._runners) >= 4:
                self._runners.pop(next(iter(self._runners)))
            runner = build()
        self._runners[key] = runner
        return runner

    def _step_scan_body(self, step_fn, collect: bool):
        """The shared inner scan body: slice step s's batch out of a
        device-resident epoch index tensor, run step_fn."""
        batch = self.batch

        def over(idx):
            def body(c, s):
                b = jax.lax.dynamic_slice(idx, (s * batch,), (batch,))
                out = step_fn(c, b)
                return out if collect else (out, None)

            return body

        return over

    def run_epoch(self, epoch: int, step_fn, carry, *,
                  steps: Optional[int] = None, collect: bool = False):
        """Run an epoch's training steps in ONE compiled program.

        ``lax.scan`` drives ``step_fn`` over the epoch's step windows with
        the batch slice fused into the program, so a whole epoch costs a
        single dispatch — no per-step Python or eager-slice overhead at
        all (the ``epoch()`` iterator pays one eager dispatch per step,
        which is µs on real hardware but is also simply unnecessary when
        the loop body is jittable).

        ``step_fn(carry, idx_batch) -> carry`` — or, with
        ``collect=True``, ``-> (carry, y)``, and the stacked ``y``s are
        returned alongside the final carry (the usual per-step-loss
        pattern).  ``steps`` caps the step count; the default is every
        WHOLE batch (a trailing partial batch can't share the scanned
        program's shape — drive it through ``epoch()`` if it matters).
        The compiled runner is cached per ``(step_fn, steps, collect)``,
        keyed on the function OBJECT — pass the same function each epoch
        to reuse it; the cache holds the 4 most recent runners, so a
        fresh lambda per call recompiles every time.  Next-epoch prefetch
        is dispatched before the scan, exactly like ``epoch()``.
        """
        arr = self.epoch_array(epoch)
        if self.prefetch_next_epoch:
            self._prefetch(epoch)
        whole = self.num_samples // self.batch  # only whole batches scan
        nsteps = whole if steps is None else int(steps)
        if not 0 < nsteps <= whole:
            raise ValueError(
                f"steps={nsteps} not in [1, {whole}]"
                " (only whole batches can be scanned)"
            )
        def build():
            over = self._step_scan_body(step_fn, collect)

            @jax.jit
            def runner(carry, idx):
                c, ys = jax.lax.scan(
                    over(idx), carry, jnp.arange(nsteps, dtype=jnp.int32)
                )
                return (c, ys) if collect else c

            return runner

        runner = self._cached_runner((step_fn, nsteps, bool(collect)), build)
        return runner(carry, arr)

    def run_epochs(self, first_epoch: int, n_epochs: int, step_fn, carry,
                   *, collect: bool = False):
        """Run ``n_epochs`` WHOLE epochs as one compiled program.

        The permutation is a pure function of the traced epoch scalar, so
        regen itself moves inside the program: an outer ``lax.scan`` over
        epochs regenerates each epoch's index tensor in-program (via
        ``ops.xla.build_evaluator``) and an inner scan drives ``step_fn``
        over its batches — an entire training run with ZERO host
        round-trips, the logical extreme of the on-device design (even
        ``set_epoch``'s one async dispatch per epoch disappears).

        ``step_fn`` as in :meth:`run_epoch`.  With ``collect=True`` the
        stacked outputs have shape ``[n_epochs, steps, ...]``.  Note the
        epoch index tensor lives in HBM once per live epoch (the scan
        carries none across epochs).  The iterator's epoch cache is not
        consulted — regen is recomputed in-program, bit-identically.
        """
        whole = self.num_samples // self.batch
        if whole == 0:
            raise ValueError("batch exceeds the rank's whole-batch budget")
        if int(n_epochs) < 1:
            raise ValueError(f"n_epochs must be >= 1, got {n_epochs}")

        def build():
            over = self._step_scan_body(step_fn, collect)
            ev = build_evaluator(
                self.n, self.window, self.world,
                drop_last=self.kwargs.get("drop_last", False),
                order_windows=self.kwargs.get("order_windows", True),
                partition=self.kwargs.get("partition", "strided"),
                rounds=self.kwargs.get("rounds", core.DEFAULT_ROUNDS),
                shuffle=self.kwargs.get("shuffle", True),
            )
            seed_lo, seed_hi = core.fold_seed(self.seed)
            base = jnp.asarray(
                [seed_lo & 0xFFFFFFFF, seed_hi & 0xFFFFFFFF, 0,
                 self.rank & 0xFFFFFFFF],
                dtype=jnp.uint32,
            )

            @jax.jit
            def runner(carry, first):
                def epoch_body(c, e):
                    sv = base.at[2].set(e.astype(jnp.uint32))
                    idx = ev(sv)
                    return jax.lax.scan(
                        over(idx), c, jnp.arange(whole, dtype=jnp.int32)
                    )

                return jax.lax.scan(
                    epoch_body, carry,
                    first + jnp.arange(n_epochs, dtype=jnp.int32),
                )

            return runner

        runner = self._cached_runner(
            (step_fn, "epochs", int(n_epochs), bool(collect)), build
        )
        carry, ys = runner(carry, jnp.int32(first_epoch))
        return (carry, ys) if collect else carry
