"""JAX-native epoch iteration: indices never leave the device.

The torch shim streams indices to the host because torch Datasets live
there.  A JAX input pipeline doesn't need that: the epoch index tensor stays
in HBM and per-step batches are sliced/gathered inside the jitted train step
(models/train.py does exactly this).  This module packages that pattern for
standalone use, with double-buffered epoch prefetch.
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp

from ..ops import core
from ..ops.xla import build_evaluator, epoch_indices_jax


def batch_index_window(epoch_idx: jax.Array, step, batch: int) -> jax.Array:
    """The step's index window as a device array — usable inside jit.
    ``epoch_idx`` is [num_samples] (one rank) or [dp, num_samples]."""
    if epoch_idx.ndim == 1:
        return jax.lax.dynamic_slice(epoch_idx, (step * batch,), (batch,))
    dp = epoch_idx.shape[0]
    return jax.lax.dynamic_slice(epoch_idx, (0, step * batch), (dp, batch))


class DeviceEpochIterator:
    """Per-epoch, per-step index windows with next-epoch prefetch.

        it = DeviceEpochIterator(n=1_000_000, window=8192, batch=512,
                                 seed=0, rank=0, world=8)
        for epoch in range(E):
            for idx_batch in it.epoch(epoch):   # device int32[batch]
                loss = train_step(params, data, idx_batch)

    ``epoch()`` dispatches epoch e+1's regen before yielding e's first batch,
    so the next epoch's permutation is computed while this epoch trains —
    regen latency is fully hidden, which is how the "<1 ms" budget becomes
    "0 ms observed" in a real loop.

    ``epoch()`` costs one slice-and-unstack dispatch per ``_SPLIT_CHUNK``
    (512) steps — NOT one per step: a single compiled program slices a
    chunk of the epoch tensor and returns every step's batch as its own
    device buffer, so the per-step cost is a Python yield.  The chunk
    programs are double-buffered — chunk c+1 (and, across the boundary,
    the next epoch's first chunk) is dispatched while chunk c's buffers
    are being consumed — so neither the chunk seam nor the epoch
    boundary waits on a dispatch.  Loops whose
    body is jittable should still prefer :meth:`run_epoch` (whole epoch,
    one dispatch) or :meth:`run_epochs` (whole run, one dispatch, regen
    in-program) — same values, zero dispatches between steps; the
    noise-subtracted stall harness (benchmarks/stall_native.py) measures
    exactly this difference.
    """

    #: steps per unstack program in ``epoch()``: bounds both XLA output
    #: arity (compile time grows with outputs) and the transient second
    #: copy of the sliced chunk
    _SPLIT_CHUNK = 512

    def __init__(
        self,
        n: int,
        window: int,
        batch: int,
        *,
        seed: int = 0,
        rank: int = 0,
        world: int = 1,
        drop_last_batch: bool = True,
        prefetch_next_epoch: bool = True,
        **kwargs,
    ) -> None:
        if not 0 <= rank < world:
            raise ValueError(f"rank must be in [0, {world}), got {rank}")
        self.n, self.window, self.batch = n, window, batch
        self.seed, self.rank, self.world = seed, rank, world
        self.kwargs = kwargs
        self.num_samples, _ = core.shard_sizes(
            n, world, kwargs.get("drop_last", False)
        )
        self.drop_last_batch = bool(drop_last_batch)
        if drop_last_batch:
            self.steps_per_epoch = self.num_samples // batch
        else:
            self.steps_per_epoch = -(-self.num_samples // batch)
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"batch={batch} exceeds the rank's {self.num_samples} samples"
            )
        self.prefetch_next_epoch = prefetch_next_epoch
        self._cache: dict[int, jax.Array] = {}
        #: epoch -> (idx array, first chunk's pre-dispatched unstack
        #: buffers): the boundary half of the double-buffered ring
        self._ring: dict[int, tuple] = {}
        self._runners: dict = {}

    def _regen(self, epoch: int) -> jax.Array:
        return epoch_indices_jax(
            self.n, self.window, self.seed, epoch, self.rank, self.world,
            **self.kwargs,
        )

    def epoch_array(self, epoch: int) -> jax.Array:
        arr = self._cache.pop(epoch, None)
        if arr is None:
            arr = self._regen(epoch)
        return arr

    def _prefetch(self, epoch: int) -> None:
        # async dispatch — device works on it behind this epoch's steps
        self._cache[epoch + 1] = self._regen(epoch + 1)
        if len(self._cache) > 2:  # bound memory if epochs are skipped
            for k in sorted(self._cache)[:-2]:
                del self._cache[k]

    def _ring_dispatch(self, epoch: int) -> None:
        """Pre-dispatch ``epoch``'s FIRST chunk unstack behind the current
        epoch's steps: the next ``epoch()`` call finds its opening batches
        already split into per-step buffers, so the boundary dispatch
        overlaps the previous epoch's tail instead of gapping it.  Only
        the chunked serve path pays (and benefits): ``run_epoch`` scans
        in-program and never consults the ring."""
        arr = self._cache.get(epoch)
        if arr is None:
            return
        whole = int(arr.shape[0]) // self.batch
        if whole:
            c = min(self._SPLIT_CHUNK, whole)
            split = self._cached_runner(
                ("split", c), lambda c=c: self._build_split(c)
            )
            self._ring.clear()  # at most one boundary in flight
            self._ring[epoch] = (arr, split(arr, 0))

    def _build_split(self, chunk: int):
        """One program: slice ``chunk`` whole batches starting at a traced
        offset and unstack them — every step's batch comes back as its own
        device buffer from a single dispatch."""
        batch = self.batch

        @jax.jit
        def split(idx, start):
            block = jax.lax.dynamic_slice(idx, (start,), (chunk * batch,))
            return tuple(block.reshape(chunk, batch))

        return split

    def _serve_chunked(self, idx: jax.Array, *,
                       ring: Optional[tuple] = None) -> Iterator[jax.Array]:
        """Serve an index tensor as per-step batches: whole batches via the
        chunked one-dispatch unstack programs, then (drop_last_batch=False)
        the trailing partial batch.  epoch() and elastic_epoch() both route
        here — the serve law lives once.

        The chunk programs run DOUBLE-BUFFERED: chunk c+1's unstack is
        dispatched before chunk c's buffers are yielded, so the device
        splits the next chunk while the consumer steps through this one;
        ``ring`` additionally adopts the epoch's first chunk when
        ``_ring_dispatch`` pre-split it behind the previous epoch."""
        ns = int(idx.shape[0])
        whole = ns // self.batch
        s = 0
        ahead = None  # (start_step, bufs) dispatched one chunk ahead
        if ring is not None and ring[0] is idx:
            # the identity check pins correctness: the pre-split buffers
            # are adopted only for the exact array they were cut from
            ahead = (0, ring[1])
        while s < whole:
            c = min(self._SPLIT_CHUNK, whole - s)
            if ahead is not None and ahead[0] == s and len(ahead[1]) == c:
                bufs = ahead[1]
            else:
                split = self._cached_runner(
                    ("split", c), lambda c=c: self._build_split(c)
                )
                bufs = split(idx, s * self.batch)
            nxt = s + c
            ahead = None
            if nxt < whole:
                c2 = min(self._SPLIT_CHUNK, whole - nxt)
                split2 = self._cached_runner(
                    ("split", c2), lambda c=c2: self._build_split(c2)
                )
                ahead = (nxt, split2(idx, nxt * self.batch))
            yield from bufs
            s = nxt
        if ns > whole * self.batch and not self.drop_last_batch:
            yield idx[whole * self.batch:]

    def epoch(self, epoch: int) -> Iterator[jax.Array]:
        epoch = int(epoch)
        # an epoch (or streaming horizon-generation, docs/STREAMING.md)
        # bump is a boundary for every cache: entries BELOW the epoch
        # being served can never be legitimately served again — a
        # moving-horizon stream only advances — so drop them now rather
        # than letting a stale horizon's indices (or its HBM) outlive
        # the advance
        for k in [k for k in self._cache if k < epoch]:
            del self._cache[k]
        for k in [k for k in self._ring if k < epoch]:
            del self._ring[k]
        idx = self.epoch_array(epoch)
        # adopt this epoch's pre-split first chunk BEFORE dispatching the
        # next boundary (the ring holds at most one epoch)
        ring = self._ring.pop(int(epoch), None)
        if self.prefetch_next_epoch:
            self._prefetch(epoch)
            self._ring_dispatch(int(epoch) + 1)
        yield from self._serve_chunked(idx, ring=ring)

    def elastic_epoch_array(self, epoch: int, layers) -> jax.Array:
        """This rank's remainder-epoch indices after a world-size change
        (SPEC.md §6): build the iterator at the NEW ``(rank, world)`` and
        pass the checkpoint cascade ``[(old_world, consumed), ...]``
        outermost first.  One jitted dispatch (ops.xla.elastic_indices_jax);
        bit-identical to the torch shim's ``reshard_from_state_dict``
        stream for the same layers."""
        from ..ops.xla import elastic_indices_jax

        chain, remaining, ns = core.elastic_chain(
            self.n, layers, self.world, self.kwargs.get("drop_last", False)
        )
        if remaining == 0:
            dtype = jnp.int32 if self.n <= 0x7FFFFFFF else jnp.int64
            return jnp.empty((0,), dtype)
        return elastic_indices_jax(
            self.n, self.window, self.seed, epoch, self.rank, self.world,
            ns, chain,
            shuffle=self.kwargs.get("shuffle", True),
            order_windows=self.kwargs.get("order_windows", True),
            partition=self.kwargs.get("partition", "strided"),
            rounds=self.kwargs.get("rounds", core.DEFAULT_ROUNDS),
        )

    def elastic_epoch(self, epoch: int, layers) -> Iterator[jax.Array]:
        """Per-step batches of the remainder epoch (SPEC.md §6), served
        with the same chunked one-dispatch unstacking as :meth:`epoch`.
        After this epoch finishes, continue with ordinary :meth:`epoch`
        calls — the next epoch is a full epoch at the new world size."""
        yield from self._serve_chunked(self.elastic_epoch_array(epoch, layers))

    def _cached_runner(self, key, build):
        """LRU (bound 4) over compiled runners: refresh recency on hit,
        evict the least recently USED on miss — a hot step_fn must never
        be evicted and silently recompiled."""
        runner = self._runners.pop(key, None)
        if runner is None:
            if len(self._runners) >= 4:
                self._runners.pop(next(iter(self._runners)))
            runner = build()
        self._runners[key] = runner
        return runner

    def _step_scan_body(self, step_fn, collect: bool):
        """The shared inner scan body: slice step s's batch out of a
        device-resident epoch index tensor, run step_fn."""
        batch = self.batch

        def over(idx):
            def body(c, s):
                b = jax.lax.dynamic_slice(idx, (s * batch,), (batch,))
                out = step_fn(c, b)
                return out if collect else (out, None)

            return body

        return over

    def _tail_plan(self, on_tail: str, steps, collect: bool) -> int:
        """Validate the scanned runners' tail-batch contract and return the
        tail length to run in-program (0 = none).

        A trailing partial batch exists only when the iterator was built
        with ``drop_last_batch=False`` — i.e. the user asked for tail
        service.  Scans carry a fixed batch shape, so the tail can't ride
        the scan; it must be explicitly handled:

        * ``on_tail='error'`` (default): refuse to run, naming the choices
          — a ``drop_last_batch=False`` user never silently loses samples.
        * ``on_tail='run'``: one extra ``step_fn(carry, tail_idx)`` step is
          fused into the compiled program after the scan.  Incompatible
          with ``collect=True`` (the tail's output shape can't stack with
          the scanned ys) and with a ``steps`` cap (a partial scan
          followed by the tail would skip the batches in between).
        * ``on_tail='drop'``: scan whole batches only, acknowledged.

        With ``drop_last_batch=True`` (the default) there is no tail by
        construction and ``on_tail`` is irrelevant.
        """
        if on_tail not in ("error", "run", "drop"):
            raise ValueError(
                f"on_tail must be 'error', 'run' or 'drop', got {on_tail!r}"
            )
        tail = self.num_samples % self.batch
        if tail == 0 or self.drop_last_batch:
            return 0  # no tail, or the constructor opted out of it already
        if on_tail == "error":
            raise ValueError(
                f"this iterator serves a trailing partial batch of {tail} "
                f"(drop_last_batch=False) which a scanned runner cannot "
                f"carry; pass on_tail='run' to fuse it as one extra step, "
                f"on_tail='drop' to scan whole batches only, or use epoch()"
            )
        if on_tail == "drop":
            return 0
        if collect:
            raise ValueError(
                "on_tail='run' is incompatible with collect=True: the tail "
                "step's output cannot stack with the scanned ys — use "
                "on_tail='drop' and run the tail through epoch(), or "
                "collect=False"
            )
        if steps is not None:
            raise ValueError(
                "on_tail='run' requires steps=None: a capped scan followed "
                "by the tail would silently skip the batches in between"
            )
        return tail

    def run_epoch(self, epoch: int, step_fn, carry, *,
                  steps: Optional[int] = None, collect: bool = False,
                  on_tail: str = "error"):
        """Run an epoch's training steps in ONE compiled program.

        ``lax.scan`` drives ``step_fn`` over the epoch's step windows with
        the batch slice fused into the program, so a whole epoch costs a
        single dispatch — no per-step Python or eager-slice overhead at
        all (the ``epoch()`` iterator pays one eager dispatch per step,
        which is µs on real hardware but is also simply unnecessary when
        the loop body is jittable).

        ``step_fn(carry, idx_batch) -> carry`` — or, with
        ``collect=True``, ``-> (carry, y)``, and the stacked ``y``s are
        returned alongside the final carry (the usual per-step-loss
        pattern).  ``steps`` caps the step count; the default is every
        WHOLE batch (a trailing partial batch can't share the scanned
        program's shape — drive it through ``epoch()`` if it matters).
        The compiled runner is cached per ``(step_fn, steps, collect)``,
        keyed on the function OBJECT — pass the same function each epoch
        to reuse it; the cache holds the 4 most recent runners, so a
        fresh lambda per call recompiles every time.  Next-epoch prefetch
        is dispatched before the scan, exactly like ``epoch()``.

        When the iterator was built with ``drop_last_batch=False`` and the
        epoch has a trailing partial batch, ``on_tail`` decides its fate —
        see :meth:`_tail_plan`; the default refuses loudly rather than
        silently dropping samples the iterator contract promised to serve.
        """
        # validate BEFORE dispatching any device work: a bad steps/on_tail
        # must not trigger regen dispatches or mutate the prefetch cache.
        # _tail_plan goes first so a tail-only epoch (num_samples < batch,
        # drop_last_batch=False) gets the tail-contract guidance, and with
        # on_tail='run' such an epoch is runnable: a zero-length scan plus
        # the fused tail step.
        whole = self.num_samples // self.batch  # only whole batches scan
        tail = self._tail_plan(on_tail, steps, collect)
        nsteps = whole if steps is None else int(steps)
        if not (0 < nsteps <= whole or (nsteps == 0 and tail)):
            raise ValueError(
                f"steps={nsteps} not in [1, {whole}]"
                " (only whole batches can be scanned)"
            )
        arr = self.epoch_array(epoch)
        if self.prefetch_next_epoch:
            self._prefetch(epoch)

        def build():
            over = self._step_scan_body(step_fn, collect)
            tail_start = whole * self.batch

            @jax.jit
            def runner(carry, idx):
                if nsteps:  # static: a tail-only epoch scans nothing
                    c, ys = jax.lax.scan(
                        over(idx), carry, jnp.arange(nsteps, dtype=jnp.int32)
                    )
                else:
                    c, ys = carry, None
                if tail:  # one extra fused step on the static tail slice
                    c = step_fn(c, idx[tail_start:tail_start + tail])
                return (c, ys) if collect else c

            return runner

        runner = self._cached_runner(
            (step_fn, nsteps, bool(collect), tail), build
        )
        return runner(carry, arr)

    #: the epoch_indices_jax kwargs an in-program evaluator can honor.
    #: ``use_pallas`` is deliberately absent: run_epochs regenerates
    #: in-program through the pure-jnp evaluator (build_evaluator), which
    #: never uses Pallas — values are bit-identical either way.
    _IN_PROGRAM_KWARGS = (
        "shuffle", "drop_last", "order_windows", "partition", "rounds",
        "amortize",
    )

    def _in_program_evaluator(self):
        """The jit-composable ``sv -> ids`` evaluator ``run_epochs`` scans
        per epoch — the ONE hook a stream subclass overrides to join the
        zero-host-round-trip tier (MixtureEpochIterator does)."""
        return build_evaluator(
            self.n, self.window, self.world,
            **{k: self.kwargs[k] for k in self._IN_PROGRAM_KWARGS
               if k in self.kwargs},
        )

    def run_epochs(self, first_epoch: int, n_epochs: int, step_fn, carry,
                   *, collect: bool = False, on_tail: str = "error"):
        """Run ``n_epochs`` WHOLE epochs as one compiled program.

        The permutation is a pure function of the traced epoch scalar, so
        regen itself moves inside the program: an outer ``lax.scan`` over
        epochs regenerates each epoch's index tensor in-program (via
        ``ops.xla.build_evaluator``) and an inner scan drives ``step_fn``
        over its batches — an entire training run with ZERO host
        round-trips, the logical extreme of the on-device design (even
        ``set_epoch``'s one async dispatch per epoch disappears).

        ``step_fn`` as in :meth:`run_epoch`.  With ``collect=True`` the
        stacked outputs have shape ``[n_epochs, steps, ...]``.  Note the
        epoch index tensor lives in HBM once per live epoch (the scan
        carries none across epochs).  The iterator's epoch cache is not
        consulted — regen is recomputed in-program, bit-identically, and
        every iterator kwarg except ``use_pallas`` is honored by the
        in-program evaluator (see ``_IN_PROGRAM_KWARGS``).  Tail batches
        follow the same ``on_tail`` contract as :meth:`run_epoch` — when
        run, the tail step is fused after each epoch's inner scan.
        """
        whole = self.num_samples // self.batch
        tail = self._tail_plan(on_tail, None, collect)
        if whole == 0 and not tail:
            raise ValueError("batch exceeds the rank's whole-batch budget")
        if int(n_epochs) < 1:
            raise ValueError(f"n_epochs must be >= 1, got {n_epochs}")

        def build():
            over = self._step_scan_body(step_fn, collect)
            ev = self._in_program_evaluator()
            tail_start = whole * self.batch
            seed_lo, seed_hi = core.fold_seed(self.seed)
            base = jnp.asarray(
                [seed_lo & 0xFFFFFFFF, seed_hi & 0xFFFFFFFF, 0,
                 self.rank & 0xFFFFFFFF],
                dtype=jnp.uint32,
            )

            @jax.jit
            def runner(carry, first):
                def epoch_body(c, e):
                    sv = base.at[2].set(e.astype(jnp.uint32))
                    idx = ev(sv)
                    if whole:  # static: a tail-only epoch scans nothing
                        c, ys = jax.lax.scan(
                            over(idx), c, jnp.arange(whole, dtype=jnp.int32)
                        )
                    else:
                        ys = None
                    if tail:  # fused extra step on the static tail slice
                        c = step_fn(c, idx[tail_start:tail_start + tail])
                    return c, ys

                return jax.lax.scan(
                    epoch_body, carry,
                    first + jnp.arange(n_epochs, dtype=jnp.int32),
                )

            return runner

        runner = self._cached_runner(
            (step_fn, "epochs", int(n_epochs), bool(collect), tail), build
        )
        carry, ys = runner(carry, jnp.int32(first_epoch))
        return (carry, ys) if collect else carry


class MixtureEpochIterator(DeviceEpochIterator):
    """:class:`DeviceEpochIterator` over a weighted mixture (SPEC.md §8).

        it = MixtureEpochIterator(spec, batch=512, seed=0, rank=r, world=w)
        for epoch in range(E):
            state, losses = it.run_epoch(epoch, step, state, collect=True)

    Same drive modes and contracts as the single-source iterator —
    ``epoch()`` (chunked unstack + next-epoch prefetch), ``run_epoch``
    (whole epoch, one compiled program), ``elastic_epoch`` (remainder
    after a world change, via the §6-over-§8 law) — with the epoch index
    tensor holding mixture *global ids* (``spec.decompose`` splits them).
    The §4/§8.4 length laws coincide, so all sizing plumbing is inherited.

    ``run_epochs`` drives whole multi-epoch runs as ONE compiled program
    exactly like the single-source iterator: the in-program evaluator is
    the §8 stream (``ops.mixture.build_mixture_evaluator``), so mixture
    regen scans inside the program with zero host round-trips.
    """

    #: mixture regen additionally honors the fused-evaluator knob
    _IN_PROGRAM_KWARGS = DeviceEpochIterator._IN_PROGRAM_KWARGS + ("fused",)

    @property
    def windows(self) -> tuple:
        """Per-source §8 windows (the spec's)."""
        return self.spec.windows

    @property
    def window(self):
        """A mixture has no single window — refuse instead of publishing
        the base class's sentinel (round-4 verdict: introspecting it
        reported a meaningless 1)."""
        raise AttributeError(
            "a mixture iterator has no single window; use .windows "
            "(per-source, from the spec)"
        )

    @window.setter
    def window(self, value) -> None:
        # the base-class __init__ writes its (meaningless for mixtures)
        # window field once; swallow exactly that, refuse user writes
        if getattr(self, "_window_sealed", False):
            raise AttributeError(
                "a mixture iterator has no single window to set; the "
                "per-source windows live on the spec"
            )

    def __init__(
        self,
        spec,
        batch: int,
        *,
        seed: int = 0,
        rank: int = 0,
        world: int = 1,
        epoch_samples: Optional[int] = None,
        drop_last_batch: bool = True,
        prefetch_next_epoch: bool = True,
        **kwargs,
    ) -> None:
        from ..ops.mixture import MixtureSpec, mixture_epoch_sizes

        if not isinstance(spec, MixtureSpec):
            raise TypeError(
                f"spec must be a MixtureSpec, got {type(spec).__name__}"
            )
        self.spec = spec
        self.epoch_samples = (
            None if epoch_samples is None else int(epoch_samples)
        )
        T, _, _ = mixture_epoch_sizes(
            spec, epoch_samples, world, kwargs.get("drop_last", False)
        )
        # window is per-source state carried by the spec; the base-class
        # field is unused for mixtures (n=T drives all sizing, which is
        # the same §4 law)
        super().__init__(
            T, 1, batch, seed=seed, rank=rank, world=world,
            drop_last_batch=drop_last_batch,
            prefetch_next_epoch=prefetch_next_epoch, **kwargs,
        )
        # surface the strided-orbit starvation hazard at construction
        # (v1 / unshuffled streams only; v2 rotation is immune)
        spec.check_rank_balance(
            rank, world, self.kwargs.get("partition", "strided"),
            self.kwargs.get("shuffle", True),
        )
        self._window_sealed = True  # further .window writes refuse

    def _regen(self, epoch: int) -> jax.Array:
        from ..ops.mixture import mixture_epoch_indices_jax

        return mixture_epoch_indices_jax(
            self.spec, self.seed, epoch, self.rank, self.world,
            epoch_samples=self.epoch_samples, **self.kwargs,
        )

    def elastic_epoch_array(self, epoch: int, layers) -> jax.Array:
        from ..ops.mixture import mixture_elastic_indices_jax

        chain, remaining, ns = core.elastic_chain(
            self.n, layers, self.world, self.kwargs.get("drop_last", False)
        )
        if remaining == 0 or ns == 0:
            dtype = (jnp.int32 if self.spec.total_sources_len <= 0x7FFFFFFF
                     else jnp.int64)
            return jnp.empty((0,), dtype)
        return mixture_elastic_indices_jax(
            self.spec, self.seed, epoch, self.rank, self.world, layers,
            epoch_samples=self.epoch_samples, **self.kwargs,
        )

    def _in_program_evaluator(self):
        from ..ops.mixture import build_mixture_evaluator

        return build_mixture_evaluator(
            self.spec, self.world, epoch_samples=self.epoch_samples,
            **{k: self.kwargs[k] for k in self._IN_PROGRAM_KWARGS
               if k in self.kwargs},
        )
