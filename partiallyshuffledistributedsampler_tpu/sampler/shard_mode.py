"""Shard-index mode: partial shuffle over *storage shards* (WebDataset/tar,
tokenized C4 shard files — the [B] configs 3-4).

At billion-sample scale the shuffle unit is often the shard file, not the
sample: shard order is permuted globally (windowed, for locality across a
storage prefix), samples inside a shard stream sequentially or through a
windowed in-shard shuffle.  That is exactly the core law with
``n = num_shards`` (SURVEY.md §7 build order #7), so this module is a thin
vocabulary layer over the same spec — no second shuffle implementation.

The laws here are normative in SPEC.md §7: the per-shard seed derivation,
the within-shard order (the §3 permutation at ``n = shard_size``), and the
bounded shuffle-buffer stream are all spec'd and golden-tested, so shard
streams are checkpoint-stable across builds.
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, Optional, Sequence, Union

import numpy as np

from ..ops import core
from ..ops.cpu import epoch_indices_np
from .torch_shim import PartiallyShuffleDistributedSampler

#: SPEC.md §7 per-shard seed stride (the 64-bit golden ratio, as used by
#: splitmix64): shard ``sid`` draws its within-shard permutation from
#: ``seed XOR (_SHARD_SEED_STRIDE + sid)`` folded per SPEC.md §1.
_SHARD_SEED_STRIDE = 0x9E3779B97F4A7C15


def shard_seed(seed: int, sid: int) -> int:
    """The spec'd per-shard seed (SPEC.md §7).  Pure; any change is a spec
    version bump — checkpointed shard streams depend on it."""
    return int(seed) ^ (_SHARD_SEED_STRIDE + int(sid))


class PartialShuffleShardSampler(PartiallyShuffleDistributedSampler):
    """Yields shard ids for this rank, windowed-shuffled per epoch.

    Identical contract to the sample-level sampler; the ``window`` now
    bounds how far a shard moves from its stored order — keeping reads
    clustered within a storage prefix while still decorrelating epochs.
    """

    def __init__(self, num_shards: int, **kwargs) -> None:
        kwargs.setdefault("window", 64)
        super().__init__(int(num_shards), **kwargs)

    def device_epoch_indices(
        self,
        shard_sizes: Sequence[int],
        *,
        epoch: Optional[int] = None,
        within_shard_shuffle: Union[bool, int] = True,
    ):
        """This rank's expanded global sample indices for ``epoch``
        (default: current) as a DEVICE array in HBM — the JAX-native
        shard-mode epoch in one call: the rank's shard stream
        expanded through :func:`expand_shard_indices_jax` with this
        sampler's ``(seed, rounds)``.  Side-effect free: neither the
        consumption counters nor the xla backend's ``set_epoch`` prefetch
        buffer are touched.  ~46 ms for a 1e8-index epoch on the bench
        rig vs 51 s host-side (BASELINE.md)."""
        e = self.epoch if epoch is None else int(epoch)
        return expand_shard_indices_jax(
            self._epoch_indices(e, consume_prefetch=False), shard_sizes,
            seed=self.seed, epoch=e,
            within_shard_shuffle=within_shard_shuffle, rounds=self.rounds,
        )


def _within_shard_window(m: int, within_shard_shuffle: Union[bool, int]) -> int:
    """Resolve the within-shard shuffle option to a §3 window size.

    ``True`` -> the whole shard (window = m, a full in-shard permutation);
    an ``int`` -> that window (bounded displacement — the decompress-ahead
    distance a tar reader must buffer); ``False``/``0`` -> sequential.
    """
    if within_shard_shuffle is True:
        return m
    w = int(within_shard_shuffle)
    if w < 0:
        raise ValueError(
            f"within_shard_shuffle must be bool or >= 0, got {w}"
        )
    return min(w, m)


def shard_sample_order(
    sid: int,
    shard_size: int,
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> np.ndarray:
    """Within-shard sample order (local offsets [0, shard_size)) — SPEC.md §7.

    The §3 permutation at ``n = shard_size`` with the spec'd per-shard seed;
    vectorized (one numpy program per shard, no per-sample Python).
    """
    m = int(shard_size)
    if m <= 0:
        return np.empty(0, dtype=np.int64)
    w = _within_shard_window(m, within_shard_shuffle)
    if w <= 1:
        return np.arange(m, dtype=np.int64)
    # bounded mode keeps windows in place (order_windows=False) so every
    # sample moves strictly less than w from storage order — the §3 bound a
    # sequential tar reader's decompress-ahead buffer relies on
    return epoch_indices_np(
        m, w, shard_seed(seed, sid), epoch, 0, 1, rounds=rounds,
        order_windows=(within_shard_shuffle is True),
    ).astype(np.int64)


def _shard_epoch_keys(xp, sid_arr, seed: int):
    """Vectorized §1 fold of ``shard_seed(seed, sid)`` for a shard-id
    vector: ``(lo, hi)`` uint32 arrays — backend-generic (numpy or jnp).

    Folding commutes with XOR bit-for-bit, so
    ``fold(seed ^ K) == (fold_lo(seed) ^ K_lo, fold_hi(seed) ^ K_hi)`` with
    ``K = _SHARD_SEED_STRIDE + sid``.  The 64-bit add is carried in uint32
    halves (``sum_lo < sid`` detects the wrap) so the jnp path needs no
    x64 — bit-identical to ``core.fold_seed(shard_seed(seed, sid))`` per
    shard for any ``sid < 2**32``, asserted by the batch-vs-loop parity
    test."""
    lo0, hi0 = core.fold_seed(seed)  # int, (lo, hi) pair, or traced scalar
    stride_lo = _u32c(xp, _SHARD_SEED_STRIDE & 0xFFFFFFFF)
    stride_hi = _u32c(xp, (_SHARD_SEED_STRIDE >> 32) & 0xFFFFFFFF)
    sid_u = xp.asarray(sid_arr).astype(xp.uint32)
    sum_lo = stride_lo + sid_u  # wraps mod 2^32
    carry = (sum_lo < sid_u).astype(xp.uint32)
    lo = xp.asarray(lo0).astype(xp.uint32) ^ sum_lo
    hi = xp.asarray(hi0).astype(xp.uint32) ^ (stride_hi + carry)
    return lo, hi


def _u32c(xp, v: int):
    return xp.asarray(np.uint32(v))


def _batched_shard_orders(
    sid_arr,
    m: int,
    *,
    seed: int,
    epoch: int,
    within_shard_shuffle: Union[bool, int],
    rounds: int,
    xp=np,
) -> np.ndarray:
    """Within-shard orders for a whole SIZE CLASS at once: ``[S, m]`` from
    one vectorized §3 program (the swap-or-not rounds are elementwise, so
    per-shard keys broadcast as a ``[S, 1]`` column against the shared
    ``[1, m]`` position row).  Row ``i`` is bit-identical to
    ``shard_sample_order(sid_arr[i], m, ...)``.  Backend-generic: ``xp``
    is numpy (host) or jnp (the device expansion, where it is jitted)."""
    w = _within_shard_window(m, within_shard_shuffle)
    out_dtype = np.int64 if xp is np else xp.int32
    if w <= 1:
        return xp.broadcast_to(
            xp.arange(m, dtype=out_dtype), (len(sid_arr), m)
        )
    lo, hi = _shard_epoch_keys(xp, sid_arr, seed)
    ek = core.derive_epoch_key(xp, (lo[:, None], hi[:, None]), epoch)
    p = xp.arange(m, dtype=xp.uint32)[None, :]
    return core.windowed_perm(
        xp, p, m, w, ek,
        order_windows=(within_shard_shuffle is True), rounds=rounds,
    ).astype(out_dtype)


#: shards per batch block in the streaming expander — bounds transient
#: memory at block * max_shard_size while keeping the per-size-class
#: vectorization (WebDataset/C4 shard sizes are near-uniform, so a block
#: is typically one or two classes)
_EXPAND_BLOCK = 8192

#: element cap per batched §3 program: keeps each slab's uint32
#: intermediates cache-resident through the swap-or-not rounds (a 1e8-
#: element single slab measured 3x slower than 4M-element slabs)
_BATCH_ELEMS = 1 << 22


def _size_class_members(m_of: np.ndarray):
    """Yield ``(m, members)`` index arrays grouped by shard size, from ONE
    stable argsort — O(S log S) no matter how many distinct sizes there
    are (a per-class ``m_of == m`` scan would be O(S * classes), quadratic
    for variable-length document shards)."""
    order = np.argsort(m_of, kind="stable")
    uniq, starts = np.unique(m_of[order], return_index=True)
    bounds = np.append(starts, len(order))
    for i, m in enumerate(uniq):
        yield int(m), order[bounds[i]:bounds[i + 1]]


def _block_shard_arrays(sid_block, sizes, offsets, *, seed, epoch,
                        within_shard_shuffle, rounds):
    """Global index arrays for a block of shard ids, IN THE BLOCK'S ORDER,
    computed one size class at a time (batched)."""
    m_of = sizes[sid_block]
    out = [None] * len(sid_block)
    for m, members in _size_class_members(m_of):
        if m == 0:
            empty = np.empty(0, dtype=np.int64)
            for i in members:
                out[i] = empty
            continue
        orders = _batched_shard_orders(
            sid_block[members], m, seed=seed, epoch=epoch,
            within_shard_shuffle=within_shard_shuffle, rounds=rounds,
        )
        glob = offsets[sid_block[members]][:, None] + orders
        for row, i in enumerate(members):
            out[i] = glob[row]
    return out


def expand_shard_indices_np(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> np.ndarray:
    """Expand a rank's shard-id stream into global sample indices, vectorized
    ACROSS shards: shards are grouped by size and each size class is one
    batched §3 program (round 3 looped numpy per shard — 10^5+ calls per
    epoch at WebDataset scale, BASELINE.json configs[2-3]), scattered into a
    preallocated output instead of concatenated.  Cost is O(size classes)
    numpy programs; near-uniform shard sizes (the storage norm) make that
    O(1), and grouping is one stable argsort, so fully distinct sizes
    degrade gracefully to per-shard batches — never to a quadratic scan.
    100k near-uniform shards of 1k samples expand in well under a second
    (BASELINE.md).

    ``shard_sizes[i]`` is the sample count of shard ``i``; the sample index
    space is the concatenation of shards in id order.
    """
    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    sids = np.asarray(list(shard_ids), dtype=np.int64)
    if sids.size == 0:
        return np.empty(0, dtype=np.int64)
    m_of = sizes[sids]
    out_starts = np.concatenate([[0], np.cumsum(m_of)[:-1]])
    out = np.empty(int(m_of.sum()), dtype=np.int64)
    groups = list(_size_class_members(m_of))
    for m, members in groups:
        if m == 0:
            continue
        # slab-cap the batch: a 100k x 1000 single-class batch would walk
        # multi-GB intermediates through every swap-or-not round (measured
        # 3x slower than cache-sized slabs); uniform-size selections also
        # take the contiguous write path, skipping the scatter-index array
        contiguous = len(groups) == 1
        max_rows = max(1, _BATCH_ELEMS // m)
        for i0 in range(0, len(members), max_rows):
            sub = members[i0:i0 + max_rows]
            orders = _batched_shard_orders(
                sids[sub], m, seed=seed, epoch=epoch,
                within_shard_shuffle=within_shard_shuffle, rounds=rounds,
            )
            glob = offsets[sids[sub]][:, None] + orders
            if contiguous:
                lo = int(out_starts[sub[0]])
                out[lo:lo + glob.size] = glob.ravel()
            else:
                pos = (out_starts[sub][:, None]
                       + np.arange(m, dtype=np.int64))
                out[pos.ravel()] = glob.ravel()
    return out


@functools.lru_cache(maxsize=None)
def _class_expand_jit(m: int, full_shuffle: bool, w_int: int, rounds: int,
                      big: bool):
    """One jitted device program per (size class, static knobs): within-
    shard orders for the class plus the global offset add.  ``seed`` and
    ``epoch`` are traced uint32 scalars, so reseeds and new epochs reuse
    the executable.  The shuffle mode rides as TWO key fields
    (full_shuffle, w_int): ``True == 1`` hash-collides in a single field
    and lru_cache would silently serve the wrong program."""
    import jax
    import jax.numpy as jnp

    wss = True if full_shuffle else w_int  # w_int == 0 means sequential
    dtype = jnp.int64 if big else jnp.int32

    @jax.jit
    def f(sid_sub, off_sub, seed_lo, seed_hi, epoch_u32):
        orders = _batched_shard_orders(
            sid_sub, m, seed=(seed_lo, seed_hi), epoch=epoch_u32,
            within_shard_shuffle=wss, rounds=rounds, xp=jnp,
        )
        return off_sub.astype(dtype)[:, None] + orders.astype(dtype)

    return f


def expand_shard_indices_jax(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
):
    """Device-side expansion — same law, same order, same values as
    :func:`expand_shard_indices_np`, with each size class's batched §3
    program jitted on the accelerator and the result left in HBM for a
    JAX input pipeline.

    This is where the full in-shard shuffle stops being host-bound: at
    config-3/4 scale (100k shards x 1000 samples = 1e8 indices) the host
    expansion is permutation-bound at ~51 s/epoch (BASELINE.md) while the
    device runs the identical uint32 program in device-rate time, with
    the output resident in HBM.  Grouping by size class stays on the
    host (shard sizes are metadata); one jitted program per class size,
    reused across seeds and epochs (both traced).  Uniform sizes ship
    only shard ids + offsets; mixed sizes additionally ship one
    stream-order permutation per call and pay one device gather.
    Datasets with thousands of DISTINCT shard sizes compile one program
    per size (static shapes) — prefer the host expansion there.  Totals
    >= 2^31 need ``enable_big_index_space()``.
    """
    import jax.numpy as jnp

    from ..ops.xla import _require_x64_for_big_n

    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    sids = np.asarray(list(shard_ids), dtype=np.int64)
    total_space = int(sizes.sum())
    big = total_space > 0x7FFFFFFF
    if big:
        _require_x64_for_big_n(total_space)
    dtype = jnp.int64 if big else jnp.int32
    if sids.size == 0:
        return jnp.empty(0, dtype=dtype)
    m_of = sizes[sids]
    out_starts = np.concatenate([[0], np.cumsum(m_of)[:-1]])
    total = int(m_of.sum())
    seed_lo, seed_hi = core.fold_seed(int(seed))
    traced = (np.uint32(seed_lo), np.uint32(seed_hi),
              np.uint32(int(epoch) & 0xFFFFFFFF))
    groups = [(m, members) for m, members in _size_class_members(m_of)
              if m > 0]
    # normalize the shuffle mode exactly like _within_shard_window: `is
    # True` means full shuffle; anything else (False, int, np.integer) is
    # a window int — a bool() coercion here would turn np.int64(3) into a
    # full shuffle and silently diverge from the host path
    full = within_shard_shuffle is True
    w_int = 0 if full else int(within_shard_shuffle)
    off_dtype = np.int64 if big else np.int32  # avoid silent x64 downcasts

    def run_class(m, members):
        f = _class_expand_jit(m, full, w_int, int(rounds), big)
        return f(sids[members].astype(np.uint32),
                 offsets[sids[members]].astype(off_dtype), *traced)

    if len(groups) == 1 and groups[0][1].shape[0] == sids.size:
        # uniform sizes: one program, the reshape IS the stream order
        return run_class(*groups[0]).reshape(-1)
    # mixed sizes: concatenate per-class results on device, then ONE
    # gather through a host-built stream-order permutation (a per-class
    # scatter would copy the whole output buffer once per class)
    parts = [run_class(m, members).reshape(-1) for m, members in groups]
    cat = jnp.concatenate(parts) if parts else jnp.empty(0, dtype=dtype)
    # zero-size shards occupy no output width, so the nonzero groups tile
    # [0, total) exactly and the permutation below is total
    perm = np.empty(total, dtype=off_dtype)
    base = 0
    for m, members in groups:
        k = len(members)
        ar = np.arange(m, dtype=np.int64)
        stream_pos = (out_starts[members][:, None] + ar).ravel()
        cat_pos = (base + np.arange(k, dtype=np.int64)[:, None] * m
                   + ar).ravel()
        perm[stream_pos] = cat_pos
        base += k * m
    return cat[jnp.asarray(perm)]


def expand_shard_indices(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> Iterator[int]:
    """Generator form of :func:`expand_shard_indices_np` (same law, same
    order), for pipelines that want an index iterator.  Streams in blocks of
    ``_EXPAND_BLOCK`` shards — each block is expanded with the same
    per-size-class batching, then yielded shard by shard, so memory stays
    O(block) with no O(total) concatenation anywhere."""
    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    sids = np.asarray(list(shard_ids), dtype=np.int64)
    for start in range(0, len(sids), _EXPAND_BLOCK):
        block = sids[start:start + _EXPAND_BLOCK]
        for arr in _block_shard_arrays(
            block, sizes, offsets, seed=seed, epoch=epoch,
            within_shard_shuffle=within_shard_shuffle, rounds=rounds,
        ):
            yield from arr.tolist()


def shuffle_buffer(
    items: Iterable,
    buffer_size: int,
    *,
    seed: int = 0,
    epoch: int = 0,
) -> Iterator:
    """Deterministic bounded shuffle buffer (SPEC.md §7) — the WebDataset
    ``.shuffle(N)`` stage, reproducible from ``(seed, epoch)``.

    Maintains a buffer of ``buffer_size`` items; each step evicts the slot
    ``mix32(key ^ step) mod fill`` (key = the §1 epoch key xored with
    0x51ED270B then mixed) and refills from upstream.  Memory is O(buffer);
    an item can appear at most ``buffer_size - 1`` positions *early* (hard
    bound — it must enter the buffer first) and late with geometric tail;
    replaying the same ``(seed, epoch)`` over the same upstream order
    reproduces the stream exactly — which is what makes mid-epoch resume
    possible for sample streams whose shard expansion happens outside the
    index space (tar readers).
    """
    if buffer_size < 1:
        raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
    key = core.mix32(
        np, core.derive_epoch_key(np, seed, epoch) ^ np.uint32(0x51ED270B)
    )
    buf = []
    step = np.uint32(0)
    one = np.uint32(1)
    it = iter(items)
    for item in it:
        buf.append(item)
        if len(buf) < buffer_size:
            continue
        j = int(core.mix32(np, key ^ step) % np.uint32(len(buf)))
        step = step + one
        buf[j], buf[-1] = buf[-1], buf[j]
        yield buf.pop()
    while buf:
        j = int(core.mix32(np, key ^ step) % np.uint32(len(buf)))
        step = step + one
        buf[j], buf[-1] = buf[-1], buf[j]
        yield buf.pop()
