"""Shard-index mode: partial shuffle over *storage shards* (WebDataset/tar,
tokenized C4 shard files — the [B] configs 3-4).

At billion-sample scale the shuffle unit is often the shard file, not the
sample: shard order is permuted globally (windowed, for locality across a
storage prefix), samples inside a shard stream sequentially or through a
small in-memory shuffle buffer.  That is exactly the core law with
``n = num_shards`` (SURVEY.md §7 build order #7), so this module is a thin
vocabulary layer over the same spec — no second shuffle implementation.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

from ..ops import core
from ..ops.cpu import epoch_indices_np
from .torch_shim import PartiallyShuffleDistributedSampler


class PartialShuffleShardSampler(PartiallyShuffleDistributedSampler):
    """Yields shard ids for this rank, windowed-shuffled per epoch.

    Identical contract to the sample-level sampler; the ``window`` now
    bounds how far a shard moves from its stored order — keeping reads
    clustered within a storage prefix while still decorrelating epochs.
    """

    def __init__(self, num_shards: int, **kwargs) -> None:
        kwargs.setdefault("window", 64)
        super().__init__(int(num_shards), **kwargs)


def expand_shard_indices(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: bool = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> Iterator[int]:
    """Expand a rank's shard-id stream into global sample indices.

    ``shard_sizes[i]`` is the sample count of shard ``i``; sample index
    space is the concatenation of shards in id order.  Within a shard the
    samples are emitted in keyed-bijection order (window = whole shard) or
    sequentially — deterministic in (seed, epoch, shard), so resume can
    replay exactly.
    """
    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    for sid in shard_ids:
        m = int(sizes[sid])
        if m == 0:
            continue
        if within_shard_shuffle and m > 1:
            order = epoch_indices_np(
                m, m, seed ^ (0x9E3779B97F4A7C15 + sid), epoch, 0, 1,
                rounds=rounds,
            )
        else:
            order = range(m)
        base = int(offsets[sid])
        for o in order:
            yield base + int(o)
