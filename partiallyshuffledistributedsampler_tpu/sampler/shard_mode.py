"""Shard-index mode: partial shuffle over *storage shards* (WebDataset/tar,
tokenized C4 shard files — the [B] configs 3-4).

At billion-sample scale the shuffle unit is often the shard file, not the
sample: shard order is permuted globally (windowed, for locality across a
storage prefix), samples inside a shard stream sequentially or through a
windowed in-shard shuffle.  That is exactly the core law with
``n = num_shards`` (SURVEY.md §7 build order #7), so this module is a thin
vocabulary layer over the same spec — no second shuffle implementation.

The laws here are normative in SPEC.md §7: the per-shard seed derivation,
the within-shard order (the §3 permutation at ``n = shard_size``), and the
bounded shuffle-buffer stream are all spec'd and golden-tested, so shard
streams are checkpoint-stable across builds.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence, Union

import numpy as np

from ..ops import core
from ..ops.cpu import epoch_indices_np
from .torch_shim import PartiallyShuffleDistributedSampler

#: SPEC.md §7 per-shard seed stride (the 64-bit golden ratio, as used by
#: splitmix64): shard ``sid`` draws its within-shard permutation from
#: ``seed XOR (_SHARD_SEED_STRIDE + sid)`` folded per SPEC.md §1.
_SHARD_SEED_STRIDE = 0x9E3779B97F4A7C15


def shard_seed(seed: int, sid: int) -> int:
    """The spec'd per-shard seed (SPEC.md §7).  Pure; any change is a spec
    version bump — checkpointed shard streams depend on it."""
    return int(seed) ^ (_SHARD_SEED_STRIDE + int(sid))


class PartialShuffleShardSampler(PartiallyShuffleDistributedSampler):
    """Yields shard ids for this rank, windowed-shuffled per epoch.

    Identical contract to the sample-level sampler; the ``window`` now
    bounds how far a shard moves from its stored order — keeping reads
    clustered within a storage prefix while still decorrelating epochs.
    """

    def __init__(self, num_shards: int, **kwargs) -> None:
        kwargs.setdefault("window", 64)
        super().__init__(int(num_shards), **kwargs)


def _within_shard_window(m: int, within_shard_shuffle: Union[bool, int]) -> int:
    """Resolve the within-shard shuffle option to a §3 window size.

    ``True`` -> the whole shard (window = m, a full in-shard permutation);
    an ``int`` -> that window (bounded displacement — the decompress-ahead
    distance a tar reader must buffer); ``False``/``0`` -> sequential.
    """
    if within_shard_shuffle is True:
        return m
    w = int(within_shard_shuffle)
    if w < 0:
        raise ValueError(
            f"within_shard_shuffle must be bool or >= 0, got {w}"
        )
    return min(w, m)


def shard_sample_order(
    sid: int,
    shard_size: int,
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> np.ndarray:
    """Within-shard sample order (local offsets [0, shard_size)) — SPEC.md §7.

    The §3 permutation at ``n = shard_size`` with the spec'd per-shard seed;
    vectorized (one numpy program per shard, no per-sample Python).
    """
    m = int(shard_size)
    if m <= 0:
        return np.empty(0, dtype=np.int64)
    w = _within_shard_window(m, within_shard_shuffle)
    if w <= 1:
        return np.arange(m, dtype=np.int64)
    # bounded mode keeps windows in place (order_windows=False) so every
    # sample moves strictly less than w from storage order — the §3 bound a
    # sequential tar reader's decompress-ahead buffer relies on
    return epoch_indices_np(
        m, w, shard_seed(seed, sid), epoch, 0, 1, rounds=rounds,
        order_windows=(within_shard_shuffle is True),
    ).astype(np.int64)


def expand_shard_indices_np(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> np.ndarray:
    """Expand a rank's shard-id stream into global sample indices, vectorized.

    ``shard_sizes[i]`` is the sample count of shard ``i``; the sample index
    space is the concatenation of shards in id order.  One int64 array out —
    no per-sample Python on the hot path (the round-2 generator boxed every
    index through a Python int; at C4-scale shard sizes that re-created the
    epoch-boundary cost the chunked streaming work had just removed).
    """
    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    parts = []
    for sid in shard_ids:
        sid = int(sid)
        m = int(sizes[sid])
        if m == 0:
            continue
        parts.append(
            int(offsets[sid])
            + shard_sample_order(
                sid, m, seed=seed, epoch=epoch,
                within_shard_shuffle=within_shard_shuffle, rounds=rounds,
            )
        )
    if not parts:
        return np.empty(0, dtype=np.int64)
    return np.concatenate(parts)


def expand_shard_indices(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> Iterator[int]:
    """Generator form of :func:`expand_shard_indices_np` (same law, same
    order), for pipelines that want an index iterator.  Internally chunked
    per shard — yields from a vectorized array, never one numpy call per
    sample."""
    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    for sid in shard_ids:
        sid = int(sid)
        m = int(sizes[sid])
        if m == 0:
            continue
        order = shard_sample_order(
            sid, m, seed=seed, epoch=epoch,
            within_shard_shuffle=within_shard_shuffle, rounds=rounds,
        )
        yield from (int(offsets[sid]) + order).tolist()


def shuffle_buffer(
    items: Iterable,
    buffer_size: int,
    *,
    seed: int = 0,
    epoch: int = 0,
) -> Iterator:
    """Deterministic bounded shuffle buffer (SPEC.md §7) — the WebDataset
    ``.shuffle(N)`` stage, reproducible from ``(seed, epoch)``.

    Maintains a buffer of ``buffer_size`` items; each step evicts the slot
    ``mix32(key ^ step) mod fill`` (key = the §1 epoch key xored with
    0x51ED270B then mixed) and refills from upstream.  Memory is O(buffer);
    an item can appear at most ``buffer_size - 1`` positions *early* (hard
    bound — it must enter the buffer first) and late with geometric tail;
    replaying the same ``(seed, epoch)`` over the same upstream order
    reproduces the stream exactly — which is what makes mid-epoch resume
    possible for sample streams whose shard expansion happens outside the
    index space (tar readers).
    """
    if buffer_size < 1:
        raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
    key = core.mix32(
        np, core.derive_epoch_key(np, seed, epoch) ^ np.uint32(0x51ED270B)
    )
    buf = []
    step = np.uint32(0)
    one = np.uint32(1)
    it = iter(items)
    for item in it:
        buf.append(item)
        if len(buf) < buffer_size:
            continue
        j = int(core.mix32(np, key ^ step) % np.uint32(len(buf)))
        step = step + one
        buf[j], buf[-1] = buf[-1], buf[j]
        yield buf.pop()
    while buf:
        j = int(core.mix32(np, key ^ step) % np.uint32(len(buf)))
        step = step + one
        buf[j], buf[-1] = buf[-1], buf[j]
        yield buf.pop()
