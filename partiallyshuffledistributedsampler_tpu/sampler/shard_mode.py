"""Shard-index mode: partial shuffle over *storage shards* (WebDataset/tar,
tokenized C4 shard files — the [B] configs 3-4).

At billion-sample scale the shuffle unit is often the shard file, not the
sample: shard order is permuted globally (windowed, for locality across a
storage prefix), samples inside a shard stream sequentially or through a
windowed in-shard shuffle.  That is exactly the core law with
``n = num_shards`` (SURVEY.md §7 build order #7), so this module is a thin
vocabulary layer over the same spec — no second shuffle implementation.

The laws here are normative in SPEC.md §7: the per-shard seed derivation,
the within-shard order (the §3 permutation at ``n = shard_size``), and the
bounded shuffle-buffer stream are all spec'd and golden-tested, so shard
streams are checkpoint-stable across builds.
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, Optional, Sequence, Union

import numpy as np

from ..ops import core
from ..ops.cpu import epoch_indices_np
from .torch_shim import PartiallyShuffleDistributedSampler

#: SPEC.md §7 per-shard seed stride (the 64-bit golden ratio, as used by
#: splitmix64): shard ``sid`` draws its within-shard permutation from
#: ``seed XOR (_SHARD_SEED_STRIDE + sid)`` folded per SPEC.md §1.
_SHARD_SEED_STRIDE = 0x9E3779B97F4A7C15


def shard_seed(seed: int, sid: int) -> int:
    """The spec'd per-shard seed (SPEC.md §7).  Pure; any change is a spec
    version bump — checkpointed shard streams depend on it."""
    return int(seed) ^ (_SHARD_SEED_STRIDE + int(sid))


class PartialShuffleShardSampler(PartiallyShuffleDistributedSampler):
    """Yields shard ids for this rank, windowed-shuffled per epoch.

    Identical contract to the sample-level sampler; the ``window`` now
    bounds how far a shard moves from its stored order — keeping reads
    clustered within a storage prefix while still decorrelating epochs.
    """

    def __init__(self, num_shards: int, **kwargs) -> None:
        kwargs.setdefault("window", 64)
        super().__init__(int(num_shards), **kwargs)

    def device_epoch_indices(
        self,
        shard_sizes: Sequence[int],
        *,
        epoch: Optional[int] = None,
        within_shard_shuffle: Union[bool, int] = True,
    ):
        """This rank's expanded global sample indices for ``epoch``
        (default: current) as a DEVICE array in HBM — the JAX-native
        shard-mode epoch in one call: the rank's shard stream
        expanded through :func:`expand_shard_indices_jax` with this
        sampler's ``(seed, rounds)``.  Side-effect free: neither the
        consumption counters nor the xla backend's ``set_epoch`` prefetch
        buffer are touched.  ~46 ms for a 1e8-index epoch on the bench
        rig vs 51 s host-side (BASELINE.md)."""
        e = self.epoch if epoch is None else int(epoch)
        return expand_shard_indices_jax(
            self._epoch_indices(e, consume_prefetch=False), shard_sizes,
            seed=self.seed, epoch=e,
            within_shard_shuffle=within_shard_shuffle, rounds=self.rounds,
        )


def _within_shard_window(m: int, within_shard_shuffle: Union[bool, int]) -> int:
    """Resolve the within-shard shuffle option to a §3 window size.

    ``True`` -> the whole shard (window = m, a full in-shard permutation);
    an ``int`` -> that window (bounded displacement — the decompress-ahead
    distance a tar reader must buffer); ``False``/``0`` -> sequential.
    """
    if within_shard_shuffle is True:
        return m
    w = int(within_shard_shuffle)
    if w < 0:
        raise ValueError(
            f"within_shard_shuffle must be bool or >= 0, got {w}"
        )
    return min(w, m)


def shard_sample_order(
    sid: int,
    shard_size: int,
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> np.ndarray:
    """Within-shard sample order (local offsets [0, shard_size)) — SPEC.md §7.

    The §3 permutation at ``n = shard_size`` with the spec'd per-shard seed;
    vectorized (one numpy program per shard, no per-sample Python).
    """
    m = int(shard_size)
    if m <= 0:
        return np.empty(0, dtype=np.int64)
    w = _within_shard_window(m, within_shard_shuffle)
    if w <= 1:
        return np.arange(m, dtype=np.int64)
    # bounded mode keeps windows in place (order_windows=False) so every
    # sample moves strictly less than w from storage order — the §3 bound a
    # sequential tar reader's decompress-ahead buffer relies on
    return epoch_indices_np(
        m, w, shard_seed(seed, sid), epoch, 0, 1, rounds=rounds,
        order_windows=(within_shard_shuffle is True),
    ).astype(np.int64)


def _shard_epoch_keys(xp, sid_arr, seed: int):
    """Vectorized §1 fold of ``shard_seed(seed, sid)`` for a shard-id
    vector: ``(lo, hi)`` uint32 arrays — backend-generic (numpy or jnp).

    Folding commutes with XOR bit-for-bit, so
    ``fold(seed ^ K) == (fold_lo(seed) ^ K_lo, fold_hi(seed) ^ K_hi)`` with
    ``K = _SHARD_SEED_STRIDE + sid``.  The 64-bit add is carried in uint32
    halves (``sum_lo < sid`` detects the wrap) so the jnp path needs no
    x64 — bit-identical to ``core.fold_seed(shard_seed(seed, sid))`` per
    shard for any ``sid < 2**32``, asserted by the batch-vs-loop parity
    test."""
    lo0, hi0 = core.fold_seed(seed)  # int, (lo, hi) pair, or traced scalar
    stride_lo = _u32c(xp, _SHARD_SEED_STRIDE & 0xFFFFFFFF)
    stride_hi = _u32c(xp, (_SHARD_SEED_STRIDE >> 32) & 0xFFFFFFFF)
    sid_u = xp.asarray(sid_arr).astype(xp.uint32)
    sum_lo = stride_lo + sid_u  # wraps mod 2^32
    carry = (sum_lo < sid_u).astype(xp.uint32)
    lo = xp.asarray(lo0).astype(xp.uint32) ^ sum_lo
    hi = xp.asarray(hi0).astype(xp.uint32) ^ (stride_hi + carry)
    return lo, hi


def _u32c(xp, v: int):
    return xp.asarray(np.uint32(v))


def _batched_shard_orders(
    sid_arr,
    m: int,
    *,
    seed: int,
    epoch: int,
    within_shard_shuffle: Union[bool, int],
    rounds: int,
    xp=np,
) -> np.ndarray:
    """Within-shard orders for a whole SIZE CLASS at once: ``[S, m]`` from
    one vectorized §3 program (the swap-or-not rounds are elementwise, so
    per-shard keys broadcast as a ``[S, 1]`` column against the shared
    ``[1, m]`` position row).  Row ``i`` is bit-identical to
    ``shard_sample_order(sid_arr[i], m, ...)``.  Backend-generic: ``xp``
    is numpy (host) or jnp (the device expansion, where it is jitted)."""
    w = _within_shard_window(m, within_shard_shuffle)
    out_dtype = np.int64 if xp is np else xp.int32
    if w <= 1:
        return xp.broadcast_to(
            xp.arange(m, dtype=out_dtype), (len(sid_arr), m)
        )
    lo, hi = _shard_epoch_keys(xp, sid_arr, seed)
    ek = core.derive_epoch_key(xp, (lo[:, None], hi[:, None]), epoch)
    p = xp.arange(m, dtype=xp.uint32)[None, :]
    return core.windowed_perm(
        xp, p, m, w, ek,
        order_windows=(within_shard_shuffle is True), rounds=rounds,
    ).astype(out_dtype)


#: shards per batch block in the streaming expander — bounds transient
#: memory at block * max_shard_size while keeping the per-size-class
#: vectorization (WebDataset/C4 shard sizes are near-uniform, so a block
#: is typically one or two classes)
_EXPAND_BLOCK = 8192

#: element cap per batched §3 program: keeps each slab's uint32
#: intermediates cache-resident through the swap-or-not rounds (a 1e8-
#: element single slab measured 3x slower than 4M-element slabs)
_BATCH_ELEMS = 1 << 22


def _validate_sids(sids: np.ndarray, num_shards: int) -> None:
    """An out-of-range shard id would wrap through numpy's negative
    indexing into a DIFFERENT shard's expansion (and the native kernel
    refuses it) — fail identically on every backend instead."""
    if sids.size and (sids.min() < 0 or int(sids.max()) >= num_shards):
        raise ValueError(
            f"shard ids must be in [0, {num_shards}); got range "
            f"[{sids.min()}, {sids.max()}]"
        )


def _size_class_members(m_of: np.ndarray):
    """Yield ``(m, members)`` index arrays grouped by shard size, from ONE
    stable argsort — O(S log S) no matter how many distinct sizes there
    are (a per-class ``m_of == m`` scan would be O(S * classes), quadratic
    for variable-length document shards)."""
    order = np.argsort(m_of, kind="stable")
    uniq, starts = np.unique(m_of[order], return_index=True)
    bounds = np.append(starts, len(order))
    for i, m in enumerate(uniq):
        yield int(m), order[bounds[i]:bounds[i + 1]]


def _block_shard_arrays(sid_block, sizes, offsets, *, seed, epoch,
                        within_shard_shuffle, rounds):
    """Global index arrays for a block of shard ids, IN THE BLOCK'S ORDER,
    computed one size class at a time (batched)."""
    m_of = sizes[sid_block]
    out = [None] * len(sid_block)
    for m, members in _size_class_members(m_of):
        if m == 0:
            empty = np.empty(0, dtype=np.int64)
            for i in members:
                out[i] = empty
            continue
        orders = _batched_shard_orders(
            sid_block[members], m, seed=seed, epoch=epoch,
            within_shard_shuffle=within_shard_shuffle, rounds=rounds,
        )
        glob = offsets[sid_block[members]][:, None] + orders
        for row, i in enumerate(members):
            out[i] = glob[row]
    return out


def expand_shard_indices_np(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> np.ndarray:
    """Expand a rank's shard-id stream into global sample indices, vectorized
    ACROSS shards: shards are grouped by size and each size class is one
    batched §3 program (round 3 looped numpy per shard — 10^5+ calls per
    epoch at WebDataset scale, BASELINE.json configs[2-3]), scattered into a
    preallocated output instead of concatenated.  Cost is O(size classes)
    numpy programs; near-uniform shard sizes (the storage norm) make that
    O(1), and grouping is one stable argsort, so fully distinct sizes
    degrade gracefully to per-shard batches — never to a quadratic scan.
    100k near-uniform shards of 1k samples expand in well under a second
    (BASELINE.md).

    ``shard_sizes[i]`` is the sample count of shard ``i``; the sample index
    space is the concatenation of shards in id order.
    """
    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    sids = np.asarray(list(shard_ids), dtype=np.int64)
    _validate_sids(sids, len(sizes))
    if sids.size == 0:
        return np.empty(0, dtype=np.int64)
    m_of = sizes[sids]
    out_starts = np.concatenate([[0], np.cumsum(m_of)[:-1]])
    out = np.empty(int(m_of.sum()), dtype=np.int64)
    groups = list(_size_class_members(m_of))
    for m, members in groups:
        if m == 0:
            continue
        # slab-cap the batch: a 100k x 1000 single-class batch would walk
        # multi-GB intermediates through every swap-or-not round (measured
        # 3x slower than cache-sized slabs); uniform-size selections also
        # take the contiguous write path, skipping the scatter-index array
        contiguous = len(groups) == 1
        max_rows = max(1, _BATCH_ELEMS // m)
        for i0 in range(0, len(members), max_rows):
            sub = members[i0:i0 + max_rows]
            orders = _batched_shard_orders(
                sids[sub], m, seed=seed, epoch=epoch,
                within_shard_shuffle=within_shard_shuffle, rounds=rounds,
            )
            glob = offsets[sids[sub]][:, None] + orders
            if contiguous:
                lo = int(out_starts[sub[0]])
                out[lo:lo + glob.size] = glob.ravel()
            else:
                pos = (out_starts[sub][:, None]
                       + np.arange(m, dtype=np.int64))
                out[pos.ravel()] = glob.ravel()
    return out


#: distinct-size-class cap for the one-program-per-class device path;
#: beyond it (variable-length document corpora) shards bucket into
#: power-of-two padded widths — O(log(size range)) compiled programs
#: total instead of O(distinct sizes)
_MAX_CLASS_PROGRAMS = 16


def _rowwise_swap(xp, x, m_col, key, pair_col, rounds: int):
    """swap-or-not over ``[0, m_col)`` with a PER-ROW traced modulus:
    ``x`` is [R, m_b] lanes, ``m_col``/``pair_col`` are [R, 1] columns.
    The per-round pairing constant ``K_r = mix32(pair ^ r*GOLDEN) % m``
    is computed on the R-element column (one tiny division per row per
    round) and broadcasts — the per-lane work stays division-free, so a
    bucket of differently-sized shards rides one compiled program.
    Bit-identical per row to ``core.swap_or_not`` with that row's
    ``(m, pair_key)``; rows with ``m <= 1`` pass through (core's early
    return)."""
    key2 = core.mix32(xp, key ^ core._u32(xp, core._C_BIT))
    one = core._u32(xp, 1)
    m_ok = m_col > one
    for r in range(rounds):
        k_r = core.mix32(
            xp, pair_col ^ core._u32(xp, (r * core._GOLDEN) & core._M32)
        ) % xp.where(m_ok, m_col, one)
        partner = k_r + (m_col - x)
        partner = xp.where(partner >= m_col, partner - m_col, partner)
        c = xp.where(x > partner, x, partner)
        b = core.mix32(
            xp, c ^ key2 ^ core._u32(xp, (r * core._RC_BIT) & core._M32)
        )
        x = xp.where(((b & one) == one) & m_ok, partner, x)
    return x


@functools.lru_cache(maxsize=None)
def _bucket_scatter_jit(out_len: int, m_b: int, big: bool):
    """The (cheap to compile) scatter stage: padded bucket values [R, m_b]
    land in ONE shared accumulator at per-row traced start positions, pad
    lanes OOB-dropped.  Split from the bijection program deliberately:
    ``out_len`` tracks the rank's per-epoch total and changes between
    epochs — that must invalidate only this trivial program, never the
    24-round-unrolled bucket bijections.

    The accumulator is donated: every (bucket, slab) program writes its
    rows into the same exactly-``total``-long buffer in place.  The
    first cut instead had each slab scatter into a fresh zeroed
    next-pow2(total) buffer and summed them — O(slabs x pow2(total))
    dense device adds and a 2x padded live buffer per slab, all of it
    pure overhead since the slabs' target rows are disjoint by
    construction (ADVICE r5 #4).

    The scatter itself is the point of the design: a host-built
    stream-order permutation array is O(total) bytes shipped host→device
    per epoch — measured as the dominant cost of the first bucketed cut
    on the tunnel-attached bench device — while the per-row starts are
    O(rows)."""
    import jax
    import jax.numpy as jnp

    del big  # dtype rides in with the accumulator

    @functools.partial(jax.jit, donate_argnums=(0,))
    def f(acc, vals, n_sub, starts_sub):
        c = jnp.arange(m_b, dtype=starts_sub.dtype)[None, :]
        valid = jnp.arange(m_b, dtype=jnp.uint32)[None, :] \
            < n_sub.astype(jnp.uint32)[:, None]
        tgt = jnp.where(
            valid, starts_sub[:, None] + c,
            jnp.asarray(out_len, dtype=starts_sub.dtype),  # OOB -> dropped
        )
        return acc.at[tgt.reshape(-1)].set(vals.reshape(-1), mode="drop")

    return f


@functools.lru_cache(maxsize=None)
def _bucket_expand_jit(m_b: int, full_like: bool, w_int: int, rounds: int,
                       big: bool):
    """One jitted program per (power-of-two bucket width, mode): within-
    shard orders for R shards of VARYING sizes (``n_sub`` traced; 0
    marks padding rows), padded to ``m_b`` columns, plus the global
    offset add.  ``full_like`` serves both the full in-shard shuffle and
    bounded windows covering the shard (both are one inner bijection
    over [0, n)); the bounded mode (``w_int`` static) adds the windowed
    body + per-row tail.  The stream-order scatter is a separate program
    (``_bucket_scatter_jit``) so epoch-varying output lengths never
    recompile these."""
    import jax
    import jax.numpy as jnp

    dtype = jnp.int64 if big else jnp.int32

    @jax.jit
    def f(sid_sub, n_sub, off_sub, seed_lo, seed_hi, epoch_u32):
        lo, hi = _shard_epoch_keys(jnp, sid_sub, (seed_lo, seed_hi))
        ek = core.derive_epoch_key(
            jnp, (lo[:, None], hi[:, None]), epoch_u32
        )  # [R, 1]
        u = jnp.arange(m_b, dtype=jnp.uint32)[None, :]  # [1, m_b]
        n_raw = n_sub.astype(jnp.uint32)[:, None]       # [R, 1]; 0 = pad
        n_col = jnp.maximum(n_raw, jnp.uint32(1))
        u_c = jnp.minimum(u, n_col - jnp.uint32(1))     # pad lanes clipped
        u_c = jnp.broadcast_to(u_c, (n_col.shape[0], m_b))
        if full_like:
            # W = n: nw = 1, k = 0 -> one inner bijection over [0, n)
            kin = core.inner_key(jnp, ek, jnp.uint32(0))
            idx = _rowwise_swap(
                jnp, u_c, n_col, kin, core.inner_pair_key(jnp, ek), rounds
            )
        else:
            # bounded window w < n (order_windows=False: windows stay put)
            w = jnp.uint32(w_int)
            nw_col = n_col // w                       # >= 1 (w < n)
            body_col = nw_col * w
            win = jnp.minimum(u_c // w, nw_col - jnp.uint32(1))
            r0 = u_c % w
            kin = core.inner_key(jnp, ek, win)
            rho = core.swap_or_not(
                jnp, r0, w_int, kin, rounds,
                pair_key=core.inner_pair_key(jnp, ek),
            )
            body_idx = win * w + rho
            tail_col = n_col - body_col               # in [0, w)
            is_tail = u_c >= body_col
            tpos = jnp.where(is_tail, u_c - body_col, jnp.uint32(0))
            tpos = jnp.minimum(
                tpos, jnp.maximum(tail_col, jnp.uint32(1)) - jnp.uint32(1)
            )
            rho_t = _rowwise_swap(
                jnp, tpos, tail_col, core.tail_key(jnp, ek),
                core.tail_key(jnp, ek), rounds,
            )
            idx = jnp.where(is_tail, body_col + rho_t, body_idx)
        return off_sub.astype(dtype)[:, None] + idx.astype(dtype)

    return f


def _next_pow2(m: int) -> int:
    return 1 << (int(m) - 1).bit_length()


@functools.lru_cache(maxsize=None)
def _class_expand_jit(m: int, full_shuffle: bool, w_int: int, rounds: int,
                      big: bool):
    """One jitted device program per (size class, static knobs): within-
    shard orders for the class plus the global offset add.  ``seed`` and
    ``epoch`` are traced uint32 scalars, so reseeds and new epochs reuse
    the executable.  The shuffle mode rides as TWO key fields
    (full_shuffle, w_int): ``True == 1`` hash-collides in a single field
    and lru_cache would silently serve the wrong program."""
    import jax
    import jax.numpy as jnp

    wss = True if full_shuffle else w_int  # w_int == 0 means sequential
    dtype = jnp.int64 if big else jnp.int32

    @jax.jit
    def f(sid_sub, off_sub, seed_lo, seed_hi, epoch_u32):
        orders = _batched_shard_orders(
            sid_sub, m, seed=(seed_lo, seed_hi), epoch=epoch_u32,
            within_shard_shuffle=wss, rounds=rounds, xp=jnp,
        )
        return off_sub.astype(dtype)[:, None] + orders.astype(dtype)

    return f


def expand_shard_indices_jax(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
):
    """Device-side expansion — same law, same order, same values as
    :func:`expand_shard_indices_np`, with each size class's batched §3
    program jitted on the accelerator and the result left in HBM for a
    JAX input pipeline.

    This is where the full in-shard shuffle stops being host-bound: at
    config-3/4 scale (100k shards x 1000 samples = 1e8 indices) the host
    expansion is permutation-bound at ~51 s/epoch (BASELINE.md) while the
    device runs the identical uint32 program in device-rate time, with
    the output resident in HBM.  Grouping by size class stays on the
    host (shard sizes are metadata); one jitted program per class size,
    reused across seeds and epochs (both traced).  Host→device traffic
    is O(shards) in every mode — uniform sizes ship only shard ids +
    offsets, and mixed sizes scatter each class into one donated
    output accumulator at O(rows) stream starts (never an O(total)
    permutation ship).
    Datasets with MANY distinct shard sizes (a variable-length document
    corpus) do not compile one program per size: beyond
    ``_MAX_CLASS_PROGRAMS`` distinct sizes, shards bucket into
    power-of-two padded widths and each bucket runs one program with the
    per-shard size TRACED (``_bucket_expand_jit``) — O(log size-range)
    compiled programs total, ≤2x padded lanes, same values.  Totals
    >= 2^31 need ``enable_big_index_space()``.
    """
    import jax.numpy as jnp

    from ..ops.xla import _require_x64_for_big_n

    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    sids = np.asarray(list(shard_ids), dtype=np.int64)
    _validate_sids(sids, len(sizes))
    total_space = int(sizes.sum())
    big = total_space > 0x7FFFFFFF
    if big:
        _require_x64_for_big_n(total_space)
    dtype = jnp.int64 if big else jnp.int32
    if sids.size == 0:
        return jnp.empty(0, dtype=dtype)
    m_of = sizes[sids]
    out_starts = np.concatenate([[0], np.cumsum(m_of)[:-1]])
    total = int(m_of.sum())
    seed_lo, seed_hi = core.fold_seed(int(seed))
    traced = (np.uint32(seed_lo), np.uint32(seed_hi),
              np.uint32(int(epoch) & 0xFFFFFFFF))
    groups = [(m, members) for m, members in _size_class_members(m_of)
              if m > 0]
    # normalize the shuffle mode exactly like _within_shard_window: `is
    # True` means full shuffle; anything else (False, int, np.integer) is
    # a window int — a bool() coercion here would turn np.int64(3) into a
    # full shuffle and silently diverge from the host path
    full = within_shard_shuffle is True
    w_int = 0 if full else int(within_shard_shuffle)
    off_dtype = np.int64 if big else np.int32  # avoid silent x64 downcasts

    if len(groups) > _MAX_CLASS_PROGRAMS:
        return _expand_bucketed_jax(
            sids, m_of, offsets, out_starts, total, full, w_int,
            int(rounds), big, off_dtype, dtype, traced,
        )

    def run_class(m, members):
        f = _class_expand_jit(m, full, w_int, int(rounds), big)
        return f(sids[members].astype(np.uint32),
                 offsets[sids[members]].astype(off_dtype), *traced)

    if len(groups) == 1 and groups[0][1].shape[0] == sids.size:
        # uniform sizes: one program, the reshape IS the stream order
        return run_class(*groups[0]).reshape(-1)
    # mixed sizes: each class's [k, m] block scatters straight into ONE
    # donated, exactly-``total``-long accumulator at per-row stream
    # starts — zero-size shards occupy no output width, so the nonzero
    # classes' target rows tile [0, total) disjointly and the in-place
    # scatters compose with no cross-class adds.  The previous cut
    # concatenated the class results and gathered them through a
    # host-built stream-order permutation: an O(total) host build, an
    # O(total) host→device ship, and a full extra device copy per
    # epoch, all replaced by O(rows) start positions (the same donation
    # law as the bucketed path below).
    acc = jnp.zeros((total,), dtype)
    for m, members in groups:
        scat = _bucket_scatter_jit(total, m, big)
        acc = scat(acc, run_class(m, members),
                   np.full(len(members), m, np.uint32),
                   out_starts[members].astype(off_dtype))
    return acc


#: per-program lane budget for the bucketed device expansion (element
#: count of the padded [R, m_b] block) — sized for HBM, not host cache;
#: each program DISPATCH costs a fixed floor on a tunnel-attached device,
#: so the bucketed path must run few, large programs (50 host-cache-sized
#: slabs measured 70x the single-program uniform cost on the bench rig)
_DEVICE_SLAB_ELEMS = 1 << 28


def _expand_bucketed_jax(sids, m_of, offsets, out_starts, total, full,
                         w_int, rounds, big, off_dtype, dtype, traced):
    """The many-distinct-sizes device expansion: ONE traced-size program
    per shuffle-mode group (``_bucket_expand_jit``), every shard padded
    to the group's power-of-two width and the row count padded to a
    power of two — so the compiled shapes are stable across epochs even
    though the rank's shard draw changes — each program scattering its
    rows straight into ONE donated, exactly-``total``-long output buffer
    at per-row start positions (the slabs' target rows tile [0, total)
    disjointly, so in-place scatters compose with no cross-slab adds).
    Host→device traffic is O(rows), never O(total): the first cut
    shipped an O(total) stream-order permutation and measured 50x the
    uniform-size cost on the bench rig's tunnel."""
    import jax.numpy as jnp

    # a bounded window covering the shard is the same one-bijection
    # program as the full shuffle (nw == 1); sequential (w <= 1) rides
    # the bounded program at w=1, which is the identity per the §3 law
    w_eff = max(w_int, 1)
    nz = np.flatnonzero(m_of > 0)
    # bucket key = (mode, next_pow2(size)): per-size-class pow2 buckets
    # keep the padded-lane waste <= 2x for ANY size distribution (a
    # single group padded to the group max would be O(max/mean) waste on
    # a heavy-tailed corpus) while the program count stays O(log range)
    groups: dict = {}
    for i in nz:
        full_like = full or int(m_of[i]) <= w_eff
        groups.setdefault(
            (full_like, _next_pow2(int(m_of[i]))), []
        ).append(i)
    if not groups:
        return jnp.empty(0, dtype=dtype)
    out_len = int(total)
    acc = jnp.zeros((out_len,), dtype)
    for full_like, m_b in sorted(groups):
        members = np.asarray(groups[(full_like, m_b)])
        f = _bucket_expand_jit(
            m_b, full_like, 0 if full_like else w_eff, rounds, big
        )
        scat = _bucket_scatter_jit(out_len, m_b, big)
        max_rows = _next_pow2(max(1, _DEVICE_SLAB_ELEMS // m_b))
        for i0 in range(0, len(members), max_rows):
            slab = members[i0:i0 + max_rows]
            rows = _next_pow2(len(slab))  # stable shapes across epochs
            sid_in = np.zeros(rows, np.uint32)
            sid_in[:len(slab)] = sids[slab]
            n_in = np.zeros(rows, np.uint32)  # 0 marks padding rows
            n_in[:len(slab)] = m_of[slab]
            off_in = np.zeros(rows, off_dtype)
            off_in[:len(slab)] = offsets[sids[slab]]
            starts_in = np.zeros(rows, off_dtype)
            starts_in[:len(slab)] = out_starts[slab]
            acc = scat(acc, f(sid_in, n_in, off_in, *traced), n_in,
                       starts_in)
    return acc


def expand_shard_indices(
    shard_ids: Sequence[int],
    shard_sizes: Sequence[int],
    *,
    seed: int = 0,
    epoch: int = 0,
    within_shard_shuffle: Union[bool, int] = True,
    rounds: int = core.DEFAULT_ROUNDS,
) -> Iterator[int]:
    """Generator form of :func:`expand_shard_indices_np` (same law, same
    order), for pipelines that want an index iterator.  Streams in blocks of
    ``_EXPAND_BLOCK`` shards — each block is expanded with the same
    per-size-class batching, then yielded shard by shard, so memory stays
    O(block) with no O(total) concatenation anywhere."""
    sizes = np.asarray(shard_sizes, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    sids = np.asarray(list(shard_ids), dtype=np.int64)
    for start in range(0, len(sids), _EXPAND_BLOCK):
        block = sids[start:start + _EXPAND_BLOCK]
        for arr in _block_shard_arrays(
            block, sizes, offsets, seed=seed, epoch=epoch,
            within_shard_shuffle=within_shard_shuffle, rounds=rounds,
        ):
            yield from arr.tolist()


def shuffle_buffer(
    items: Iterable,
    buffer_size: int,
    *,
    seed: int = 0,
    epoch: int = 0,
) -> Iterator:
    """Deterministic bounded shuffle buffer (SPEC.md §7) — the WebDataset
    ``.shuffle(N)`` stage, reproducible from ``(seed, epoch)``.

    Maintains a buffer of ``buffer_size`` items; each step evicts the slot
    ``mix32(key ^ step) mod fill`` (key = the §1 epoch key xored with
    0x51ED270B then mixed) and refills from upstream.  Memory is O(buffer);
    an item can appear at most ``buffer_size - 1`` positions *early* (hard
    bound — it must enter the buffer first) and late with geometric tail;
    replaying the same ``(seed, epoch)`` over the same upstream order
    reproduces the stream exactly — which is what makes mid-epoch resume
    possible for sample streams whose shard expansion happens outside the
    index space (tar readers).
    """
    if buffer_size < 1:
        raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
    key = core.mix32(
        np, core.derive_epoch_key(np, seed, epoch) ^ np.uint32(0x51ED270B)
    )
    buf = []
    step = np.uint32(0)
    one = np.uint32(1)
    it = iter(items)
    for item in it:
        buf.append(item)
        if len(buf) < buffer_size:
            continue
        j = int(core.mix32(np, key ^ step) % np.uint32(len(buf)))
        step = step + one
        buf[j], buf[-1] = buf[-1], buf[j]
        yield buf.pop()
    while buf:
        j = int(core.mix32(np, key ^ step) % np.uint32(len(buf)))
        step = step + one
        buf[j], buf[-1] = buf[-1], buf[j]
        yield buf.pop()
