"""Shared index-serving service: one sampler daemon, many loader clients.

The local samplers make every trainer host regenerate the full windowed
permutation; this subsystem turns that into infrastructure (docs/SERVICE.md):
:class:`IndexServer` owns one :class:`PartialShuffleSpec` (plain, mixture,
or shard-mode), generates each epoch once through the existing backends,
and streams disjoint per-rank index ranges to N
:class:`ServiceIndexClient` s over loopback TCP — with backpressure,
rank leases, reconnect/resume, snapshots, metrics, and elastic
membership (mid-epoch resharding with preemption-aware drain,
docs/RESILIENCE.md "Elastic membership").  A primary/standby pair adds
hot-standby replication: WAL shipping, transparent client failover, and
split-brain fencing (docs/RESILIENCE.md "Replication & failover").  A
``multi_tenant=True`` daemon hosts several jobs at once — one namespace
per world-stripped spec fingerprint, with per-tenant quotas
(:class:`~..tenancy.TenantQuota`), fair-share regen scheduling
(:class:`~..tenancy.FairShareScheduler`), and isolated metrics/trace
views (docs/SERVICE.md "Tenancy").
"""

from ..tenancy import FairShareScheduler, TenantQuota  # noqa: F401
from .client import (  # noqa: F401
    FencedError,
    ReshardInProgress,
    ServiceError,
    ServiceIndexClient,
    ServiceUnavailable,
    SpecMismatchError,
)
from .metrics import ServiceMetrics  # noqa: F401
from .protocol import PROTOCOL_VERSION, ProtocolError  # noqa: F401
from .server import IndexServer  # noqa: F401
from .spec import PartialShuffleSpec  # noqa: F401
