"""Hot-standby replication: WAL shipping from a primary ``IndexServer``.

The primary appends every state-mutating transition to a sequenced
in-memory WAL (:class:`ReplicationLog`) and a background
:class:`ReplicationShipper` streams it to a standby ``IndexServer``
over the existing length-prefixed protocol (``REPL_SYNC`` /
``REPL_APPEND`` frames, docs/RESILIENCE.md "Replication & failover").

Design points:

* **Serving never blocks on the standby.**  ``append`` is an in-memory
  deque push under a lock; the shipper drains it asynchronously.  A
  slow, dead, or never-attached standby costs the primary nothing but
  the (bounded) log memory; the shipper reconnects with backoff and
  re-bootstraps (``REPL_SYNC`` carries the full snapshot-v2 state) when
  the tail it needs has been dropped.
* **Record vocabulary.**  Cheap high-frequency transitions ship as
  narrow records (``cursor`` upserts, ``lease`` grants/releases,
  ``epoch`` sets); the rare complex transitions — a reshard barrier's
  freeze→drain flip and its commit — ship the full state dict
  (``state`` records), so the standby applies them with the same code
  path a snapshot restore uses and cannot mis-replay a barrier.
  ``seal`` marks a primary snapshot write, letting a standby with its
  own ``snapshot_path`` persist at the same cadence.
* **Fencing terms.**  Every frame carries the primary's ``term``.  A
  promoted standby answers an old-term frame with
  ``ERROR(code='fenced')`` carrying the winning term — the zombie
  primary's shipper surfaces that through ``on_fenced`` and the server
  fences itself (every subsequent client write refused, docs/
  RESILIENCE.md "Split-brain fencing").
* **Fault sites.**  ``repl.append`` fires on every WAL append; an
  injected fault there degrades to a forced re-SYNC (counted as
  ``repl_append_errors``) — replication is an availability feature and
  must never take the serving path down.  ``repl.promote`` fires inside
  the standby's promotion (server.py) before any state flips.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import deque
from typing import Callable, Optional

from .. import faults as F
from .. import telemetry
from . import protocol as P
from ..analysis.lockorder import new_lock

#: how many WAL records the in-memory log retains; a standby that falls
#: further behind is re-bootstrapped via REPL_SYNC instead of replaying
LOG_TAIL = 4096

#: idle shipper tick: also the empty-append heartbeat cadence the standby
#: judges feed freshness by (repl_feed_timeout must comfortably exceed it)
SHIP_TICK_S = 0.2


class ReplicationLog:
    """Sequenced, bounded, thread-safe WAL of state transitions.

    Records are ``{"lsn": int, "op": str, **data}``; ``lsn`` is a dense
    1-based sequence.  ``append`` is the ``repl.append`` fault site: an
    injected failure marks the log for re-SYNC (the shipper re-ships the
    full state) rather than surfacing into the serving path.

    With a :class:`~..durability.WriteAheadLog` attached (``wal=``) the
    in-memory deque becomes a *view* over the disk log: every record is
    written through to the segments (under this log's lock — the WAL's
    own lock nests inside it), ``lsn`` resumes from ``wal.last_lsn``
    across restarts, and ``take()`` falls back to reading the segments
    when the deque has rotated past a slow standby's cursor, so a
    catch-up that used to force a full re-SYNC becomes a tail read."""

    def __init__(self, metrics=None, tail: int = LOG_TAIL,
                 wal=None) -> None:
        self._lock = new_lock("repl.log")
        self._cond = threading.Condition(self._lock)
        self._records: deque = deque(maxlen=max(1, int(tail)))  # guarded by: self._lock
        self.wal = wal
        self.lsn = wal.last_lsn if wal is not None else 0  # guarded by: self._lock — last appended
        self.resync_needed = False  # guarded by: self._lock
        self._urgent = False       # guarded by: self._lock — non-absorbing record pending
        self._metrics = metrics

    def append(self, op: str, data: dict) -> None:
        try:
            F.fire("repl.append")
        except F.InjectedThreadDeath:
            raise
        except Exception:
            # an append that failed mid-transition could leave the log
            # with a hole; the recovery is a full re-SYNC, never an
            # error on the serving path that caused the transition
            with self._cond:
                self.lsn += 1
                self.resync_needed = True
                self._urgent = True
                self._cond.notify_all()
            if self._metrics is not None:
                self._metrics.inc("repl_append_errors")
            return
        with self._cond:
            self.lsn += 1
            rec = {"lsn": self.lsn, "op": op, **data}
            self._records.append(rec)
            if self.wal is not None:
                # write-through: the WAL assigns noop fillers for any
                # lsn a previously-injected fault dropped, keeping the
                # on-disk sequence dense; a drop here degrades
                # durability observably, never the serving path
                self.wal.append(rec)
            # ``cursor`` upserts arrive once per served batch and are
            # absorbing (a newer one supersedes an older one for the
            # same rank), so they coalesce until the next ship tick
            # instead of waking the shipper into a per-batch round trip
            # — that synchronous chatter is what would otherwise make
            # replication visible in the serving path's wall clock
            if op != "cursor":
                self._urgent = True
                self._cond.notify_all()
        if self._metrics is not None:
            self._metrics.inc("repl_appends")

    def take(self, after_lsn: int, timeout: float = SHIP_TICK_S):
        """Records with ``lsn > after_lsn``, waiting up to ``timeout``
        unless a non-absorbing record is already pending.  Superseded
        ``cursor`` records (an older upsert for a rank that has a newer
        one in the same batch) are thinned out; the standby's applied
        cursor jumps over the thinned lsns, which its gap check allows
        because the batch's boundary lsns stay intact.  Returns
        ``(records, resync)``: ``resync`` True when the tail no longer
        reaches back to ``after_lsn + 1`` (or an append failed) and the
        shipper must re-bootstrap.  With a ``wal`` attached, a deque
        that rotated past the cursor first falls back to reading the
        catch-up tail from the disk segments (``repl_wal_reads``);
        only a tail the checkpoint GC already cut forces the re-SYNC."""
        with self._cond:
            if not self._urgent and not self.resync_needed:
                self._cond.wait(timeout)
            self._urgent = False
            if self.resync_needed:
                return [], True
            recs = [r for r in self._records if r["lsn"] > after_lsn]
            gap = ((bool(recs) and recs[0]["lsn"] != after_lsn + 1)
                   or (not recs and self.lsn > after_lsn))
        if gap:
            if self.wal is None:
                return [], True  # tail rotated past the standby's cursor
            # segment records are immutable once framed, so the read
            # runs outside the log lock and never blocks appends
            recs = self.wal.read_records(after_lsn=after_lsn)
            if not recs or recs[0]["lsn"] != after_lsn + 1:
                return [], True  # GC cut past the cursor: re-bootstrap
            if self._metrics is not None:
                self._metrics.inc("repl_wal_reads")
        # upserts coalesce per (tenant, rank): a multi-tenant primary
        # tags records with the owning tenant id, and two tenants'
        # rank-0 cursors must not thin each other
        newest_cursor = {
            (r.get("tenant"), r["rank"]): r["lsn"]
            for r in recs if r["op"] == "cursor"}
        return [r for r in recs
                if r["op"] != "cursor"
                or newest_cursor[(r.get("tenant"), r["rank"])] == r["lsn"]
                ], False

    def clear_resync(self) -> None:
        with self._cond:
            self.resync_needed = False


class TenantTaggedLog:
    """A tenant engine's view of the front daemon's shared WAL.

    Multi-tenant daemons (docs/SERVICE.md "Tenancy") keep ONE sequenced
    log; each tenant engine appends through this wrapper, which stamps
    the owning tenant id into every record so the standby can route it
    to its mirror of that tenant and ``take()`` can thin cursor upserts
    per ``(tenant, rank)``."""

    def __init__(self, log: ReplicationLog, tenant: str) -> None:
        self._log = log
        self.tenant = str(tenant)

    def append(self, op: str, data: dict) -> None:
        self._log.append(op, {**data, "tenant": self.tenant})

    @property
    def lsn(self) -> int:
        """The shared sequence's last lsn — a tenant engine's seal
        stamps it as the checkpoint watermark (``wal_lsn``)."""
        return self._log.lsn


class ReplicationShipper:
    """The primary's background thread streaming its WAL to the standby.

    ``state_fn`` produces the full snapshot-v2 state for bootstrap;
    ``term_fn`` the current fencing term (stamped into every frame);
    ``on_fenced(term)`` is called when the standby answers with a newer
    term — the server uses it to fence itself (it has been superseded).

    The class attributes name the wire fault site and the metric family,
    so the cross-cell :class:`~..federation.WalShipper` — the same loop
    pointed at a remote cell — observes under its own names without
    duplicating the ship/fence/resync machinery.
    """

    #: fault-injection site armed on every outbound frame (None = none);
    #: the federation shipper overrides with "cell.ship"
    SITE: Optional[str] = None
    M_SHIPPED = "repl_shipped"
    M_RESYNCS = "repl_resyncs"
    M_LAG_MS = "repl_lag_ms"

    def __init__(
        self,
        log: ReplicationLog,
        standby_address,
        *,
        state_fn: Callable[[], dict],
        term_fn: Callable[[], int],
        on_fenced: Callable[[int], None],
        metrics=None,
        timeout: float = 5.0,
    ) -> None:
        self.log = log
        self.standby_address = (str(standby_address[0]),
                                int(standby_address[1]))
        self._state_fn = state_fn
        self._term_fn = term_fn
        self._on_fenced = on_fenced
        self._metrics = metrics
        self.timeout = float(timeout)
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.shipped_lsn = 0     # standby-acked prefix
        self.synced = threading.Event()  # a SYNC has been acked at least once
        self._backoff = 0.05

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="psds-service-repl-ship")
        self._thread.start()

    def stop(self, join: bool = True) -> None:
        self._stop.set()
        self._close()
        t, self._thread = self._thread, None
        if t is not None and join:
            t.join(timeout=2.0)

    def _close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # ------------------------------------------------------------- the loop
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._sock is None:
                    self._connect_and_sync()
                    continue
                recs, resync = self.log.take(self.shipped_lsn)
                if self._stop.is_set():
                    return
                if resync:
                    self.log.clear_resync()
                    self._close()  # next tick reconnects and re-SYNCs
                    if self._metrics is not None:
                        self._metrics.inc(self.M_RESYNCS)
                    continue
                # an empty append doubles as the feed-freshness heartbeat
                self._ship(P.MSG_REPL_APPEND, {
                    "term": self._term_fn(),
                    "from_lsn": self.shipped_lsn + 1,
                    "records": recs,
                })
                if recs and self._metrics is not None:
                    self._metrics.inc(self.M_SHIPPED, value=len(recs))
            except _Fenced:
                return  # superseded: on_fenced already ran; stop shipping
            except (ConnectionError, socket.timeout, OSError,
                    P.ProtocolError):
                self._close()
                self._stop.wait(self._backoff)
                self._backoff = min(1.0, self._backoff * 2)

    def _connect_and_sync(self) -> None:
        sock = socket.create_connection(self.standby_address,
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self.timeout)
        self._sock = sock
        state = self._state_fn()
        # the bootstrap names the lsn the tail continues from: everything
        # the state dict already reflects is never re-shipped
        lsn = self.log.lsn
        self._ship(P.MSG_REPL_SYNC, {"term": self._term_fn(), "lsn": lsn,
                                     "state": state})
        self.shipped_lsn = lsn
        self.log.clear_resync()
        self._backoff = 0.05
        if self.synced.is_set() and self._metrics is not None:
            # any sync after the bootstrap is a RE-sync: a torn frame or
            # dropped link forced the full-state handshake again
            self._metrics.inc(self.M_RESYNCS)
        self.synced.set()
        telemetry.event("repl_sync", lsn=lsn)

    def _send_frame(self, msg_type: int, header: dict) -> None:
        """One framed send on the replication link.  Subclasses override
        to arm their own wire fault site (the `fault-sites` lint needs
        the site literal at the send)."""
        P.send_msg(self._sock, msg_type, header, site=self.SITE)

    def _ship(self, msg_type: int, header: dict) -> None:
        t0 = time.perf_counter()
        self._send_frame(msg_type, header)
        reply, rheader, _ = P.recv_msg(self._sock)
        if reply == P.MSG_ERROR:
            code = rheader.get("code")
            if code == "fenced":
                term = int(rheader.get("term", self._term_fn() + 1))
                telemetry.event("repl_fenced", term=term)
                try:
                    self._on_fenced(term)
                finally:
                    self._close()
                raise _Fenced(term)
            if code == "repl_gap":
                self._close()  # reconnect path re-SYNCs
                if self._metrics is not None:
                    self._metrics.inc(self.M_RESYNCS)
                return
            raise P.ProtocolError(
                f"standby refused {P.msg_name(msg_type)}: {code!r}")
        applied = rheader.get("applied_lsn")
        if applied is not None:
            self.shipped_lsn = max(self.shipped_lsn, int(applied))
        if self._metrics is not None:
            self._metrics.registry.histogram(self.M_LAG_MS).observe(
                (time.perf_counter() - t0) * 1e3)


class _Fenced(Exception):
    """Internal shipper signal: the standby promoted past our term."""

    def __init__(self, term: int) -> None:
        super().__init__(f"fenced at term {term}")
        self.term = int(term)
