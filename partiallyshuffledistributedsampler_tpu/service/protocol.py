"""Wire protocol of the index service: length-prefixed binary framing.

One frame on the wire is

    u32  length of everything after this field (big-endian)
    u8   message type (the MSG_* constants)
    u32  header length H (big-endian)
    H bytes of UTF-8 JSON header
    remaining bytes: raw payload (index batches ride here as native
                     numpy bytes; ``header["dtype"]`` names the layout)

The header carries the small structured fields (rank, epoch, seq, error
codes); the payload is reserved for bulk index data so a batch costs one
JSON parse of a ~100-byte header, never a JSON encode of the indices.
BATCH headers additionally carry ``crc32`` over the payload; a receiver
that sees a mismatch raises :class:`ChecksumError` and (being idempotent)
simply re-requests the same seq.

Versioning: ``HELLO`` carries ``proto=PROTOCOL_VERSION``; the peers
negotiate it explicitly — a mismatch draws a typed
``ERROR(code='protocol_version')`` carrying both version ints, so an old
client fails at the handshake with an actionable error instead of
undefined frame decoding mid-epoch.  Message types are stable small ints
— new types may be added within a version; unknown types draw an
``ERROR`` reply, not a closed connection.  Version 2 added the elastic
membership messages (``LEAVE``/``RESHARD``), generation-stamped
``GET_BATCH``, and the v2 snapshot schema (docs/SERVICE.md).

Request → reply pairs (client sends left, server answers right):

    HELLO      → WELCOME | ERROR     claim a rank (``rank=-1`` auto-claims)
    GET_BATCH  → BATCH | ERROR       one batch of the rank's epoch stream
    SET_EPOCH  → OK | ERROR          advance the served epoch
    SNAPSHOT   → SNAPSHOT_STATE      server state (restart/restore dict)
    HEARTBEAT  → OK                  keep the rank lease alive
    METRICS    → METRICS_REPORT      the daemon's counters/timers
    LEAVE      → OK | ERROR          preemption-notice drain: trigger a
                                     reshard to world-1 and drain out
    RESHARD    → OK | ERROR          explicit mid-epoch world change
    TRACE_DUMP → TRACE_REPORT        recent telemetry entries (the
                                     flight-recorder ring, bounded by
                                     ``limit``; docs/OBSERVABILITY.md)

Replication frames (docs/RESILIENCE.md "Replication & failover"; the
primary's shipper sends left, the standby answers right):

    REPL_SYNC    → OK | ERROR        bootstrap: term + lsn + the full
                                     snapshot-v2 state dict
    REPL_APPEND  → OK | ERROR        a run of sequenced WAL records
                                     (``ERROR(code='repl_gap')`` asks
                                     for a re-SYNC; ``fenced`` tells a
                                     zombie primary it was superseded)
    REPL_PROMOTE → OK | ERROR        promote the standby to primary
                                     (refused ``standby`` while its
                                     replication feed is still fresh,
                                     unless ``force`` is set)

Elastic error codes (docs/RESILIENCE.md "Elastic membership"):
``reshard`` (barrier in progress — retry shortly), ``resharded`` (the
request named a stale generation; the header carries the new
``generation``/``world``/``layers`` membership to adopt).

Replication error codes (docs/RESILIENCE.md "Replication & failover"):
``standby`` (this server is a hot standby; the header carries the
``primary`` address and the current ``term`` — data ops are refused
until a promotion), ``fenced`` (the request's fencing term lost: the
header carries the winning ``term`` and ``serving`` — True when THIS
server keeps serving at that term and the caller should adopt it and
retry, False when this server is a fenced zombie and the caller must
fail over), ``repl_gap`` (an append's ``from_lsn`` does not extend the
standby's applied prefix; the shipper re-SYNCs).

Tenancy fields and codes (docs/SERVICE.md "Tenancy"): ``HELLO`` MAY
carry the full wire ``spec`` alongside ``spec_fingerprint`` — a
multi-tenant daemon uses it to *create* the job's namespace on first
contact; a single-tenant daemon ignores it.  ``WELCOME`` carries the
assigned ``tenant`` id, and any request header MAY stamp ``tenant`` to
name its namespace explicitly (a reconnect that lost its HELLO binding).
Both ride inside protocol version 2 the same way ``trace`` does —
additive header fields, ignored by peers that predate them.  Error
codes: ``spec_mismatch`` (terminal — the fingerprints disagree and no
tenant can be attached; the header carries both ``server_fingerprint``
and ``client_fingerprint``, plus ``tenants``/``max_tenants`` when the
refusal was a capacity limit), ``tenant_admission`` (retryable — a
per-tenant quota refused the HELLO; the header carries ``retry_ms``).

Sharding fields and codes (docs/SHARDING.md): a ``ShardRouter``'s
``WELCOME`` carries ``router=true`` plus the deployment's ``shard_map``
(``{version, world, shards:[{id, ranks:[lo,hi), addr}], fingerprint}``)
and assigns no rank — the client direct-connects the owning shard; a
shard's ``WELCOME`` rides ``shard`` and the same ``shard_map``.  ``HELLO``
MAY carry ``attach=true`` to admit/create a tenant namespace WITHOUT
claiming a rank lease (answered ``OK`` with the ``tenant`` id).
``RESHARD`` MAY carry ``phase`` (``prepare`` | ``commit`` | ``abort``)
for the router's two-phase cross-shard barrier — ``commit`` imposes the
global ``barrier_units``, the post-barrier ``map``, and ``dead_ranks``
(sent only to the shard owning rank 0, which serves the orphan prefix).
All are additive header fields inside protocol version 2.  Error codes:
``wrong_shard`` (retryable — the dialed shard does not own the rank; the
header carries ``retry_ms``, ``owner`` and a fresh ``shard_map`` so the
client re-routes without a router round-trip), ``router_route`` (an
injected route fault; retryable), ``shard_barrier`` (a cross-shard
fan-out did not complete; retryable — barrier requests are idempotent).

Capability frames (docs/CAPABILITY.md — serve seeds, not indices):

    GET_CAPABILITY → CAPABILITY | ERROR   a signed epoch capability: the
                                          world-stripped spec fingerprint,
                                          epoch seed, membership generation
                                          + cascade ``layers``, tenant, and
                                          an HMAC over the canonical
                                          encoding.  The reply carries the
                                          current membership, the slot's
                                          server-side ``ack`` cursor (a
                                          takeover of a partly-served
                                          slot resumes regeneration at
                                          ``ack + 1``, never seq 0) and,
                                          when a drain barrier is already
                                          in flight for the rank, its
                                          ``target_samples`` clamp.

A capability-mode client sends only ``HEARTBEAT`` frames with the
``hb=[epoch, ack]`` piggyback while it regenerates indices on-device;
the ``OK`` reply MAY carry ``cap_drain={"epoch", "target_samples"}`` to
tell a batchless stream its drain clamp (an additive header field;
served-batch clients never see it).  Error codes: ``capability_stale``
(retryable — the request named a revoked generation; the header carries
a fresh ``capability`` plus the new membership to adopt),
``capability_issue`` (retryable — an injected/transient issuance fault),
``capability_unsupported`` (terminal — the daemon has no signing secret
configured; use the served-batch path).  Both frame types are additive
within protocol version 2: a deployment that never requests a
capability puts zero extra bytes on the wire.

Streaming frames (docs/STREAMING.md — epochless moving-horizon shuffle):

    APPEND → OK | ERROR              a feeder extends the append-only
                                     index space by ``count`` samples;
                                     idempotent under retry via the
                                     monotonic ``stream_seq`` per
                                     ``feeder`` id, MAY carry an
                                     additive per-source
                                     ``weights_delta`` folded into the
                                     mixture weights at the next
                                     horizon advance.  The ``OK`` reply
                                     carries ``appended``, ``eligible``
                                     (fully-appended horizons) and the
                                     stream's current horizon ``epoch``.

On a stream-mode spec the epoch number of ``GET_BATCH`` /
``GET_CAPABILITY`` *is* the horizon generation; the server gates it with
typed retryable refusals: ``horizon_pending`` (the horizon is not fully
appended yet — the header carries ``appended``/``eligible`` and
``retry_ms``), ``horizon_advance`` (the ack-gated advance barrier is
waiting on straggler ranks, or an injected ``stream.advance`` fault
aborted the advance before any state moved — retry and the barrier
resolves), ``stream_append`` (an injected/transient ``stream.append``
fault refused the APPEND; retryable — the ``stream_seq`` makes the
retry exact-once).  All are additive within protocol version 2: a
frozen-dataset deployment never sees them.

Tracing: any request header MAY carry ``trace=[trace_id, span_id]`` —
the sender's open span context (docs/OBSERVABILITY.md).  Receivers that
know about it parent their dispatch span under it; receivers that don't
ignore it like any unknown header field, so the field rides inside
protocol version 2 without a bump.  A disabled tracer never adds the
field, so tracing-off peers put zero extra bytes on the wire.
"""

from __future__ import annotations

import json
import socket
import struct
import zlib

import numpy as np

from .. import faults as F

#: bump on any framing/semantics change; HELLO negotiates it.
#: v2: LEAVE/RESHARD messages, generation-stamped GET_BATCH, snapshot v2.
#: Additive-within-v2 (no bump needed): TRACE_DUMP/TRACE_REPORT message
#: types and the optional ``trace`` request-header field.
PROTOCOL_VERSION = 2

#: frames above this are a protocol violation (a corrupt length prefix
#: must not make the reader try to allocate gigabytes)
MAX_FRAME = 1 << 26  # 64 MiB

MSG_HELLO = 1
MSG_WELCOME = 2
MSG_GET_BATCH = 3
MSG_BATCH = 4
MSG_SET_EPOCH = 5
MSG_SNAPSHOT = 6
MSG_SNAPSHOT_STATE = 7
MSG_HEARTBEAT = 8
MSG_OK = 9
MSG_ERROR = 10
MSG_METRICS = 11
MSG_METRICS_REPORT = 12
MSG_LEAVE = 13
MSG_RESHARD = 14
MSG_TRACE_DUMP = 15
MSG_TRACE_REPORT = 16
# additive-within-v2 (like TRACE_DUMP): hot-standby replication frames
MSG_REPL_SYNC = 17
MSG_REPL_APPEND = 18
MSG_REPL_PROMOTE = 19
# additive-within-v2: signed epoch capabilities (docs/CAPABILITY.md) —
# a client that never sends GET_CAPABILITY pays zero protocol overhead
MSG_GET_CAPABILITY = 20
MSG_CAPABILITY = 21
# additive-within-v2: the moving-horizon stream's feeder frame
# (docs/STREAMING.md) — a frozen-dataset deployment never sends it
MSG_APPEND = 22

_NAMES = {
    v: k[len("MSG_"):] for k, v in list(globals().items())
    if k.startswith("MSG_")
}


def msg_name(msg_type: int) -> str:
    return _NAMES.get(msg_type, f"UNKNOWN({msg_type})")


class ProtocolError(RuntimeError):
    """Malformed frame or out-of-contract message sequence."""


class ChecksumError(ProtocolError):
    """BATCH payload failed its CRC32 — the frame arrived torn/corrupted.

    Unlike other protocol errors this one is *recoverable by re-request*
    (the server's reply is a pure function of ``(epoch, seq)``), so the
    client rejects the batch and asks for the same seq again instead of
    tearing the connection down."""


def pack(msg_type: int, header: dict, payload: bytes = b"") -> bytes:
    h = json.dumps(header, separators=(",", ":")).encode()
    body_len = 1 + 4 + len(h) + len(payload)
    if body_len > MAX_FRAME:
        raise ProtocolError(f"frame of {body_len} bytes exceeds {MAX_FRAME}")
    return struct.pack("!IBI", body_len, msg_type, len(h)) + h + payload


def send_msg(sock: socket.socket, msg_type: int, header: dict,
             payload: bytes = b"", *, site: str = None) -> None:
    """Frame and send one message.  ``site`` names a fault-injection
    point (docs/RESILIENCE.md): under an armed plan the framed bytes may
    be delayed, torn mid-frame, corrupted, or replaced by a reset."""
    frame = pack(msg_type, header, payload)
    if site is not None:
        rule = F.draw(site)
        if rule is not None:
            frame = F.apply_to_frame(rule, sock, frame)
    sock.sendall(frame)


def send_msgs(sock: socket.socket, msgs, *, site: str = None) -> None:
    """Frame several messages and send them as ONE coalesced buffer
    (writev-style) — one syscall instead of one per request, which is
    what lets the pipelined client top up its lookahead window without
    multiplying per-step wire ops.

    ``msgs`` is an iterable of ``(msg_type, header)`` or ``(msg_type,
    header, payload)`` tuples, each packed exactly as :func:`send_msg`
    packs it, so the receiver cannot tell coalesced frames from
    individual sends.  A single fault draw applies to the *combined*
    buffer: a ``torn_frame``/``reset`` rule tears mid-stream across
    message boundaries — exactly the failure a pipelined sender must
    survive with its acks intact.
    """
    parts = []
    for m in msgs:
        payload = m[2] if len(m) > 2 else b""
        parts.append(pack(m[0], m[1], payload))
    frame = b"".join(parts)
    if site is not None:
        rule = F.draw(site)
        if rule is not None:
            frame = F.apply_to_frame(rule, sock, frame)
    sock.sendall(frame)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame"
                                  if buf or n else "peer closed")
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket, *, site: str = None):
    """Read one frame → ``(msg_type, header, payload)``.

    Raises ``ConnectionError`` on a clean or mid-frame close (the retry
    layer's signal to reconnect) and :class:`ProtocolError` on a frame
    that cannot be parsed (never retried — the peer is broken).  ``site``
    names a fault-injection point: reset/delay fire before the read,
    ``corrupt`` flips a byte of the received payload (which the CRC32
    check in :func:`decode_indices` must then catch)."""
    rule = F.draw(site) if site is not None else None
    if rule is not None and rule.kind != "corrupt":
        F.perform(rule)
    (body_len,) = struct.unpack("!I", _recv_exact(sock, 4))
    if not 5 <= body_len <= MAX_FRAME:
        raise ProtocolError(f"frame length {body_len} outside [5, {MAX_FRAME}]")
    body = _recv_exact(sock, body_len)
    msg_type, hlen = struct.unpack("!BI", body[:5])
    if hlen > body_len - 5:
        raise ProtocolError(f"header length {hlen} overruns frame {body_len}")
    try:
        header = json.loads(body[5:5 + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise ProtocolError(f"unparseable header: {exc}") from None
    if not isinstance(header, dict):
        raise ProtocolError(f"header must be a JSON object, got "
                            f"{type(header).__name__}")
    payload = body[5 + hlen:]
    if rule is not None and rule.kind == "corrupt":
        payload = F.flip_byte(payload)
    return msg_type, header, payload


# ------------------------------------------------------- index batch codec
def encode_indices(arr: np.ndarray):
    """``(header_fields, payload)`` for an index batch: raw bytes plus the
    dtype string (with byte order) the receiver rebuilds from, and a
    CRC32 of the payload so a torn/corrupted frame that survives framing
    cannot become silently wrong indices."""
    a = np.ascontiguousarray(arr)
    payload = a.tobytes()
    return {"dtype": a.dtype.str, "count": int(a.shape[0]),
            "crc32": zlib.crc32(payload) & 0xFFFFFFFF}, payload


def decode_indices(header: dict, payload: bytes) -> np.ndarray:
    try:
        dtype = np.dtype(header["dtype"])
    except (KeyError, TypeError, ValueError) as exc:
        raise ProtocolError(f"bad BATCH dtype: {exc}") from None
    count = int(header.get("count", -1))
    if dtype.itemsize * max(count, 0) != len(payload):
        raise ProtocolError(
            f"BATCH payload is {len(payload)} bytes; header promises "
            f"{count} x {dtype}"
        )
    crc = header.get("crc32")
    if crc is not None and (zlib.crc32(payload) & 0xFFFFFFFF) != int(crc):
        # absent crc32 is tolerated (pre-checksum peers within the same
        # protocol version); a PRESENT mismatch is a corrupted payload
        raise ChecksumError(
            f"BATCH payload crc32 mismatch (header {int(crc)}); "
            "rejecting the corrupted frame"
        )
    arr = np.frombuffer(payload, dtype=dtype)
    arr.setflags(write=False)  # frombuffer views are read-only anyway
    return arr
