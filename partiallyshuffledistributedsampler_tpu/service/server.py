"""The index-serving daemon: one process owns epoch state, N clients stream.

``IndexServer`` owns exactly one :class:`~.spec.PartialShuffleSpec` and
serves its per-rank epoch streams over loopback TCP (the :mod:`.protocol`
framing).  Design points, in the order they matter:

* **One generation per (epoch, rank).**  A rank's stream is generated
  once via the spec's backend (cpu/native/xla), cached read-only, and
  every (re)connected client of that rank replays from the cache — the
  redundant per-host regen the local samplers do N times collapses to
  one, and the regen latency is timed into ``epoch_regen_ms``.
* **Client-driven cursors → exactly-once.**  ``GET_BATCH`` names an
  explicit ``(epoch, seq)``; the server is a pure function of that name
  plus the spec, so a client that reconnects after a server restart and
  re-requests its cursor gets bit-identical bytes (counted as a
  ``resend`` when the seq was already served).
* **Backpressure.**  A rank may run at most ``max_inflight`` batches
  past its acked cursor; beyond that ``GET_BATCH`` draws an
  ``ERROR(code='throttle', retry_ms=...)`` instead of queueing unbounded
  frames into a slow consumer's socket.
* **Leases, not registrations.**  A rank is leased to one connection;
  the lease expires after ``heartbeat_timeout`` seconds of silence
  (evicted lazily on claim *and* by the accept-loop sweep, which also
  closes the idle socket).  A dropped connection releases its lease
  immediately, so crash-reconnect never waits out the timeout.
* **Snapshots.**  Server state — spec wire form, current epoch, per-rank
  cursors — persists through ``utils/checkpoint``'s atomic-json helpers
  to ``snapshot_path`` (on SET_EPOCH, lease changes, every
  ``snapshot_interval`` batches, and at ``stop()``); a restarted server
  resumes from it.  Correctness does not depend on the snapshot (streams
  are pure), it restores the *operational* state: the served epoch and
  where each client was.
"""

from __future__ import annotations

import os
import socket
import threading
import time
import warnings
from collections import OrderedDict
from typing import Optional

from .. import faults as F
from ..utils.checkpoint import load_sampler_state, save_sampler_state
from . import protocol as P
from .metrics import ServiceMetrics
from .spec import PartialShuffleSpec

SNAPSHOT_KIND = "index_service"


class IndexServer:
    """Threaded loopback daemon serving one spec's index streams.

        spec = PartialShuffleSpec.plain(n, window=8192, world=4)
        with IndexServer(spec, port=0) as srv:   # ephemeral port
            addr = srv.address                   # (host, port)
            ...

    One thread accepts, one thread per connection serves; all daemonic.
    ``max_inflight`` bounds un-acked batches per rank; ``heartbeat_timeout``
    bounds how long a silent connection holds its rank lease."""

    def __init__(
        self,
        spec: PartialShuffleSpec,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_inflight: int = 8,
        heartbeat_timeout: float = 30.0,
        snapshot_path: Optional[str] = None,
        snapshot_interval: int = 64,
        max_cached_arrays: Optional[int] = None,
        metrics: Optional[ServiceMetrics] = None,
        clock=time.monotonic,
    ) -> None:
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        self.spec = spec
        self.host, self.port = host, int(port)
        self.max_inflight = int(max_inflight)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.snapshot_path = snapshot_path
        self.snapshot_interval = max(1, int(snapshot_interval))
        #: lease time source — injectable so eviction timing is testable
        #: against a fake clock (real deployments never override it)
        self._clock = clock
        # current epoch + one behind: a client finishing epoch e while
        # another already moved to e+1 must not thrash regeneration
        self._max_cached = (
            2 * spec.world if max_cached_arrays is None
            else max(1, int(max_cached_arrays))
        )
        self.metrics = metrics if metrics is not None else ServiceMetrics()
        self.epoch = 0
        self._lock = threading.Lock()          # leases / cursors / epoch
        self._gen_lock = threading.Lock()      # the (epoch, rank) cache
        self._cache: OrderedDict[tuple, object] = OrderedDict()
        #: rank -> {"owner": conn_id|None, "last_seen": t, "batch": int}
        self._leases: dict[int, dict] = {}
        #: rank -> {"epoch": e, "acked": int, "hi": int} (hi = highest
        #: seq ever served; a request at or below it is a resend)
        self._cursors: dict[int, dict] = {}
        self._listener: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._conn_socks: dict[int, socket.socket] = {}
        self._next_conn_id = 0
        self._unsnapshotted = 0
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._snapshot_error_warned = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> tuple[str, int]:
        """Bind, restore any snapshot, and begin accepting.  Returns the
        bound ``(host, port)`` — pass ``port=0`` for an ephemeral port."""
        if self._listener is not None:
            raise RuntimeError("server already started")
        self._stop.clear()
        self._draining.clear()
        if self.snapshot_path and os.path.exists(self.snapshot_path):
            self._restore(load_sampler_state(self.snapshot_path))
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.host, self.port))
        ls.listen(128)
        ls.settimeout(0.2)  # the accept loop doubles as the lease sweeper
        self.host, self.port = ls.getsockname()[:2]
        self._listener = ls
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="psds-service-accept")
        t.start()
        self._threads.append(t)
        return self.host, self.port

    @property
    def address(self) -> tuple[str, int]:
        return self.host, self.port

    def stop(self, drain_s: float = 0.05) -> None:
        """Graceful shutdown: drain, drop every connection, persist a
        snapshot.

        Drain phase: accepting stops and, for ``drain_s`` seconds,
        requests still arriving on live connections are answered
        ``ERROR(code='draining', retry_ms=...)`` — a structured "come
        back shortly" the retry layer sleeps on, instead of a raw reset
        racing the last reply.  Then every connection socket is shut down
        and closed *before* the serve threads are joined, so a thread
        blocked in ``recv`` wakes immediately and the join cannot leak
        threads; any survivor past the join timeout is counted
        (``leaked_threads``) and warned about rather than silently
        abandoned."""
        self._draining.set()
        ls, self._listener = self._listener, None
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        if drain_s > 0 and not self._stop.is_set():
            time.sleep(drain_s)
        self._stop.set()
        with self._lock:
            socks = list(self._conn_socks.values())
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        leaked = [t for t in self._threads if t.is_alive()]
        if leaked:
            self.metrics.inc("leaked_threads", value=len(leaked))
            warnings.warn(
                f"IndexServer.stop(): {len(leaked)} serve thread(s) "
                f"survived the join timeout: "
                f"{[t.name for t in leaked]}", RuntimeWarning,
            )
        self._threads.clear()
        self._write_snapshot(force=True)

    def __enter__(self) -> "IndexServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- snapshot
    def _state_dict(self) -> dict:
        with self._lock:
            return {
                "kind": SNAPSHOT_KIND,
                "proto": P.PROTOCOL_VERSION,
                "spec": self.spec.to_wire(),
                "epoch": self.epoch,
                "cursors": {
                    str(r): dict(c) for r, c in self._cursors.items()
                },
            }

    def _restore(self, state: dict) -> None:
        if state.get("kind") != SNAPSHOT_KIND:
            raise ValueError(
                f"snapshot kind {state.get('kind')!r} is not a "
                f"{SNAPSHOT_KIND!r} snapshot"
            )
        theirs = PartialShuffleSpec.from_wire(state["spec"],
                                              backend=self.spec.backend)
        if theirs.fingerprint() != self.spec.fingerprint():
            raise ValueError(
                "snapshot was written by a server with a different stream "
                f"spec: {theirs.fingerprint()} != {self.spec.fingerprint()}; "
                "serving it would hand clients a different permutation"
            )
        with self._lock:
            self.epoch = int(state.get("epoch", 0))
            self._cursors = {
                int(r): {"epoch": int(c["epoch"]), "acked": int(c["acked"]),
                         "hi": int(c["hi"])}
                for r, c in state.get("cursors", {}).items()
            }

    def _write_snapshot(self, force: bool = False) -> None:
        if not self.snapshot_path:
            return
        with self._lock:
            self._unsnapshotted += 1
            if not force and self._unsnapshotted < self.snapshot_interval:
                return
            self._unsnapshotted = 0
        state = self._state_dict()
        try:
            F.fire("server.snapshot_write")
            save_sampler_state(self.snapshot_path, state)
        except OSError as exc:
            # The snapshot is operational state, never a correctness
            # dependency (streams are pure functions of the spec) — a
            # full/unwritable disk must degrade observably, not take the
            # serving path down with it.
            self.metrics.inc("snapshot_errors")
            if not self._snapshot_error_warned:
                self._snapshot_error_warned = True
                warnings.warn(
                    f"IndexServer: snapshot write to "
                    f"{self.snapshot_path!r} failed ({exc!r}); serving "
                    "continues without persistence", RuntimeWarning,
                )

    # ------------------------------------------------------------ the cache
    def _rank_array(self, epoch: int, rank: int):
        key = (int(epoch), int(rank))
        with self._gen_lock:
            arr = self._cache.get(key)
            if arr is not None:
                self._cache.move_to_end(key)
                return arr
            with self.metrics.regen_timer.measure():
                arr = self.spec.rank_indices(epoch, rank)
            arr.setflags(write=False)
            self._cache[key] = arr
            while len(self._cache) > self._max_cached:
                self._cache.popitem(last=False)
            return arr

    # --------------------------------------------------------------- accept
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            ls = self._listener
            if ls is None:
                return
            try:
                sock, _addr = ls.accept()
            except socket.timeout:
                self._sweep_leases()
                continue
            except OSError:
                return  # listener closed by stop()
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                conn_id = self._next_conn_id
                self._next_conn_id += 1
                self._conn_socks[conn_id] = sock
            t = threading.Thread(
                target=self._serve_conn, args=(sock, conn_id), daemon=True,
                name=f"psds-service-conn-{conn_id}",
            )
            t.start()
            # prune finished serve threads while appending: a long-lived
            # daemon churning reconnects must not accumulate dead Thread
            # objects (and stop() must not re-join them)
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _sweep_leases(self) -> None:
        """Evict ranks whose connection went silent past the lease timeout
        and close their sockets (frees the rank AND unblocks the reader)."""
        now = self._clock()
        to_close = []
        with self._lock:
            for rank, lease in self._leases.items():
                owner = lease.get("owner")
                if owner is None:
                    continue
                if now - lease["last_seen"] > self.heartbeat_timeout:
                    lease["owner"] = None
                    self.metrics.inc("evictions", rank)
                    sock = self._conn_socks.get(owner)
                    if sock is not None:
                        to_close.append(sock)
        for sock in to_close:
            try:
                sock.close()
            except OSError:
                pass

    # ------------------------------------------------------- per-connection
    def _serve_conn(self, sock: socket.socket, conn_id: int) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg, header, payload = P.recv_msg(sock)
                except P.ProtocolError as exc:
                    # best-effort complaint, then drop the broken peer
                    try:
                        P.send_msg(sock, P.MSG_ERROR,
                                   {"code": "protocol", "detail": str(exc)})
                    except OSError:
                        pass
                    return
                try:
                    F.fire("server.dispatch")
                    self._dispatch(sock, conn_id, msg, header, payload)
                except OSError:
                    return  # peer vanished mid-reply
        except (ConnectionError, OSError):
            return
        except F.InjectedThreadDeath:
            return  # injected serve-thread death; cleanup below still runs
        finally:
            self._release_conn(conn_id)
            try:
                sock.close()
            except OSError:
                pass

    def _release_conn(self, conn_id: int) -> None:
        """A closed connection releases its leases at once — a crashed
        client's replacement must not wait out the heartbeat timeout."""
        with self._lock:
            self._conn_socks.pop(conn_id, None)
            for lease in self._leases.values():
                if lease.get("owner") == conn_id:
                    lease["owner"] = None

    def _touch(self, rank: int, lease: dict) -> None:
        now = self._clock()
        if now - lease["last_seen"] > self.heartbeat_timeout:
            # the client went silent past the lease but came back before
            # anything evicted it — a heartbeat gap worth counting
            self.metrics.inc("heartbeat_gaps", rank)
        lease["last_seen"] = now

    def _dispatch(self, sock, conn_id, msg, header, payload) -> None:
        if self._draining.is_set():
            # graceful drain: answer every request arriving during the
            # stop() window with a structured "retry shortly" instead of
            # letting the imminent socket close read as a raw reset
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "draining",
                "detail": "server is stopping; reconnect shortly",
                "retry_ms": 200,
            })
            return
        if msg == P.MSG_HELLO:
            self._on_hello(sock, conn_id, header)
        elif msg == P.MSG_GET_BATCH:
            self._on_get_batch(sock, conn_id, header)
        elif msg == P.MSG_SET_EPOCH:
            with self._lock:
                self.epoch = int(header.get("epoch", 0))
            self._write_snapshot(force=True)
            P.send_msg(sock, P.MSG_OK, {"epoch": self.epoch})
        elif msg == P.MSG_HEARTBEAT:
            rank = header.get("rank")
            with self._lock:
                lease = self._leases.get(int(rank)) if rank is not None \
                    else None
                if lease is not None and lease.get("owner") == conn_id:
                    self._touch(int(rank), lease)
            P.send_msg(sock, P.MSG_OK, {})
        elif msg == P.MSG_SNAPSHOT:
            self._write_snapshot(force=True)
            P.send_msg(sock, P.MSG_SNAPSHOT_STATE,
                       {"state": self._state_dict()})
        elif msg == P.MSG_METRICS:
            P.send_msg(sock, P.MSG_METRICS_REPORT,
                       {"report": self.metrics.report()})
        else:
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "unknown_type",
                "detail": f"message type {P.msg_name(msg)} not served",
            })

    # ---------------------------------------------------------------- HELLO
    def _on_hello(self, sock, conn_id, header) -> None:
        proto = header.get("proto")
        if proto != P.PROTOCOL_VERSION:
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "proto",
                "detail": f"server speaks protocol {P.PROTOCOL_VERSION}, "
                          f"client sent {proto!r}",
            })
            return
        world = header.get("world")
        if world is not None and int(world) != self.spec.world:
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "world",
                "detail": f"server world is {self.spec.world}, client "
                          f"expects {world}",
            })
            return
        fp = header.get("spec_fingerprint")
        if fp is not None and fp != self.spec.fingerprint():
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "spec",
                "detail": "client and server stream specs differ; refusing "
                          "to serve a different permutation than requested",
            })
            return
        batch = int(header.get("batch", 0))
        if batch < 1:
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "batch", "detail": f"batch must be >= 1, "
                                                   f"got {batch}"})
            return
        want = header.get("rank", -1)
        want = -1 if want is None else int(want)
        now = self._clock()
        with self._lock:
            rank = self._claim_rank(want, conn_id, now)
            if rank is None:
                code = "rank_taken" if 0 <= want < self.spec.world \
                    else "no_rank"
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": code,
                    "detail": f"rank {want} is live-leased" if code ==
                              "rank_taken" else
                              f"all {self.spec.world} ranks are live-leased",
                })
                return
            self._leases[rank]["batch"] = batch
            if rank in self._cursors:
                self.metrics.inc("reconnects", rank)
            epoch = self.epoch
        self._write_snapshot()
        P.send_msg(sock, P.MSG_WELCOME, {
            "proto": P.PROTOCOL_VERSION,
            "rank": rank,
            "world": self.spec.world,
            "epoch": epoch,
            "spec": self.spec.to_wire(),
        })

    def _claim_rank(self, want: int, conn_id: int, now: float):
        """Grant ``want`` (or the lowest free rank for -1).  Called under
        ``self._lock``.  A stale live lease is evicted on the spot."""
        candidates = ([want] if want >= 0 else range(self.spec.world))
        for rank in candidates:
            if not 0 <= rank < self.spec.world:
                return None
            lease = self._leases.get(rank)
            if lease is not None and lease.get("owner") is not None:
                if now - lease["last_seen"] <= self.heartbeat_timeout:
                    continue  # genuinely live
                lease["owner"] = None
                self.metrics.inc("evictions", rank)
            self._leases[rank] = {"owner": conn_id, "last_seen": now,
                                  "batch": self._leases.get(rank, {}).get(
                                      "batch", 0)}
            return rank
        return None

    # ------------------------------------------------------------ GET_BATCH
    def _on_get_batch(self, sock, conn_id, header) -> None:
        try:
            rank = int(header["rank"])
            epoch = int(header["epoch"])
            seq = int(header["seq"])
        except (KeyError, TypeError, ValueError):
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "bad_request",
                        "detail": "GET_BATCH needs rank/epoch/seq ints"})
            return
        if seq < 0:
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "bad_request", "detail": f"seq {seq} < 0"})
            return
        with self._lock:
            lease = self._leases.get(rank)
            if lease is None or lease.get("owner") != conn_id:
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "not_owner",
                    "detail": f"rank {rank} is not leased to this "
                              "connection; HELLO first",
                })
                return
            self._touch(rank, lease)
            batch = lease["batch"]
            cur = self._cursors.get(rank)
            if cur is None or cur["epoch"] != epoch:
                cur = self._cursors[rank] = {"epoch": epoch, "acked": -1,
                                             "hi": -1}
            ack = header.get("ack")
            if ack is not None:
                cur["acked"] = max(cur["acked"], int(ack))
            if seq > cur["acked"] + self.max_inflight:
                self.metrics.inc("throttled", rank)
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "throttle",
                    "detail": f"seq {seq} is {seq - cur['acked']} past the "
                              f"acked cursor; max_inflight="
                              f"{self.max_inflight}",
                    "retry_ms": 20,
                })
                return
            resend = seq <= cur["hi"]
        arr = self._rank_array(epoch, rank)
        lo = seq * batch
        total = int(arr.shape[0])
        if lo >= total:
            P.send_msg(sock, P.MSG_BATCH,
                       {"seq": seq, "eof": True, "total": total})
            return
        fields, payload = P.encode_indices(arr[lo:lo + batch])
        with self._lock:
            cur = self._cursors.get(rank)
            if cur is not None and cur["epoch"] == epoch:
                cur["hi"] = max(cur["hi"], seq)
        self.metrics.inc("batches_served", rank)
        if resend:
            self.metrics.inc("resends", rank)
        self._write_snapshot()
        P.send_msg(sock, P.MSG_BATCH,
                   {"seq": seq, "eof": False, "total": total, **fields},
                   payload)
