"""The index-serving daemon: one process owns epoch state, N clients stream.

``IndexServer`` owns exactly one :class:`~.spec.PartialShuffleSpec` and
serves its per-rank epoch streams over loopback TCP (the :mod:`.protocol`
framing).  Design points, in the order they matter:

* **One generation per (epoch, rank).**  A rank's stream is generated
  once via the spec's backend (cpu/native/xla), cached read-only, and
  every (re)connected client of that rank replays from the cache — the
  redundant per-host regen the local samplers do N times collapses to
  one, and the regen latency is timed into ``epoch_regen_ms``.
* **Client-driven cursors → exactly-once.**  ``GET_BATCH`` names an
  explicit ``(epoch, seq)``; the server is a pure function of that name
  plus the spec, so a client that reconnects after a server restart and
  re-requests its cursor gets bit-identical bytes (counted as a
  ``resend`` when the seq was already served).
* **Backpressure.**  A rank may run at most ``max_inflight`` batches
  past its acked cursor; beyond that ``GET_BATCH`` draws an
  ``ERROR(code='throttle', retry_ms=...)`` instead of queueing unbounded
  frames into a slow consumer's socket.
* **Leases, not registrations.**  A rank is leased to one connection;
  the lease expires after ``heartbeat_timeout`` seconds of silence
  (evicted lazily on claim *and* by the accept-loop sweep, which also
  closes the idle socket).  A dropped connection releases its lease
  immediately, so crash-reconnect never waits out the timeout.
* **Snapshots.**  Server state — spec wire form, current epoch, per-rank
  cursors — persists through ``utils/checkpoint``'s atomic-json helpers
  to ``snapshot_path`` (on SET_EPOCH, lease changes, every
  ``snapshot_interval`` batches, and at ``stop()``); a restarted server
  resumes from it.  Correctness does not depend on the snapshot (streams
  are pure), it restores the *operational* state: the served epoch and
  where each client was.
* **Elastic membership** (docs/RESILIENCE.md "Elastic membership").  A
  client ``LEAVE`` (preemption-notice drain), a rank staying vacant past
  ``membership_timeout``, or an explicit ``RESHARD(new_world)`` RPC
  freezes a reshard barrier: the per-rank consumption watermarks already
  tracked by the batch cursors are converted to whole consumed base
  units (samples, or SHARDS for shard mode), the barrier is their max
  ``C``, and every live rank drains — keeps being served its old
  partition, clamped to the barrier's per-rank sample target.  A rank
  counts as drained only once the client has *acked* delivery of its
  full pre-barrier span (via ``GET_BATCH``'s ack, or a ``HEARTBEAT``
  carrying the cursor when the client is idle) — a served-but-lost
  final reply stays resendable instead of being dropped by the commit.
  When all participants have drained (dead ones become *orphan*
  descriptors, served later as a prefix of rank 0's stream), the server
  appends the
  ``(old_world, C)`` cascade layer from SPEC.md §6, re-partitions the
  remainder at the new world via ``ops.core``'s reshard chain, and bumps
  its ``generation``; requests stamped with a stale generation draw
  ``ERROR(code='resharded')`` carrying the new membership, so surviving
  clients pick up the remainder stream exactly-once — no index served
  twice or dropped.  The v2 snapshot persists the cascade + watermarks,
  so a killed-and-restarted daemon resumes mid-cascade.
* **Hot-standby replication** (docs/RESILIENCE.md "Replication &
  failover").  A primary constructed with ``standby=(host, port)``
  appends every state-mutating transition — lease grant/release, epoch
  set, ack-watermark advance, reshard freeze/drain/commit, snapshot
  seal — to a sequenced in-memory WAL (:mod:`.replication`) and ships
  it to an ``IndexServer(role='standby')`` over ``REPL_SYNC`` /
  ``REPL_APPEND`` frames; the standby bootstraps from the full
  snapshot-v2 state and continuously applies.  Clients learn the
  standby address at HELLO; on primary loss they re-HELLO the standby
  with ``failover=true``, which promotes it once its replication feed
  has been stale for ``repl_feed_timeout`` seconds (or immediately
  under a forced ``REPL_PROMOTE``).  Promotion bumps a monotonic
  fencing ``term``; a zombie ex-primary — still accepting after the
  promotion — learns the winning term through its own shipper and
  refuses every client write with ``ERROR(code='fenced')``, so
  split-brain cannot double-serve a span.
* **Multi-tenancy** (docs/SERVICE.md "Tenancy").  With
  ``multi_tenant=True`` the daemon serves many specs: namespaces are
  keyed by the world-stripped spec fingerprint, a HELLO carrying an
  unknown fingerprint plus its spec wire form creates-or-attaches a
  tenant (up to ``max_tenants``, through the ``tenant.admission`` fault
  site), and each tenant is an unstarted nested ``IndexServer`` engine
  owning its own leases/cursors/barriers/snapshot/metrics — the front
  server routes frames by the connection's HELLO binding (or an
  additive ``tenant`` header field), runs all tenants' epoch regens
  through one :class:`~..tenancy.FairShareScheduler`, enforces
  :class:`~..tenancy.TenantQuota` caps at admission with typed
  ``retry_ms`` backpressure, tags every WAL record with its tenant so
  one standby mirrors and fails over ALL tenants, and filters
  ``TRACE_DUMP`` so one tenant never reads another's spans.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import warnings
import zlib
from collections import OrderedDict
from contextlib import nullcontext
from typing import Optional

import numpy as np

from .. import faults as F
from ..analysis.lockorder import new_lock
from .. import telemetry
from ..capability import EpochCapability
from ..durability import FsyncPolicy, WriteAheadLog
from ..durability.recover import replay_wal_tail
from ..telemetry import annotate as _annotate, span as _span
from ..tenancy import FairShareScheduler, TenantQuota, tenant_id_for
from ..utils.checkpoint import (
    list_tenant_snapshots,
    load_sampler_state,
    save_sampler_state,
    tenant_snapshot_path,
)
from . import protocol as P
from .backpressure import BackpressurePolicy
from .dispatch import DispatchListener
from .metrics import ServiceMetrics
from .replication import ReplicationLog, ReplicationShipper, TenantTaggedLog
from .spec import PartialShuffleSpec

SNAPSHOT_KIND = "index_service"

#: message types that mutate server state — the ones a fencing term
#: guards and a standby refuses pre-promotion (observability ops and the
#: REPL_* feed are exempt)
_MUTATING_MSGS = frozenset({
    P.MSG_HELLO, P.MSG_GET_BATCH, P.MSG_SET_EPOCH, P.MSG_HEARTBEAT,
    P.MSG_LEAVE, P.MSG_RESHARD, P.MSG_GET_CAPABILITY, P.MSG_APPEND,
})


def _state_crc(state: dict) -> int:
    """CRC32 over the canonical JSON of ``state`` minus its own crc
    field — what ``_write_snapshot`` embeds and ``_restore`` verifies,
    so a torn/corrupted snapshot is refused instead of half-applied."""
    body = json.dumps({k: v for k, v in state.items() if k != "crc32"},
                      sort_keys=True, separators=(",", ":")).encode()
    return zlib.crc32(body) & 0xFFFFFFFF


def _cursor_from_wire(c: dict) -> dict:
    """Rebuild one rank's batch cursor from a snapshot/WAL/replication
    record.  The streaming-only keys (``batch``, ``total`` — the advance
    barrier's pinned per-rank target, docs/STREAMING.md) must survive
    every restore path, or a recovered/promoted server would refuse the
    next horizon advance as a permanent straggler; frozen-dataset
    cursors carry neither and restore byte-identically."""
    cur = {"epoch": int(c["epoch"]), "acked": int(c["acked"]),
           "hi": int(c["hi"]), "samples": int(c.get("samples", 0))}
    for k in ("batch", "total"):
        if c.get(k) is not None:
            cur[k] = int(c[k])
    return cur


class IndexServer(DispatchListener):
    """Threaded loopback daemon serving one spec's index streams.

        spec = PartialShuffleSpec.plain(n, window=8192, world=4)
        with IndexServer(spec, port=0) as srv:   # ephemeral port
            addr = srv.address                   # (host, port)
            ...

    One thread accepts, one thread per connection serves; all daemonic.
    ``max_inflight`` bounds un-acked batches per rank; ``heartbeat_timeout``
    bounds how long a silent connection holds its rank lease.

    ``membership_timeout`` (seconds, default None = disabled) arms the
    eviction reshard: a rank whose lease stays vacant that long is
    treated as permanently preempted — the server triggers a reshard to
    ``world - vacancies`` and converts the rank's un-drained allocation
    to orphan descriptors instead of stalling the whole pod on it."""

    def __init__(
        self,
        spec: PartialShuffleSpec,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_inflight: int = 8,
        heartbeat_timeout: float = 30.0,
        membership_timeout: Optional[float] = None,
        snapshot_path: Optional[str] = None,
        snapshot_interval: int = 64,
        max_cached_arrays: Optional[int] = None,
        metrics: Optional[ServiceMetrics] = None,
        clock=time.monotonic,
        role: str = "primary",
        standby=None,
        repl_feed_timeout: float = 2.0,
        multi_tenant: bool = False,
        max_tenants: int = 8,
        tenant_quota: Optional[TenantQuota] = None,
        regen_scheduler: Optional[FairShareScheduler] = None,
        wal_dir: Optional[str] = None,
        fsync: str = "group_commit",
        capability_secret=None,
        backpressure: Optional[BackpressurePolicy] = None,
        cell_id: Optional[str] = None,
        cell_directory=None,
    ) -> None:
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        if role not in ("primary", "standby"):
            raise ValueError(f"role must be 'primary' or 'standby', "
                             f"got {role!r}")
        self.spec = spec
        self.host, self.port = host, int(port)
        self.max_inflight = int(max_inflight)
        #: every typed retry_ms hint comes from this table
        #: (service/backpressure.py) — tests pin sites, the autopilot's
        #: shed arm scales the whole table with observed queue depth
        self.backpressure = (backpressure if backpressure is not None
                             else BackpressurePolicy())
        # ---- multi-cell federation (docs/FEDERATION.md) ----
        #: which cell this server serves in; None on unfederated
        #: deployments (the cell gate and WELCOME fields then cost
        #: zero wire bytes)
        self.cell_id = None if cell_id is None else str(cell_id)
        #: the shared directory holder (``DirectoryRef``-like: its
        #: ``current()`` yields the live ``CellDirectory``) or a static
        #: directory value; consulted at every HELLO
        self._cell_directory = cell_directory
        #: the cell-cutover barrier (``freeze_writes``): while set,
        #: mutating client ops answer the retryable ``reshard`` refusal
        #: so a migration can ship a stable WAL tail
        self._cell_frozen = threading.Event()
        # ---- autopilot knobs (docs/AUTOPILOT.md) ----
        #: transport-batch size recommended to clients; None until a
        #: controller tunes it (zero WELCOME/heartbeat bytes until then)
        self._batch_hint: Optional[int] = None
        #: True once a controller touched a knob: heartbeat replies then
        #: carry the additive ``knobs`` field so already-connected
        #: clients adopt re-sized windows without a re-HELLO
        self._advertise_knobs = False
        #: newest replicated controller policy state (an ``autopilot``
        #: WAL record) — a promoted standby's controller resumes the
        #: closed loop from here  # guarded by: self._lock
        self._autopilot_state: Optional[dict] = None
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.membership_timeout = (
            None if membership_timeout is None else float(membership_timeout)
        )
        self.snapshot_path = snapshot_path
        self.snapshot_interval = max(1, int(snapshot_interval))
        #: lease time source — injectable so eviction timing is testable
        #: against a fake clock (real deployments never override it)
        self._clock = clock
        # current epoch + one behind: a client finishing epoch e while
        # another already moved to e+1 must not thrash regeneration
        self._max_cached = (
            2 * spec.world if max_cached_arrays is None
            else max(1, int(max_cached_arrays))
        )
        self.metrics = metrics if metrics is not None else ServiceMetrics()
        self.epoch = 0
        self._lock = new_lock("server.state")  # leases / cursors / epoch
        self._gen_lock = new_lock("server.gencache")  # the (epoch, rank) cache
        self._cache: OrderedDict[tuple, object] = OrderedDict()  # guarded by: self._gen_lock
        #: rank -> {"owner": conn_id|None, "last_seen": t, "batch": int}
        self._leases: dict[int, dict] = {}  # guarded by: self._lock
        #: rank -> {"epoch": e, "acked": int, "hi": int, "samples": int}
        #: (hi = highest seq ever served, a request at or below it is a
        #: resend; samples = served sample high-water, the consumption
        #: watermark an elastic barrier cuts on)
        self._cursors: dict[int, dict] = {}  # guarded by: self._lock
        # ---- elastic membership state (all under self._lock) ----
        #: bumped at every reshard commit; GET_BATCH stamps it
        self.generation = 0  # guarded by: self._lock
        #: SPEC.md §6 cascade [(world, consumed_units), ...] outermost
        #: first, applying to epoch ``elastic_epoch`` only
        self.layers: list[tuple[int, int]] = []  # guarded by: self._lock
        self.elastic_epoch: Optional[int] = None  # guarded by: self._lock
        #: un-drained allocations of dead ranks, served as a prefix of
        #: rank 0's stream: JSON-safe {epoch, rank, world, layers, lo, hi}
        #: descriptors over the PURE partition stream of their generation
        self._orphans: list[dict] = []  # guarded by: self._lock
        #: in-flight reshard (phase 'freeze' → 'drain'), None otherwise
        self._reshard: Optional[dict] = None  # guarded by: self._lock
        #: per-deployment HMAC key for signed epoch capabilities
        #: (docs/CAPABILITY.md); None keeps GET_CAPABILITY refused and
        #: the wire format byte-identical to a pre-capability daemon
        self.capability_secret = capability_secret
        #: rank -> {"epoch", "gen", "total"} issued-capability records:
        #: which ranks consume via on-device regen (their ack-only
        #: cursors carry the consumption slack), replicated/persisted so
        #: a promoted standby keeps honoring the grants
        self._cap_records: dict[int, dict] = {}  # guarded by: self._lock
        #: rank -> clock time its lease went vacant (membership_timeout)
        self._vacated: dict[int, float] = {}  # guarded by: self._lock
        # ---- moving-horizon streaming (docs/STREAMING.md) ----
        #: True when the spec is a StreamSpec: ``self.epoch`` is the
        #: current horizon generation, and GET_BATCH/GET_CAPABILITY run
        #: the eligibility + ack-gated advance gate before serving
        self.streaming = getattr(spec, "mode", None) == "stream"
        #: True for a non-uniform sampling spec (docs/SAMPLING.md):
        #: SET_EPOCH accepts additive ``weights_delta`` re-weights
        #: (prioritized mode) and snapshots carry the adopted weights
        #: plus the dedup seen-state boundary
        self.sampling = getattr(spec, "sampling_mode", None) is not None
        #: absolute appended-sample total — monotonic, so a WAL replay
        #: takes the max and a dropped append record can only UNDER-count
        #: (the eligibility gate then serves later, never twice)
        self._stream_appended = 0  # guarded by: self._lock
        #: feeder id -> last applied stream_seq (APPEND retry dedup)
        self._stream_seqs: dict[str, int] = {}  # guarded by: self._lock
        #: accumulated additive per-source weights delta, folded into the
        #: spec's per-horizon weights at the next advance
        self._stream_pending: Optional[list] = None  # guarded by: self._lock
        #: horizon gen -> perf stamp of the append that opened it, popped
        #: into ``append_visible_ms`` when the horizon completes
        self._stream_first_t: dict[int, float] = {}  # guarded by: self._lock
        self._listener: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._conn_socks: dict[int, socket.socket] = {}
        self._next_conn_id = 0
        self._unsnapshotted = 0
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._snapshot_error_warned = False
        # ---- hot-standby replication (docs/RESILIENCE.md) ----
        #: 'primary' serves clients and (optionally) ships its WAL;
        #: 'standby' applies the feed and refuses data ops until promoted
        self.role = role
        #: monotonic fencing term; promotion bumps it, every REPL frame
        #: and post-failover client write carries it
        self.term = 0
        #: set when a newer term superseded this server: every client
        #: write is refused with ERROR(code='fenced') from then on
        self._fenced_term: Optional[int] = None
        self._standby_addr = (
            None if standby is None
            else (str(standby[0]), int(standby[1]))
        )
        self.repl_feed_timeout = float(repl_feed_timeout)
        self._repl_log: Optional[ReplicationLog] = None
        self._shipper: Optional[ReplicationShipper] = None
        # standby-side feed state
        self._applied_lsn = 0
        self._feed_last: Optional[float] = None
        self._primary_addr = None       # learned from REPL_SYNC
        self._seal_pending = False
        # ---- durability (docs/RESILIENCE.md "Durability & recovery") ----
        #: segment-WAL directory; None keeps the pre-durability behavior
        #: (in-memory replication log only, full-snapshot restores)
        self.wal_dir = wal_dir
        #: parsed eagerly so a bad policy string fails construction,
        #: not the first append
        self.fsync_policy = FsyncPolicy.parse(fsync)
        self._wal: Optional[WriteAheadLog] = None
        #: the WAL lsn the restored snapshot checkpoint reflects —
        #: recovery replays the tail strictly above it
        self._ckpt_lsn = 0  # guarded by: self._lock
        # ---- multi-tenancy (docs/SERVICE.md "Tenancy") ----
        #: this server's own namespace id — the world-stripped spec
        #: fingerprint hashed down to a short wire/file-safe token.  A
        #: single-tenant daemon still has one (it IS its default tenant).
        self.tenant_id = tenant_id_for(spec.fingerprint(include_world=False))
        self.multi_tenant = bool(multi_tenant)
        self.max_tenants = max(1, int(max_tenants))
        #: quota stamped onto tenants this daemon creates; the default
        #: tenant (the constructor spec) itself runs unquotaed unless a
        #: parent stamped one on this engine
        self.tenant_quota = (tenant_quota if tenant_quota is not None
                             else TenantQuota())
        self.quota: Optional[TenantQuota] = None
        #: tenant engines: unstarted IndexServer instances (no listener,
        #: no threads) owning one spec's leases/cursors/barriers/snapshot
        #: each; the front server routes frames into them
        self._tenants: "OrderedDict[str, IndexServer]" = OrderedDict()
        self._tenant_by_id: dict[str, "IndexServer"] = {}
        #: conn_id -> tenant engine bound at HELLO (front server only)
        self._conn_tenant: dict[int, "IndexServer"] = {}
        #: the engine's owner when this instance is a tenant engine
        self._parent: Optional["IndexServer"] = None
        #: shared fair-share regen queue (engines borrow the front's)
        self._regen_sched = (
            regen_scheduler if regen_scheduler is not None
            else (FairShareScheduler(metrics=self.metrics.registry)
                  if self.multi_tenant else None)
        )
        if (self._regen_sched is not None
                and self._regen_sched._metrics is None):
            # a caller-provided queue still reports wait time through
            # this daemon's registry (``regen_queue_ms``)
            self._regen_sched._metrics = self.metrics.registry

    # ------------------------------------------------------------ lifecycle
    def start(self) -> tuple[str, int]:
        """Bind, restore any snapshot, and begin accepting.  Returns the
        bound ``(host, port)`` — pass ``port=0`` for an ephemeral port."""
        if self._listener is not None:
            raise RuntimeError("server already started")
        self._stop.clear()
        self._draining.clear()
        self._recover_from_disk()
        # the accept loop (service/dispatch.py) doubles as the lease sweeper
        self._listener_bind()
        if self.role == "primary" and (self._standby_addr is not None
                                       or self._wal is not None):
            # the log exists whenever there is somewhere for records to
            # go: a standby to ship to, a WAL to write through to, or
            # both (then they share one lsn sequence)
            self._repl_log = ReplicationLog(metrics=self.metrics,
                                            wal=self._wal)
            for eng in self._engines():
                eng._repl_log = TenantTaggedLog(self._repl_log,
                                                eng.tenant_id)
            if self._standby_addr is not None:
                self._shipper = ReplicationShipper(
                    self._repl_log, self._standby_addr,
                    state_fn=self._repl_sync_state,
                    term_fn=lambda: self.term,
                    on_fenced=self._fence,
                    metrics=self.metrics,
                )
                self._shipper.start()
        return self.host, self.port

    @property
    def address(self) -> tuple[str, int]:
        return self.host, self.port

    def stop(self, drain_s: float = 0.05) -> None:
        """Graceful shutdown: drain, drop every connection, persist a
        snapshot.

        Drain phase: accepting stops and, for ``drain_s`` seconds,
        requests still arriving on live connections are answered
        ``ERROR(code='draining', retry_ms=...)`` — a structured "come
        back shortly" the retry layer sleeps on, instead of a raw reset
        racing the last reply.  Then every connection socket is shut down
        and closed *before* the serve threads are joined, so a thread
        blocked in ``recv`` wakes immediately and the join cannot leak
        threads; any survivor past the join timeout is counted
        (``leaked_threads``) and warned about rather than silently
        abandoned."""
        self._draining.set()
        shipper, self._shipper = self._shipper, None
        if shipper is not None:
            shipper.stop()
        ls, self._listener = self._listener, None
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        if drain_s > 0 and not self._stop.is_set():
            time.sleep(drain_s)
        self._stop.set()
        with self._lock:
            socks = list(self._conn_socks.values())
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        leaked = [t for t in self._threads if t.is_alive()]
        if leaked:
            self.metrics.inc("leaked_threads", value=len(leaked))
            warnings.warn(
                f"IndexServer.stop(): {len(leaked)} serve thread(s) "
                f"survived the join timeout: "
                f"{[t.name for t in leaked]}", RuntimeWarning,
            )
        self._threads.clear()
        for eng in self._engines():
            eng._draining.set()
            eng._stop.set()
            eng._write_snapshot(force=True)
        self._write_snapshot(force=True)
        wal, self._wal = self._wal, None
        if wal is not None:
            for eng in self._engines():
                eng._wal = None
            wal.close(sync=True)

    def kill(self) -> None:
        """Abrupt death for failover drills: the ``kill -9`` a ``stop()``
        is not.  No drain window, no final snapshot, no goodbye frames —
        the listener and every connection just disappear, exactly what
        clients of a preempted primary observe."""
        self._stop.set()
        shipper, self._shipper = self._shipper, None
        if shipper is not None:
            shipper.stop(join=False)
        ls, self._listener = self._listener, None
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        with self._lock:
            socks = list(self._conn_socks.values())
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=1.0)
        self._threads.clear()
        # no final sync: a killed host never got one either.  The close
        # only drops the handle; whatever the fsync policy had already
        # made durable is what recovery will see
        wal, self._wal = self._wal, None
        if wal is not None:
            for eng in self._engines():
                eng._wal = None
            wal.close(sync=False)

    def __enter__(self) -> "IndexServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- tenancy
    def _engines(self) -> list:
        """The tenant engines (never includes self — the front server IS
        its own default tenant).  Safe without the lock: the dict only
        ever grows, and callers tolerate a stale snapshot of it."""
        return list(self._tenants.values())

    def tenants(self) -> dict:
        """Public view: ``tenant_id -> world-stripped fingerprint`` for
        every namespace this daemon serves, the default one included."""
        out = {self.tenant_id: self.spec.fingerprint(include_world=False)}
        with self._lock:
            for fp, eng in self._tenants.items():
                out[eng.tenant_id] = fp
        return out

    def _make_tenant_engine(self, spec: PartialShuffleSpec) -> "IndexServer":
        """Build (and, when its snapshot exists, restore) one tenant
        engine: an unstarted IndexServer owning the tenant's leases,
        cursors, barriers, snapshot file, and scoped metrics.  It shares
        the front server's socket plane, WAL, and fair-share queue."""
        q = self.tenant_quota
        tid = tenant_id_for(spec.fingerprint(include_world=False))
        eng = IndexServer(
            spec,
            max_inflight=q.clamp_inflight(self.max_inflight),
            heartbeat_timeout=self.heartbeat_timeout,
            membership_timeout=self.membership_timeout,
            snapshot_path=(tenant_snapshot_path(self.snapshot_path, tid)
                           if self.snapshot_path else None),
            snapshot_interval=self.snapshot_interval,
            metrics=self.metrics.scoped(tid),
            clock=self._clock,
            role=self.role,
            regen_scheduler=self._regen_sched,
            capability_secret=self.capability_secret,
            # shared object, not a copy: an autopilot shed-scale on the
            # front paces every tenant's refusals too
            backpressure=self.backpressure,
        )
        eng.quota = q
        eng._parent = self
        eng.term = self.term
        if self._repl_log is not None:
            eng._repl_log = TenantTaggedLog(self._repl_log, tid)
        if self._wal is not None:
            # the engine shares the front's WAL: its records are
            # tenant-tagged in the same lsn sequence, and registering it
            # as an owner pins GC until it has sealed twice itself
            eng._wal = self._wal
            self._wal.register_owner(tid)
        if self._regen_sched is not None:
            self._regen_sched.set_quota(tid, weight=q.weight,
                                        concurrency=q.regen_concurrency)
        if eng.snapshot_path and os.path.exists(eng.snapshot_path):
            try:
                eng._restore_from_disk()
            except (OSError, ValueError, KeyError) as exc:
                warnings.warn(
                    f"IndexServer: tenant snapshot {eng.snapshot_path!r} "
                    f"not restored ({exc!r}); tenant {tid} starts fresh",
                    RuntimeWarning,
                )
        return eng

    def _register_tenant_locked(self, fp: str, eng: "IndexServer") -> None:
        self._tenants[fp] = eng
        self._tenant_by_id[eng.tenant_id] = eng

    def _restore_tenants(self) -> None:
        """Rediscover per-tenant snapshots next to ``snapshot_path`` on
        start, so a restarted multi-tenant daemon resumes every
        namespace, not just its constructor spec's."""
        own = self.spec.fingerprint(include_world=False)
        for tid, path in list_tenant_snapshots(self.snapshot_path).items():
            try:
                st = load_sampler_state(path)
                spec = PartialShuffleSpec.from_wire(
                    st["spec"], backend=self.spec.backend)
            except (OSError, ValueError, KeyError) as exc:
                # with a WAL the previous checkpoint can still name the
                # tenant's spec; _make_tenant_engine then restores from
                # it through the same fallback path
                spec = None
                if self._wal is not None and os.path.exists(path + ".prev"):
                    try:
                        st = load_sampler_state(path + ".prev")
                        spec = PartialShuffleSpec.from_wire(
                            st["spec"], backend=self.spec.backend)
                    except (OSError, ValueError, KeyError):
                        spec = None
                if spec is None:
                    warnings.warn(
                        f"IndexServer: tenant snapshot {path!r} unreadable "
                        f"({exc!r}); skipped", RuntimeWarning)
                    continue
            fp = spec.fingerprint(include_world=False)
            if fp == own:
                continue
            eng = self._make_tenant_engine(spec)
            with self._lock:
                if fp not in self._tenants:
                    self._register_tenant_locked(fp, eng)

    def _apply_tenant_state_locked(self, tid: str, tstate: dict) -> None:
        """Standby side: route a replicated tenant state (REPL_SYNC's
        ``tenants`` map, or a ``tenant`` WAL record) into the mirror
        engine, creating it from the state's spec wire form first if
        this standby has never seen the tenant.  Under ``self._lock``;
        lock order is always front → engine."""
        eng = self._tenant_by_id.get(tid)
        if eng is None:
            wire = tstate.get("spec")
            if wire is None:
                return
            spec = PartialShuffleSpec.from_wire(wire,
                                                backend=self.spec.backend)
            fp = spec.fingerprint(include_world=False)
            eng = self._make_tenant_engine(spec)
            self._register_tenant_locked(fp, eng)
        with eng._lock:
            eng._apply_state_locked(tstate)

    def _regen_cost(self) -> float:
        """Fair-share cost of one epoch regen for this tenant — its
        per-rank sample count (heavier tenants advance their virtual
        time faster, so a 1B-sample regen yields the queue sooner)."""
        n = None
        try:
            n = self.spec.num_samples(0)
        except (TypeError, ValueError):
            n = None
        if n is None and self.spec.shard_sizes is not None:
            n = int(np.sum(self.spec.shard_sizes)) \
                // max(1, self.spec.world)
        return float(max(1, n if n is not None else 1))

    # ------------------------------------------------------------- snapshot
    def _state_dict(self) -> dict:
        """Snapshot format 2 (docs/SERVICE.md): v1's kind/proto/spec/
        epoch/cursors plus the elastic membership — generation, cascade
        layers, orphan descriptors, per-cursor sample watermarks, and an
        in-flight drain (so a killed daemon resumes mid-cascade).  Leave
        grace deadlines are monotonic-clock-relative and do NOT persist
        (a restarted drain falls back to ``membership_timeout``)."""
        with self._lock:
            return self._state_dict_locked()

    def _state_dict_locked(self) -> dict:
        state = {
            "kind": SNAPSHOT_KIND,
            "format": 2,
            "proto": P.PROTOCOL_VERSION,
            "spec": self.spec.to_wire(),
            "epoch": self.epoch,
            "generation": self.generation,
            "term": int(self.term),
            "layers": [[int(w), int(c)] for w, c in self.layers],
            "elastic_epoch": self.elastic_epoch,
            "orphans": [dict(o) for o in self._orphans],
            "cursors": {
                str(r): dict(c) for r, c in self._cursors.items()
            },
            # lease batch sizes: a standby needs them for the drain
            # gate ((acked+1)*batch >= target); ownership does not
            # replicate — every lease is vacant on the peer
            "leases": {str(r): int(l.get("batch") or 0)
                       for r, l in self._leases.items()},
            # issued-capability records (additive within format 2): a
            # restarted/promoted daemon keeps honoring outstanding
            # grants' ack-only cursors (docs/CAPABILITY.md)
            "capabilities": {str(r): dict(rec)
                             for r, rec in self._cap_records.items()},
        }
        if self.streaming:
            # additive within format 2 (docs/STREAMING.md): absent for
            # every frozen-dataset snapshot, which stays byte-identical.
            # Totals are absolute and seqs are maxima, so restoring an
            # older checkpoint plus the WAL tail converges on the truth.
            state["stream"] = {
                "appended": int(self._stream_appended),
                "seqs": {str(k): int(v)
                         for k, v in self._stream_seqs.items()},
                "pending": (list(self._stream_pending)
                            if self._stream_pending is not None else None),
                "weights": {str(g): [int(x) for x in w]
                            for g, w in self.spec.stream_weights.items()},
            }
        if self.sampling:
            # additive within format 2 (docs/SAMPLING.md): adopted
            # per-epoch weights (prioritized) and the newest dedup
            # epoch-boundary seen state.  Both are recomputable — the
            # weights from the WAL, the boundary by refolding from
            # epoch 0 — so the block is a recovery accelerator, never
            # the source of truth
            blk = {"weights": {str(g): [int(x) for x in w]
                               for g, w in self.spec.stream_weights.items()}}
            bw = None
            if hasattr(self.spec, "dedup_boundary_wire"):
                # epoch + 1: serving epoch e folds through its END, so
                # the newest boundary the spec holds is e+1's start —
                # exactly the state a successor needs for epoch e+1
                bw = self.spec.dedup_boundary_wire(self.epoch + 1)
            if bw is not None:
                blk["dedup"] = bw
            state["sampling"] = blk
        if self._wal is not None and self._repl_log is not None:
            # the WAL position this snapshot reflects — recovery
            # replays the tail strictly above it.  Exact: every append
            # happens under this same lock, so nothing can slip between
            # reading the lsn and sealing the state
            state["wal_lsn"] = int(self._repl_log.lsn)
        rs = self._reshard
        if rs is not None and rs.get("phase") == "drain":
            state["reshard"] = {
                "target_world": int(rs["target_world"]),
                "epoch": int(rs["epoch"]),
                "barrier_units": int(rs["barrier_units"]),
                "targets": {str(r): int(t)
                            for r, t in rs["targets"].items()},
                "drained": sorted(rs["drained"]),
                "dead": sorted(rs["dead"]),
                "leaving": sorted(rs["leaving"]),
            }
        return state

    def _recover_from_disk(self) -> dict:
        """The restart-time recovery sequence (docs/RESILIENCE.md
        "Durability & recovery"): open the WAL — a torn tail is
        detected and cut there — restore the newest readable snapshot
        checkpoint, rediscover tenant snapshots, then replay the WAL
        tail above each owner's watermark through the same record path
        a hot standby applies.  Runs before the socket binds;
        :func:`~..durability.recover_unstarted` drives it directly for
        the crash matrix.  Returns the replay stats dict."""
        # a standby with a wal_dir opens its OWN log too: the receive
        # side of cross-cell shipping persists applied records so a DR
        # cell can recover a tenant from its local tail alone
        # (docs/FEDERATION.md "Cross-cell shipping")
        if (self.wal_dir is not None
                and self.role in ("primary", "standby")
                and self._wal is None):
            self._wal = WriteAheadLog(self.wal_dir,
                                      fsync=self.fsync_policy,
                                      metrics=self.metrics)
            self._wal.register_owner(self.tenant_id)
            for eng in self._engines():
                # same-instance restart: engines re-attach to the
                # reopened log (their old handle was closed at stop)
                eng._wal = self._wal
                self._wal.register_owner(eng.tenant_id)
        self._restore_from_disk()
        if self.multi_tenant and self.snapshot_path:
            self._restore_tenants()
        if self._wal is None:
            return {"replayed": 0, "skipped": 0, "last_lsn": 0,
                    "replay_ms": 0.0}
        stats = replay_wal_tail(self)
        if self.role == "standby":
            # a restarted DR standby resumes its applied prefix from its
            # shipped-tail WAL; the feed's lsn-overlap check then makes
            # any re-shipped records idempotent
            with self._lock:
                self._applied_lsn = max(int(self._applied_lsn),
                                        int(self._wal.last_lsn))
        return stats

    def _restore_from_disk(self) -> None:
        """Restore from ``snapshot_path``.  Without a WAL this is the
        pre-durability behavior: the one snapshot either restores or —
        on a CRC failure — is refused loudly and the server starts
        fresh.  With a WAL, a corrupt or unreadable newest checkpoint
        falls back to its retained ``.prev`` predecessor plus a longer
        tail replay (counted as ``snapshot_fallbacks``); only when
        neither is readable does the state rebuild from lsn 0."""
        if not (self.snapshot_path and os.path.exists(self.snapshot_path)):
            return
        if self._wal is None:
            self._restore(load_sampler_state(self.snapshot_path))
            return
        for fallback, path in enumerate(
                (self.snapshot_path, self.snapshot_path + ".prev")):
            if not os.path.exists(path):
                continue
            try:
                state = load_sampler_state(path)
            except (OSError, ValueError) as exc:
                warnings.warn(
                    f"IndexServer: checkpoint {path!r} unreadable "
                    f"({exc!r}); trying the previous one", RuntimeWarning)
                continue
            if self._restore(state):
                if fallback:
                    self.metrics.inc("snapshot_fallbacks")
                    warnings.warn(
                        f"IndexServer: newest checkpoint was refused; "
                        f"fell back to {path!r} — the WAL replay covers "
                        "the difference", RuntimeWarning)
                return
        # neither checkpoint readable: the WAL replay rebuilds the
        # operational state from lsn 0

    def _restore(self, state: dict) -> bool:
        crc = state.get("crc32")
        if crc is not None and _state_crc(state) != int(crc):
            # a torn/corrupted snapshot must be refused, not half-loaded:
            # correctness never depends on it (streams are pure), so the
            # server starts fresh — loudly
            self.metrics.inc("snapshot_corrupt")
            warnings.warn(
                f"IndexServer: snapshot {self.snapshot_path!r} failed its "
                f"CRC32 check (stored {int(crc)}, computed "
                f"{_state_crc(state)}); refusing the corrupted snapshot "
                "and starting fresh", RuntimeWarning,
            )
            return False
        if state.get("kind") != SNAPSHOT_KIND:
            raise ValueError(
                f"snapshot kind {state.get('kind')!r} is not a "
                f"{SNAPSHOT_KIND!r} snapshot"
            )
        fmt = int(state.get("format", 1))
        theirs = PartialShuffleSpec.from_wire(state["spec"],
                                              backend=self.spec.backend)
        # world is authoritative SERVER state once resharding exists, so
        # the identity check strips it; a v2 snapshot's world is adopted
        ours = self.spec.fingerprint(include_world=False)
        if theirs.fingerprint(include_world=False) != ours:
            raise ValueError(
                "snapshot was written by a server with a different stream "
                f"spec: {theirs.fingerprint()} != {self.spec.fingerprint()}; "
                "serving it would hand clients a different permutation"
            )
        if fmt < 2 and theirs.world != self.spec.world:
            raise ValueError(
                f"pre-elastic (format 1) snapshot has world {theirs.world}; "
                f"this server was constructed with world {self.spec.world}"
            )
        with self._lock:
            self.epoch = int(state.get("epoch", 0))
            self._ckpt_lsn = int(state.get("wal_lsn", 0))
            self._cursors = {
                int(r): _cursor_from_wire(c)
                for r, c in state.get("cursors", {}).items()
            }
            if fmt < 2:
                return True
            self.generation = int(state.get("generation", 0))
            self.term = max(self.term, int(state.get("term", 0)))
            for r, b in (state.get("leases") or {}).items():
                l = self._leases.setdefault(
                    int(r), {"owner": None, "last_seen": self._clock(),
                             "batch": 0})
                l["batch"] = int(b)
            self.layers = [(int(w), int(c))
                           for w, c in state.get("layers") or []]
            ee = state.get("elastic_epoch")
            self.elastic_epoch = None if ee is None else int(ee)
            self._orphans = [dict(o) for o in state.get("orphans") or []]
            self._cap_records = {
                int(r): {"epoch": int(c["epoch"]), "gen": int(c["gen"]),
                         "total": int(c["total"])}
                for r, c in (state.get("capabilities") or {}).items()
            }
            if theirs.world != self.spec.world:
                self.spec = self.spec.with_world(theirs.world)
            st = state.get("stream")
            if self.streaming and st is not None:
                self._stream_appended = max(self._stream_appended,
                                            int(st.get("appended", 0)))
                for k, v in (st.get("seqs") or {}).items():
                    self._stream_seqs[str(k)] = max(
                        self._stream_seqs.get(str(k), -1), int(v))
                p = st.get("pending")
                self._stream_pending = (None if p is None
                                        else [int(x) for x in p])
                w = st.get("weights") or {}
                if w:
                    self.spec = self.spec.with_stream_weights(
                        {int(g): tuple(int(x) for x in ws)
                         for g, ws in w.items()})
            sm = state.get("sampling")
            if self.sampling and sm is not None:
                w = sm.get("weights") or {}
                if w:
                    self.spec = self.spec.with_stream_weights(
                        {int(g): tuple(int(x) for x in ws)
                         for g, ws in w.items()})
                bw = sm.get("dedup")
                if bw is not None and hasattr(self.spec,
                                              "with_dedup_boundary"):
                    # recovery accelerator only: folding from epoch 0
                    # reaches the identical state (docs/SAMPLING.md)
                    self.spec = self.spec.with_dedup_boundary(
                        int(bw["epoch"]), bw["seen"])
            rs = state.get("reshard")
            if rs is not None:
                self._reshard = {
                    "phase": "drain",
                    "target_world": int(rs["target_world"]),
                    "epoch": int(rs["epoch"]),
                    "barrier_units": int(rs["barrier_units"]),
                    "targets": {int(r): int(t)
                                for r, t in rs["targets"].items()},
                    "drained": {int(r) for r in rs.get("drained", [])},
                    "dead": {int(r) for r in rs.get("dead", [])},
                    "leaving": {int(r): None for r in rs.get("leaving", [])},
                }
                # every lease is vacant after a restart: put each
                # un-drained participant on the membership_timeout clock
                # now, so a participant that never reconnects (its grace
                # deadline did not survive the restart either) is
                # eventually declared dead instead of deadlocking the
                # barrier for every survivor
                now = self._clock()
                for r in self._reshard["targets"]:
                    if (r not in self._reshard["drained"]
                            and r not in self._reshard["dead"]):
                        self._vacated.setdefault(r, now)
        return True

    def _write_snapshot(self, force: bool = False) -> None:
        if not self.snapshot_path:
            return
        with self._lock:
            self._unsnapshotted += 1
            if not force and self._unsnapshotted < self.snapshot_interval:
                return
            self._unsnapshotted = 0
        state = self._state_dict()
        state["crc32"] = _state_crc(state)
        wal = self._wal
        try:
            F.fire("server.snapshot_write")
            if wal is not None and os.path.exists(self.snapshot_path):
                # previous-checkpoint retention: keep the predecessor so
                # a corrupt newest snapshot can fall back to it plus a
                # longer WAL replay (``snapshot_fallbacks``)
                os.replace(self.snapshot_path,
                           self.snapshot_path + ".prev")
            save_sampler_state(self.snapshot_path, state, durable=True)
            if self._repl_log is not None:
                # the seal marks the durable point in the WAL: a standby
                # with its own snapshot_path persists at the same cadence
                self._repl_log.append("seal", {})
            if wal is not None:
                # the seal is an incremental checkpoint: record this
                # owner's watermark and GC segments every owner has
                # checkpointed past (twice — previous retention)
                wal.sync()
                wal.checkpoint(self.tenant_id,
                               int(state.get("wal_lsn", 0)))
                with self._lock:
                    self._ckpt_lsn = int(state.get("wal_lsn", 0))
        except OSError as exc:
            # The snapshot is operational state, never a correctness
            # dependency (streams are pure functions of the spec) — a
            # full/unwritable disk must degrade observably, not take the
            # serving path down with it.
            self.metrics.inc("snapshot_errors")
            if not self._snapshot_error_warned:
                self._snapshot_error_warned = True
                warnings.warn(
                    f"IndexServer: snapshot write to "
                    f"{self.snapshot_path!r} failed ({exc!r}); serving "
                    "continues without persistence", RuntimeWarning,
                )

    # ------------------------------------------- hot-standby replication
    def _repl_append(self, op: str, **data) -> None:
        """Append one WAL record when replication is on (no-op
        otherwise).  Safe under ``self._lock`` — the log has its own
        lock and never takes the server's."""
        log = self._repl_log
        if log is not None:
            log.append(op, data)

    def _repl_sync_state(self) -> dict:
        state = self._state_dict()
        # the SYNC bootstrap also teaches the standby where the primary
        # serves, so its 'standby' refusals can redirect misrouted clients
        state["primary_addr"] = [self.host, self.port]
        tenants = {eng.tenant_id: eng._state_dict()
                   for eng in self._engines()}
        if tenants:
            state["tenants"] = tenants
        return state

    def _fence(self, term: int) -> None:
        """A newer term exists (the standby promoted past this server):
        refuse every client write from here on — split-brain must not
        double-serve a span.  Observability ops keep being served."""
        with self._lock:
            if self._fenced_term is None or int(term) > self._fenced_term:
                self._fenced_term = int(term)
        self.metrics.inc("fenced")
        telemetry.event("fenced", term=int(term))

    def _try_promote(self, force: bool = False) -> bool:
        """Standby → primary, gated on feed staleness: while the
        replication feed is fresh the primary is alive and the promotion
        is refused (split-brain guard); ``force`` overrides for an
        operator-driven switchover."""
        with self._lock:
            if self.role != "standby":
                return True
            if not force:
                last = self._feed_last
                if last is not None and \
                        self._clock() - last <= self.repl_feed_timeout:
                    return False  # the primary's feed is alive
            try:
                F.fire("repl.promote")
            except F.InjectedThreadDeath:
                raise
            except Exception:  # lint: allow-broad-except(injected promote fault; client retries)
                # the fault fires BEFORE any state flips: still a
                # standby, and the failing-over client simply retries
                return False
            self.term = int(self.term) + 1
            self.role = "primary"
            self._promote_local_state_locked()
            for eng in self._tenant_by_id.values():
                # tenants promote with their front: same term, and their
                # own in-flight drains go on the vacancy clock too
                with eng._lock:
                    eng.role = "primary"
                    eng.term = self.term
                    eng._promote_local_state_locked()
            if self._wal is not None and self._repl_log is None:
                # a DR standby promoting over its shipped-tail WAL
                # becomes a durable primary on the spot: new transitions
                # write through to the SAME on-disk sequence the feed
                # left off at (docs/FEDERATION.md "Cell-kill recovery")
                self._repl_log = ReplicationLog(metrics=self.metrics,
                                                wal=self._wal)
                for eng in self._engines():
                    eng._repl_log = TenantTaggedLog(self._repl_log,
                                                    eng.tenant_id)
            self.metrics.inc("promotions")
            term = self.term
        telemetry.event("promoted", term=term)
        return True

    def _promote_local_state_locked(self) -> None:
        """Post-promotion bookkeeping shared by the front server and its
        tenant engines: every lease is vacant on the promoted peer, so
        each un-drained participant of an in-flight barrier goes on the
        membership_timeout clock (one that never fails over must not
        deadlock the drain)."""
        rs = self._reshard
        if rs is not None and rs.get("phase") == "drain":
            now = self._clock()
            for r in rs["targets"]:
                if r not in rs["drained"] and r not in rs["dead"]:
                    self._vacated.setdefault(r, now)

    def _standby_refusal(self) -> dict:
        with self._lock:
            pa = self._primary_addr
            return {
                "code": "standby",
                "retry_ms": self.backpressure.retry_ms("standby"),
                "term": int(self.term),
                "primary": (list(pa) if pa is not None else None),
                "detail": "this server is a hot standby; data ops are "
                          "refused until a promotion",
            }

    def _term_refusal(self, header: dict) -> Optional[dict]:
        """The fencing gate on every mutating request (docs/RESILIENCE.md
        "Split-brain fencing").  Returns the ERROR header to refuse with,
        or None when the request may proceed."""
        t = header.get("term")
        with self._lock:
            if self._fenced_term is not None:
                refusal = {
                    "code": "fenced", "term": int(self._fenced_term),
                    "serving": False,
                    "detail": "this server was superseded by a promotion "
                              f"to term {self._fenced_term}; fail over",
                }
            elif t is not None and int(t) > self.term:
                # the request rode through a promotion this server never
                # saw — so this server IS the zombie: fence it on the spot
                self._fenced_term = int(t)
                refusal = {
                    "code": "fenced", "term": int(t), "serving": False,
                    "detail": f"request term {t} proves a newer primary "
                              "exists; this server is fenced",
                }
            elif t is not None and int(t) < self.term:
                return {
                    "code": "fenced", "term": int(self.term),
                    "serving": True,
                    "detail": f"request term {t} is stale; adopt term "
                              f"{self.term} and retry",
                }
            else:
                return None
        # a zombie refusing a write — the chaos matrix's injection point
        try:
            F.fire("server.zombie_write")
        except F.InjectedThreadDeath:
            raise
        except Exception:  # lint: allow-broad-except(injected fault must not un-refuse)
            pass
        self.metrics.inc("fenced_writes")
        return refusal

    # ------------------------------------------------ multi-cell federation
    def _cell_dir(self):
        """The live ``CellDirectory`` this server consults, or None when
        unfederated.  ``cell_directory`` is duck-typed: a
        ``DirectoryRef``-like holder (has ``current()``) or a static
        directory value — so the service layer never imports
        ``federation`` (docs/FEDERATION.md)."""
        d = self._cell_directory
        if d is None:
            return None
        return d.current() if hasattr(d, "current") else d

    def _cell_fields(self) -> dict:
        """Additive WELCOME fields naming this server's cell and the
        directory wire form — a federated client learns the global
        namespace from its very first claim; zero bytes unfederated."""
        if self.cell_id is None:
            return {}
        out = {"cell": self.cell_id}
        d = self._cell_dir()
        if d is not None:
            out["cell_directory"] = d.to_wire()
        return out

    def _cell_refusal(self, header: dict) -> Optional[dict]:
        """The cell gate on HELLO (docs/FEDERATION.md "Cell directory"):
        a tenant homed at another cell gets the typed retryable
        ``wrong_cell`` redirect carrying the home cell and the directory
        wire form — ``wrong_shard``'s exact shape, one layer up.  A
        failover HELLO is exempt: a client whose home cell just died
        must be able to knock at the DR cell BEFORE the directory
        flips — the promotion gate (feed staleness) is the safety
        there, not the gate."""
        if self.cell_id is None or header.get("failover"):
            return None
        d = self._cell_dir()
        if d is None:
            return None
        tenant = header.get("tenant")
        if tenant is None:
            fp = header.get("spec_fingerprint")
            tenant = (tenant_id_for(str(fp)) if fp is not None
                      else self.tenant_id)
        home = d.home(str(tenant))
        if home == self.cell_id:
            return None
        self.metrics.inc("cell_redirects")
        return {
            "code": "wrong_cell",
            "retry_ms": self.backpressure.retry_ms("wrong_cell"),
            "cell": self.cell_id,
            "home": home,
            "cell_directory": d.to_wire(),
            "detail": f"tenant {tenant} is homed at cell {home!r}; this "
                      f"is cell {self.cell_id!r} (directory v{d.version})",
        }

    def freeze_writes(self, on: bool = True) -> None:
        """The migration cutover barrier (docs/FEDERATION.md "Live
        migration"): while frozen, mutating client ops answer the
        retryable ``reshard`` refusal — HELLO excepted, so redirected
        clients can still land and wait — and the WAL tail goes
        quiescent so the shipper can drain it to the target cell."""
        if on:
            self._cell_frozen.set()
        else:
            self._cell_frozen.clear()

    def _apply_state_locked(self, state: dict) -> None:
        """Adopt a full replicated state dict (REPL_SYNC bootstrap, or a
        ``state`` WAL record carrying a reshard drain-flip/commit).
        Trusting by design — the feed already carries a winning term."""
        pa = state.get("primary_addr")
        if pa is not None:
            self._primary_addr = (str(pa[0]), int(pa[1]))
        wire = state.get("spec")
        if wire is not None:
            theirs = PartialShuffleSpec.from_wire(
                wire, backend=self.spec.backend)
            if theirs.world != self.spec.world:
                self.spec = self.spec.with_world(theirs.world)
        self.epoch = int(state.get("epoch", 0))
        self.generation = int(state.get("generation", 0))
        self.term = max(self.term, int(state.get("term", 0)))
        self.layers = [(int(w), int(c))
                       for w, c in state.get("layers") or []]
        ee = state.get("elastic_epoch")
        self.elastic_epoch = None if ee is None else int(ee)
        self._orphans = [dict(o) for o in state.get("orphans") or []]
        self._cap_records = {
            int(r): {"epoch": int(c["epoch"]), "gen": int(c["gen"]),
                     "total": int(c["total"])}
            for r, c in (state.get("capabilities") or {}).items()
        }
        self._cursors = {
            int(r): _cursor_from_wire(c)
            for r, c in (state.get("cursors") or {}).items()
        }
        for r, b in (state.get("leases") or {}).items():
            l = self._leases.setdefault(
                int(r), {"owner": None, "last_seen": self._clock(),
                         "batch": 0})
            l["batch"] = int(b)
        st = state.get("stream")
        if self.streaming and st is not None:
            self._stream_appended = int(st.get("appended", 0))
            self._stream_seqs = {str(k): int(v)
                                 for k, v in (st.get("seqs") or {}).items()}
            p = st.get("pending")
            self._stream_pending = (None if p is None
                                    else [int(x) for x in p])
            w = st.get("weights") or {}
            if w:
                self.spec = self.spec.with_stream_weights(
                    {int(g): tuple(int(x) for x in ws)
                     for g, ws in w.items()})
        sm = state.get("sampling")
        if self.sampling and sm is not None:
            w = sm.get("weights") or {}
            if w:
                self.spec = self.spec.with_stream_weights(
                    {int(g): tuple(int(x) for x in ws)
                     for g, ws in w.items()})
            bw = sm.get("dedup")
            if bw is not None and hasattr(self.spec, "with_dedup_boundary"):
                self.spec = self.spec.with_dedup_boundary(
                    int(bw["epoch"]), bw["seen"])
        rs = state.get("reshard")
        if rs is not None:
            self._reshard = {
                "phase": "drain",
                "target_world": int(rs["target_world"]),
                "epoch": int(rs["epoch"]),
                "barrier_units": int(rs["barrier_units"]),
                "targets": {int(r): int(t)
                            for r, t in rs["targets"].items()},
                "drained": {int(r) for r in rs.get("drained", [])},
                "dead": {int(r) for r in rs.get("dead", [])},
                "leaving": {int(r): None for r in rs.get("leaving", [])},
            }
        else:
            self._reshard = None
        for tid, tstate in (state.get("tenants") or {}).items():
            self._apply_tenant_state_locked(str(tid), dict(tstate))

    def _apply_record_locked(self, rec: dict) -> None:
        tid = rec.get("tenant")
        if tid is not None and rec.get("op") != "tenant":
            # a tenant engine's record: route it to this side's mirror
            # of that tenant (tag stripped — the engine's own handlers
            # key on rank/epoch only)
            eng = self._tenant_by_id.get(str(tid))
            if eng is not None and eng is not self:
                with eng._lock:
                    eng._apply_record_locked(
                        {k: v for k, v in rec.items() if k != "tenant"})
            return
        op = rec.get("op")
        if op == "epoch":
            self.epoch = int(rec["epoch"])
        elif op == "lease":
            l = self._leases.setdefault(
                int(rec["rank"]), {"owner": None,
                                   "last_seen": self._clock(), "batch": 0})
            l["batch"] = int(rec.get("batch") or 0)
            l["last_seen"] = self._clock()
        elif op == "lease_release":
            l = self._leases.get(int(rec["rank"]))
            if l is not None:
                l["owner"] = None
            self._vacated.setdefault(int(rec["rank"]), self._clock())
        elif op == "cursor":
            self._cursors[int(rec["rank"])] = _cursor_from_wire(rec)
        elif op == "state":
            self._apply_state_locked(rec.get("state") or {})
        elif op == "seal":
            self._seal_pending = True
        elif op == "tenant":
            # tenant creation on the primary: mirror the full engine
            # state (spec wire included) so failover restores it
            self._apply_tenant_state_locked(
                str(rec.get("tenant")), dict(rec.get("state") or {}))
        elif op == "capability":
            # an issued-capability grant: the mirror must keep applying
            # the consumption slack to this rank's ack-only cursor, or
            # a promoted standby would commit barriers below what the
            # capability client locally delivered (docs/CAPABILITY.md)
            self._cap_records[int(rec["rank"])] = {
                "epoch": int(rec["epoch"]), "gen": int(rec["gen"]),
                "total": int(rec["total"]),
            }
        elif op == "stream":
            # moving-horizon records (docs/STREAMING.md) carry ABSOLUTE
            # totals and per-feeder seq maxima, so a dropped/torn append
            # record is re-established by the next one — replay can only
            # under-count, and the eligibility gate then serves later,
            # never a sample twice
            self._stream_appended = max(self._stream_appended,
                                        int(rec.get("appended", 0)))
            for k, v in (rec.get("seqs") or {}).items():
                self._stream_seqs[str(k)] = max(
                    self._stream_seqs.get(str(k), -1), int(v))
            if "pending" in rec:
                p = rec.get("pending")
                self._stream_pending = (None if p is None
                                        else [int(x) for x in p])
            ep = rec.get("epoch")
            if ep is not None:
                # an advance record: adopt the folded weights first,
                # then the horizon generation (the pending delta it
                # consumed is spent)
                w = rec.get("weights")
                if w is not None and self.streaming:
                    self.spec = self.spec.with_stream_weights(
                        {int(ep): tuple(int(x) for x in w)})
                self.epoch = max(self.epoch, int(ep))
                self._stream_pending = None
        elif op == "sampling":
            # a prioritized re-weight adopted at SET_EPOCH
            # (docs/SAMPLING.md): the folded EFFECTIVE weights ride the
            # record, so replay adopts the same alias table without
            # re-deriving the fold — idempotent under re-application
            w = rec.get("weights")
            if w is not None and self.sampling:
                self.spec = self.spec.with_stream_weights(
                    {int(rec["epoch"]): tuple(int(x) for x in w)})
            self.epoch = int(rec["epoch"])
        elif op == "autopilot":
            # a controller decision (autopilot/controller.py): keep the
            # NEWEST policy state only — a promoted standby seeds its
            # own controller from it, so the decision stream continues
            # instead of restarting cold (docs/AUTOPILOT.md).  Knob
            # values ride the record too: the mirror advertises the
            # same tuned batch/inflight its primary did.
            st = rec.get("pstate")
            if st is not None:
                self._autopilot_state = dict(st)
            kn = rec.get("knobs") or {}
            if kn.get("max_inflight") is not None:
                self.max_inflight = max(1, int(kn["max_inflight"]))
            if kn.get("batch_hint") is not None:
                self._batch_hint = max(1, int(kn["batch_hint"]))
            if kn:
                self._advertise_knobs = True
        # unknown ops fall through: the record vocabulary is additive

    def _on_repl_sync(self, sock, header) -> None:
        term = int(header.get("term", 0))
        with self._lock:
            if self.role == "primary" or term < self.term:
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "fenced", "term": int(self.term),
                    "serving": self.role == "primary",
                    "detail": "REPL_SYNC from a superseded primary",
                })
                return
            self._apply_state_locked(header.get("state") or {})
            self.term = max(self.term, term)
            self._applied_lsn = int(header.get("lsn", 0))
            self._feed_last = self._clock()
            applied = self._applied_lsn
        telemetry.event("repl_synced", lsn=applied, term=term)
        P.send_msg(sock, P.MSG_OK, {"applied_lsn": applied})

    def _on_repl_append(self, sock, header) -> None:
        term = int(header.get("term", 0))
        with self._lock:
            if self.role == "primary" or term < self.term:
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "fenced", "term": int(self.term),
                    "serving": self.role == "primary",
                    "detail": "REPL_APPEND from a superseded primary",
                })
                return
            self.term = max(self.term, term)
            self._feed_last = self._clock()
            recs = header.get("records") or []
            from_lsn = int(header.get("from_lsn", 0))
            if recs and from_lsn > self._applied_lsn + 1:
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "repl_gap",
                    "applied_lsn": int(self._applied_lsn),
                    "detail": f"append starts at lsn {from_lsn}; applied "
                              f"prefix ends at {self._applied_lsn}",
                })
                return
            fresh = []
            for rec in recs:
                lsn = int(rec.get("lsn", 0))
                if lsn <= self._applied_lsn:
                    continue  # idempotent overlap after a re-SYNC
                self._apply_record_locked(rec)
                self._applied_lsn = lsn
                fresh.append(rec)
            applied = self._applied_lsn
            seal, self._seal_pending = self._seal_pending, False
            sealed = []
            for eng in self._tenant_by_id.values():
                if eng._seal_pending:
                    eng._seal_pending = False
                    sealed.append(eng)
        wal = self._wal
        if wal is not None:
            # receive-side write-through (docs/FEDERATION.md): a standby
            # with its own WAL persists each applied record before the
            # ack, so the shipped tail survives this cell losing its
            # feed.  The lsn guard keeps the on-disk sequence dense
            # through re-SYNC overlaps; noop fillers absorb any lsns the
            # feed's cursor coalescing skipped.  Outside self._lock —
            # the primary's append path orders repl-log before WAL too.
            for rec in fresh:
                if int(rec.get("lsn", 0)) > wal.last_lsn:
                    wal.append(rec)
        if seal:
            self._write_snapshot(force=True)
        for eng in sealed:
            eng._write_snapshot(force=True)
        P.send_msg(sock, P.MSG_OK, {"applied_lsn": applied})

    def _on_repl_promote(self, sock, header) -> None:
        if self.role == "primary":
            P.send_msg(sock, P.MSG_OK,
                       {"promoted": False, "term": int(self.term),
                        "detail": "already primary"})
            return
        if self._try_promote(force=bool(header.get("force"))):
            P.send_msg(sock, P.MSG_OK,
                       {"promoted": True, "term": int(self.term)})
        else:
            P.send_msg(sock, P.MSG_ERROR, self._standby_refusal())

    # ------------------------------------------------------------ the cache
    def _gen_layers_locked(self, epoch: int):
        """The cascade that applies to ``epoch`` (None for every other
        epoch — layers describe ONE epoch's partial consumption)."""
        if self.layers and epoch == self.elastic_epoch:
            return list(self.layers)
        return None

    def _orphan_len_locked(self, epoch: int) -> int:
        return sum(int(o["hi"]) - int(o["lo"]) for o in self._orphans
                   if int(o["epoch"]) == epoch)

    def _orphan_slice(self, spec: PartialShuffleSpec, o: dict):
        """Regenerate one orphan descriptor: the un-drained slice of a
        dead rank's stream in the generation it was defined."""
        s = spec.with_world(int(o["world"]))
        layers = [(int(w), int(c)) for w, c in o.get("layers") or []]
        arr = s.rank_indices(int(o["epoch"]), int(o["rank"]),
                             layers=layers or None)
        return np.asarray(arr)[int(o["lo"]):int(o["hi"])]

    def _rank_array(self, epoch: int, rank: int):
        with self._lock:
            spec = self.spec
            gen = self.generation
            layers = self._gen_layers_locked(int(epoch))
            orphans = ([dict(o) for o in self._orphans
                        if int(o["epoch"]) == int(epoch)]
                       if rank == 0 else [])
        key = (gen, int(epoch), int(rank))
        with self._gen_lock:
            arr = self._cache.get(key)
            if arr is not None:
                self._cache.move_to_end(key)
                return arr
            # cache miss → real regen work: multi-tenant daemons run it
            # through the fair-share queue so one tenant's huge regen
            # cannot starve another's (cache hits never queue, and a
            # single-tenant daemon has no scheduler — zero new cost)
            sched = self._regen_sched
            slot = (sched.slot(self.tenant_id, cost=self._regen_cost(),
                               clock=time.perf_counter)
                    if sched is not None else nullcontext())
            extra = ({"tenant": self.tenant_id} if sched is not None
                     else {})
            with slot:
                # t0 after the queue wait: epoch_regen_ms stays a pure
                # regen timing (queue time lands in regen_queue_ms)
                t0 = time.perf_counter()
                with _span("server.epoch_regen", epoch=int(epoch),
                           rank=int(rank), generation=gen, **extra):
                    with self.metrics.regen_timer.measure():
                        arr = np.asarray(spec.rank_indices(epoch, rank,
                                                           layers=layers))
                        if orphans:
                            # dead ranks' un-drained allocations ride as
                            # a prefix of rank 0's stream — every index
                            # still served once
                            parts = [self._orphan_slice(spec, o)
                                     for o in orphans]
                            arr = np.concatenate(parts + [arr])
            self.metrics.registry.histogram("epoch_regen_ms").observe(
                (time.perf_counter() - t0) * 1e3)
            arr.setflags(write=False)
            self._cache[key] = arr
            while len(self._cache) > self._max_cached:
                self._cache.popitem(last=False)
            return arr

    # --------------------------------------------------------------- accept
    def _on_accept_tick(self) -> None:
        # DispatchListener hook: the accept timeout is the sweep tick
        self._sweep_leases()

    def _sweep_leases(self) -> None:
        """Evict ranks whose connection went silent past the lease timeout
        and close their sockets (frees the rank AND unblocks the reader)."""
        now = self._clock()
        to_close = []
        with self._lock:
            for rank, lease in self._leases.items():
                owner = lease.get("owner")
                if owner is None:
                    continue
                if now - lease["last_seen"] > self.heartbeat_timeout:
                    lease["owner"] = None
                    self._vacated.setdefault(rank, now)
                    self._repl_append("lease_release", rank=rank)
                    self.metrics.inc("evictions", rank)
                    # eviction ends the rank's tenure: archive its
                    # per-client counters (AFTER counting the eviction,
                    # so the archive includes it)
                    self.metrics.drop_client(rank)
                    sock = self._conn_socks.get(owner)
                    if sock is not None:
                        to_close.append(sock)
        for sock in to_close:
            try:
                sock.close()
            except OSError:
                pass
        self._sweep_membership(now)
        for eng in self._engines():
            # tenant engines have no accept loop of their own; the front
            # server's tick drives their eviction and membership sweeps
            eng._sweep_leases()

    def _sweep_membership(self, now: float) -> None:
        """Elastic liveness, on the accept-loop tick: convert dead drain
        participants (grace expired, or vacant past ``membership_timeout``)
        to orphans, commit a fully-drained barrier whose committing request
        died mid-flight, and trigger the eviction reshard for ranks vacant
        past ``membership_timeout`` — so a drain can never deadlock on a
        preempted host and a permanently-lost rank shrinks the world."""
        if self.role == "standby":
            # a standby mirrors the primary's decisions; it must not
            # commit or trigger barriers of its own until promoted
            return
        trigger = None
        committed = False
        with self._lock:
            rs = self._reshard
            if rs is not None and rs.get("phase") == "drain":
                dead0 = len(rs["dead"])
                for r in rs["targets"]:
                    if r in rs["drained"] or r in rs["dead"]:
                        continue
                    deadline = rs["leaving"].get(r)
                    if deadline is not None and now >= deadline:
                        rs["dead"].add(r)
                        continue
                    lease = self._leases.get(r)
                    vacant = lease is None or lease.get("owner") is None
                    if (vacant and self.membership_timeout is not None
                            and r in self._vacated
                            and now - self._vacated[r]
                            > self.membership_timeout):
                        rs["dead"].add(r)
                try:
                    committed = self._commit_reshard_locked()
                except F.InjectedThreadDeath:
                    raise
                except Exception:  # lint: allow-broad-except(injected commit fault; retried)
                    pass
                if not committed and len(rs["dead"]) > dead0:
                    self._repl_append("state",
                                      state=self._state_dict_locked())
            elif (rs is None and self.membership_timeout is not None
                    and self.spec.world > 1 and not self._draining.is_set()):
                gone = {
                    r for r, t0 in self._vacated.items()
                    if r < self.spec.world
                    and now - t0 > self.membership_timeout
                    and (self._leases.get(r) is None
                         or self._leases[r].get("owner") is None)
                }
                if gone:
                    trigger = (max(1, self.spec.world - len(gone)), gone)
        if committed:
            self._write_snapshot(force=True)
        if trigger is not None:
            try:
                self._trigger_reshard(trigger[0], dead=trigger[1])
            except F.InjectedThreadDeath:
                raise
            except Exception:  # lint: allow-broad-except(injected trigger fault; sweep re-arms)
                pass

    # ------------------------------------------------------- per-connection
    # the serve loop itself lives in DispatchListener (service/dispatch.py);
    # these hooks bind it to tenant routing, batch timing and lease release
    def _conn_engine(self, conn_id: int) -> "IndexServer":
        return (self._conn_tenant.get(conn_id, self)
                if self.multi_tenant else self)

    def _span_extra(self, eng) -> dict:
        return {"tenant": eng.tenant_id} if self.multi_tenant else {}

    def _observe_dispatch(self, eng, msg, t0: float) -> None:
        if msg == P.MSG_GET_BATCH:
            eng.metrics.registry.histogram(
                "batch_service_ms"
            ).observe((time.perf_counter() - t0) * 1e3)

    def _conn_cleanup(self, conn_id: int) -> None:
        teng = self._conn_tenant.pop(conn_id, None)
        if teng is not None:
            teng._release_conn(conn_id)
        self._release_conn(conn_id)

    def _release_conn(self, conn_id: int) -> None:
        """A closed connection releases its leases at once — a crashed
        client's replacement must not wait out the heartbeat timeout."""
        with self._lock:
            self._conn_socks.pop(conn_id, None)
            for rank, lease in self._leases.items():
                if lease.get("owner") == conn_id:
                    lease["owner"] = None
                    self._vacated.setdefault(rank, self._clock())
                    self._repl_append("lease_release", rank=rank)

    def _touch(self, rank: int, lease: dict) -> None:
        now = self._clock()
        if now - lease["last_seen"] > self.heartbeat_timeout:
            # the client went silent past the lease but came back before
            # anything evicted it — a heartbeat gap worth counting
            self.metrics.inc("heartbeat_gaps", rank)
        lease["last_seen"] = now

    def _dispatch(self, sock, conn_id, msg, header, payload) -> None:
        if self._draining.is_set():
            # graceful drain: answer every request arriving during the
            # stop() window with a structured "retry shortly" instead of
            # letting the imminent socket close read as a raw reset
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "draining",
                "detail": "server is stopping; reconnect shortly",
                "retry_ms": self.backpressure.retry_ms("draining"),
            })
            return
        if msg == P.MSG_REPL_SYNC:
            self._on_repl_sync(sock, header)
            return
        if msg == P.MSG_REPL_APPEND:
            self._on_repl_append(sock, header)
            return
        if msg == P.MSG_REPL_PROMOTE:
            self._on_repl_promote(sock, header)
            return
        if msg in _MUTATING_MSGS:
            if self.role == "standby":
                # a failover HELLO may promote (once the feed is stale);
                # everything else is refused until the promotion
                if not (msg == P.MSG_HELLO and header.get("failover")
                        and self._try_promote()):
                    P.send_msg(sock, P.MSG_ERROR, self._standby_refusal())
                    return
            refusal = self._term_refusal(header)
            if refusal is not None:
                _annotate(error_code="fenced")
                P.send_msg(sock, P.MSG_ERROR, refusal)
                return
            if self._cell_frozen.is_set() and msg != P.MSG_HELLO:
                # migration cutover freeze (docs/FEDERATION.md): the
                # same retryable refusal a reshard barrier uses, so the
                # client's existing retry arm pauses through the flip;
                # HELLO stays live — a redirected client must be able
                # to land and learn the post-flip directory
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "reshard",
                    "phase": "cell_freeze",
                    "retry_ms": self.backpressure.retry_ms("reshard_freeze"),
                    "detail": "cell cutover in progress; retry shortly",
                })
                return
        # tenant routing: the connection's HELLO binding wins; an
        # explicit additive ``tenant`` header field (mirroring ``trace``)
        # can name the namespace when a connection serves ops traffic
        engine = self
        if self._conn_tenant or self._tenant_by_id:
            engine = self._conn_tenant.get(conn_id, self)
            tid = header.get("tenant")
            if tid is not None:
                engine = self._tenant_by_id.get(str(tid), engine)
        if msg == P.MSG_HELLO:
            self._on_hello(sock, conn_id, header)
        elif msg == P.MSG_GET_BATCH:
            engine._on_get_batch(sock, conn_id, header)
        elif msg == P.MSG_SET_EPOCH:
            engine._on_set_epoch(sock, header)
        elif msg == P.MSG_HEARTBEAT:
            engine._on_heartbeat(sock, conn_id, header)
        elif msg == P.MSG_GET_CAPABILITY:
            engine._on_get_capability(sock, conn_id, header)
        elif msg == P.MSG_APPEND:
            engine._on_append(sock, header)
        elif msg == P.MSG_SNAPSHOT:
            engine._write_snapshot(force=True)
            P.send_msg(sock, P.MSG_SNAPSHOT_STATE,
                       {"state": engine._state_dict()})
        elif msg == P.MSG_METRICS:
            # a tenant-bound connection reads its own scoped report —
            # isolation; the front's report carries the tenant rollup
            P.send_msg(sock, P.MSG_METRICS_REPORT,
                       {"report": engine.metrics.report()})
        elif msg == P.MSG_LEAVE:
            engine._on_leave(sock, conn_id, header)
        elif msg == P.MSG_RESHARD:
            engine._on_reshard(sock, conn_id, header)
        elif msg == P.MSG_TRACE_DUMP:
            limit = int(header.get("limit", 256))
            entries = telemetry.snapshot(limit)
            if self.multi_tenant:
                # trace isolation: tenant-tagged spans of OTHER tenants
                # never leak into this connection's dump (untagged
                # entries are shared-infrastructure and stay visible)
                own = engine.tenant_id
                entries = [e for e in entries
                           if (e.get("attrs") or {}).get("tenant")
                           in (None, own)]
            P.send_msg(sock, P.MSG_TRACE_REPORT, {
                "enabled": telemetry.enabled(),
                "entries": entries,
            })
        else:
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "unknown_type",
                "detail": f"message type {P.msg_name(msg)} not served",
            })

    def _on_set_epoch(self, sock, header) -> None:
        delta = header.get("weights_delta")
        folded = None
        with self._lock:
            if delta is not None:
                # prioritized re-weighting (docs/SAMPLING.md): the
                # additive delta folds into the weights EFFECTIVE at the
                # new epoch — the streaming advance's fold law applied
                # at an epoch boundary.  Weights stay >= 1 so no source
                # is silently starved to zero by a large negative delta.
                if (not self.sampling
                        or getattr(self.spec, "sampling_mode", None)
                        != "prioritized"):
                    P.send_msg(sock, P.MSG_ERROR, {
                        "code": "bad_request",
                        "detail": "weights_delta requires a prioritized "
                                  "sampling spec",
                    })
                    return
                new_epoch = int(header.get("epoch", 0))
                base = self.spec.effective_weights(new_epoch)
                if len(delta) != len(base):
                    P.send_msg(sock, P.MSG_ERROR, {
                        "code": "bad_request",
                        "detail": f"weights_delta has {len(delta)} "
                                  f"entries for {len(base)} sources",
                    })
                    return
                from ..streaming.spec import WEIGHTS_RETAIN

                folded = tuple(max(1, int(a) + int(b))
                               for a, b in zip(base, delta))
                self.spec = self.spec.with_stream_weights(
                    {new_epoch: folded},
                    prune_below=new_epoch - WEIGHTS_RETAIN // 2)
                self.metrics.inc("sampling_reweights")
            self.epoch = int(header.get("epoch", 0))
            if folded is not None:
                self._repl_append("sampling", epoch=self.epoch,
                                  weights=[int(x) for x in folded])
            else:
                self._repl_append("epoch", epoch=self.epoch)
        self._write_snapshot(force=True)
        reply = {"epoch": self.epoch}
        if folded is not None:
            reply["weights"] = [int(x) for x in folded]
        P.send_msg(sock, P.MSG_OK, reply)

    def _ack_advance_locked(self, rank: int, lease: dict, epoch, ack) -> bool:
        """Advance ``rank``'s delivered-ack cursor for ``epoch`` and, if
        that satisfies a drain barrier's ack gate, complete the rank's
        drain.  Returns True when this ack committed the barrier.
        Shared by HEARTBEAT and the ``hb`` field piggybacked on
        GET_BATCH/HEARTBEAT; caller holds ``self._lock``."""
        committed = False
        cur = self._cursors.get(rank)
        if cur is None or cur["epoch"] != int(epoch):
            return False
        cur["acked"] = max(cur["acked"], int(ack))
        rec = self._cap_records.get(rank)
        if (rec is not None and int(rec["epoch"]) == int(epoch)
                and int(rec["gen"]) == self.generation):
            # capability-mode rank: no batches flow, so the served-
            # samples watermark an elastic barrier cuts on is maintained
            # from the acks, with a slack of ``max_inflight`` batches —
            # the client never locally delivers further past its last
            # flushed ack (docs/CAPABILITY.md "Drain law"), so the
            # barrier C covers every sample it may have consumed
            b = int(lease.get("batch") or 0)
            slack = min((cur["acked"] + 1 + self.max_inflight) * b,
                        int(rec["total"]))
            cur["samples"] = max(int(cur.get("samples", 0)), slack)
        self._repl_append("cursor", rank=rank, **cur)
        rs = self._reshard
        if (rs is not None and rs.get("phase") == "drain"
                and int(epoch) == rs["epoch"]
                and rank in rs["targets"]
                and rank not in rs["drained"]
                and (cur["acked"] + 1) * int(lease.get("batch") or 0)
                >= int(rs["targets"][rank])):
            rs["drained"].add(rank)
            try:
                committed = self._commit_reshard_locked()
            except F.InjectedThreadDeath:
                raise
            except Exception:  # lint: allow-broad-except(injected commit fault; retried)
                pass
            if not committed:
                self._repl_append("state", state=self._state_dict_locked())
        return committed

    def _apply_piggyback_ack(self, conn_id, rank, hb) -> None:
        """Apply a piggybacked ``hb: [epoch, ack]`` header field — a
        delivered-ack cursor for an epoch OTHER than the one the
        carrying request is about (typically the previous epoch's
        terminal ack, deferred by the pipelined client instead of a
        dedicated EOF poll).  Re-application is idempotent (the cursor
        is a max), so a retried request may carry the same ``hb``."""
        if hb is None or rank is None:
            return
        try:
            hb_epoch, hb_ack = int(hb[0]), int(hb[1])
        except (TypeError, ValueError, IndexError):
            return  # malformed piggyback: ignore, the request stands alone
        committed = False
        with self._lock:
            lease = self._leases.get(int(rank))
            if lease is not None and lease.get("owner") == conn_id:
                committed = self._ack_advance_locked(
                    int(rank), lease, hb_epoch, hb_ack)
        if committed:
            self._write_snapshot(force=True)

    def _on_heartbeat(self, sock, conn_id, header) -> None:
        """Keepalive, optionally carrying the client's delivered-ack
        cursor (``epoch`` + ``ack``).  The ack matters during a drain:
        the barrier commits on ACKED delivery, and a participant that
        stopped pulling batches (idle at its watermark when the barrier
        froze) would otherwise never deliver the final ack that
        completes its drain."""
        rank = header.get("rank")
        self._apply_piggyback_ack(conn_id, rank, header.get("hb"))
        committed = False
        with self._lock:
            lease = self._leases.get(int(rank)) if rank is not None \
                else None
            if lease is not None and lease.get("owner") == conn_id:
                rank = int(rank)
                self._touch(rank, lease)
                ack, epoch = header.get("ack"), header.get("epoch")
                if ack is not None and epoch is not None:
                    committed = self._ack_advance_locked(
                        rank, lease, epoch, ack)
            gen = self.generation
            reply = {"generation": gen}
            kn = self._knob_fields()
            if kn:
                # additive: autopilot-tuned knobs ride the keepalive so
                # live clients adopt them without reconnecting; absent
                # until a controller first touches one, so a disabled
                # autopilot costs zero protocol bytes (docs/AUTOPILOT.md)
                reply["knobs"] = kn
            rs = self._reshard
            rec = (self._cap_records.get(int(rank))
                   if rank is not None else None)
            if (rec is not None and rs is not None
                    and rs.get("phase") == "drain"
                    and int(rank) in rs["targets"]
                    and int(rec["epoch"]) == int(rs["epoch"])):
                # a batchless capability stream discovers its drain
                # clamp here (served-batch clients get it from the
                # GET_BATCH clamp instead): additive field, absent
                # outside a drain (docs/CAPABILITY.md "Drain law")
                reply["cap_drain"] = {
                    "epoch": int(rs["epoch"]),
                    "target_samples": int(rs["targets"][int(rank)]),
                }
        if committed:
            self._write_snapshot(force=True)
        P.send_msg(sock, P.MSG_OK, reply)

    # ----------------------------------------------------------- capability
    def _capability_locked(self, epoch: int) -> EpochCapability:
        """The signed grant for the CURRENT membership — one HMAC over
        the canonical encoding (docs/CAPABILITY.md).  Under
        ``self._lock``."""
        extra = {}
        if self.streaming or self.sampling:
            # the effective weights ride the grant — horizon mixture
            # weights (docs/STREAMING.md) or adopted prioritized
            # sampling weights (docs/SAMPLING.md): regen on the client
            # substitutes them before evaluating, so a re-weighted
            # stream folds bit-identically on device.  Absent for
            # plain-base streams, for static sampling specs, and for
            # every frozen-dataset grant (old grants verify unchanged).
            w = self.spec.weights_for(int(epoch))
            if w is not None:
                extra["stream_weights"] = tuple(int(x) for x in w)
        secret = self.capability_secret
        if hasattr(secret, "current"):
            # federated issuance (docs/FEDERATION.md): the secret is a
            # CellKeyring — the cell + key id ride INSIDE the signed
            # bytes, so a promoted DR cell can keep honoring this grant
            # while a retired key fails verification loudly
            kid, secret = secret.current()
            extra["cell"] = (self.cell_id
                             or getattr(self.capability_secret,
                                        "cell_id", None))
            extra["kid"] = int(kid)
        return EpochCapability(
            fingerprint=self.spec.fingerprint(include_world=False),
            epoch=int(epoch),
            seed=int(self.spec.seed),
            generation=int(self.generation),
            world=int(self.spec.world),
            layers=tuple((int(w), int(c)) for w, c in self.layers),
            elastic_epoch=self.elastic_epoch,
            orphans=tuple(dict(o) for o in self._orphans),
            tenant=self.tenant_id,
            **extra,
        ).signed(secret)

    def _on_get_capability(self, sock, conn_id, header) -> None:
        """Issue a signed epoch capability (docs/CAPABILITY.md): the
        client regenerates its indices on-device and reports only ack
        watermarks, so issuance must create the rank's epoch cursor (an
        ack against a missing cursor is dropped by
        :meth:`_ack_advance_locked`, which would stall drain barriers)
        and persist an issued-capability record so a restarted or
        promoted daemon keeps honoring the grant."""
        try:
            rank = int(header["rank"])
            epoch = int(header["epoch"])
        except (KeyError, TypeError, ValueError):
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "bad_request",
                        "detail": "GET_CAPABILITY needs rank/epoch ints"})
            return
        if self.capability_secret is None:
            # terminal by design: an unsigned grant would let any client
            # forge membership, so a secretless daemon only serves the
            # batch path — and puts zero capability bytes on the wire
            _annotate(error_code="capability_unsupported")
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "capability_unsupported",
                "detail": "this daemon has no capability_secret "
                          "configured; use the served-batch path",
            })
            return
        try:
            F.fire("capability.issue")
        except F.InjectedThreadDeath:
            raise
        except Exception as exc:
            self.metrics.inc("capability_rejects", rank)
            _annotate(error_code="capability_issue")
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "capability_issue",
                "retry_ms": self.backpressure.retry_ms("capability_issue"),
                "detail": f"capability issuance refused ({exc!r}); retry",
            })
            return
        t0 = time.perf_counter()
        advanced = False
        with self._lock:
            lease = self._leases.get(rank)
            if lease is None or lease.get("owner") != conn_id:
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "not_owner",
                    "detail": f"rank {rank} is not leased to this "
                              "connection; HELLO first",
                })
                return
            self._touch(rank, lease)
            if self.streaming:
                # eligibility + ack-gated advance, BEFORE any cursor
                # mutation (docs/STREAMING.md): a refused request leaves
                # the stream state exactly as it found it
                refusal, advanced = self._stream_gate_locked(epoch)
                if refusal is not None:
                    P.send_msg(sock, P.MSG_ERROR, refusal)
                    return
            rs = self._reshard
            if rs is not None and rs.get("phase") == "freeze":
                # a grant issued mid-freeze could outrun the watermark
                # snapshot the freeze took; refuse like GET_BATCH does
                _annotate(error_code="reshard")
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "reshard",
                    "retry_ms":
                        self.backpressure.retry_ms("reshard_freeze"),
                    "detail": "reshard barrier is freezing; retry shortly",
                })
                return
            cur_gen = self.generation
        if advanced:
            # the horizon advance this request committed seals a forced
            # checkpoint (outside the lock — the writer retakes it) so
            # the WAL truncates below the new watermark
            self._stream_advanced(t0)
        # the rank's total (rank 0's orphan prefix included) anchors the
        # consumption slack; _rank_array takes self._lock, so this MUST
        # stay outside it
        total = int(self._rank_array(epoch, rank).shape[0])
        with self._lock:
            if self.generation != cur_gen:
                # a sweep committed a barrier while we computed: the
                # retry is issued against the fresh membership
                _annotate(error_code="reshard")
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "reshard",
                    "retry_ms":
                        self.backpressure.retry_ms("reshard_freeze"),
                    "detail": "reshard committed mid-issuance; retry",
                })
                return
            cur = self._cursors.get(rank)
            if cur is None or cur["epoch"] != epoch:
                cur = self._cursors[rank] = {"epoch": epoch, "acked": -1,
                                             "hi": -1, "samples": 0}
            batch = int(lease.get("batch") or 0)
            if self.streaming:
                # capability-mode ranks serve no slices, so the advance
                # barrier's per-rank target is pinned at issuance; the
                # ack-to-samples batch rides along for post-lease gating
                cur["total"] = int(total)
                cur["batch"] = batch
            # consumption floor: the client may locally deliver up to
            # max_inflight batches before its first ack flush, and a
            # barrier freezing in that window must still cover them
            floor = min((cur["acked"] + 1 + self.max_inflight) * batch,
                        total)
            cur["samples"] = max(int(cur.get("samples", 0)), floor)
            rec = {"epoch": epoch, "gen": cur_gen, "total": total}
            self._cap_records[rank] = rec
            self._repl_append("capability", rank=rank, **rec)
            self._repl_append("cursor", rank=rank, **cur)
            # the slot's acked cursor rides every grant: a new lease
            # holder adopting a partly-served slot (a vacated rank
            # mid-drain, a takeover after a client death) must resume
            # regeneration at acked+1, not replay from seq 0 — the
            # capability-mode half of the double-delivery guard
            hdr = {"capability": self._capability_locked(epoch).to_wire(),
                   "ack": int(cur["acked"]),
                   **self._membership_locked()}
            rs = self._reshard
            if (rs is not None and rs.get("phase") == "drain"
                    and epoch == rs["epoch"] and rank in rs["targets"]):
                hdr["target_samples"] = int(rs["targets"][rank])
            stale = (header.get("gen") is not None
                     and int(header["gen"]) != cur_gen)
        self.metrics.registry.histogram("capability_issue_ms").observe(
            (time.perf_counter() - t0) * 1e3)
        if stale:
            # revocation surface: the request named a revoked
            # generation — the typed retryable error carries the FRESH
            # capability, so adopting and resuming costs no second trip
            self.metrics.inc("capability_stale", rank)
            _annotate(error_code="capability_stale")
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "capability_stale",
                "retry_ms": self.backpressure.retry_ms("capability_stale"),
                "detail": f"generation {header.get('gen')} was revoked "
                          f"(now at {cur_gen}); adopt the attached "
                          "membership and capability",
                **hdr,
            })
            return
        self.metrics.inc("capabilities_issued", rank)
        self._write_snapshot()
        P.send_msg(sock, P.MSG_CAPABILITY, hdr)

    # ------------------------------------------------- elastic membership
    def _membership_locked(self) -> dict:
        """The fields a client needs to adopt the current membership —
        rides in WELCOME and in every ``resharded`` error."""
        return {
            "generation": self.generation,
            "world": self.spec.world,
            "epoch": self.epoch,
            "layers": [[int(w), int(c)] for w, c in self.layers],
            "elastic_epoch": self.elastic_epoch,
            "orphans": [dict(o) for o in self._orphans],
            "vacated": sorted(int(r) for r in self._vacated),
        }

    def _resharded_err_locked(self, detail: str) -> dict:
        return {"code": "resharded", "detail": detail,
                **self._membership_locked()}

    def _consumption_locked(self, epoch: int, world: int):
        """Per-rank ``(samples, covered)`` watermarks at ``epoch`` —
        samples served vs samples ACKED delivered.  Under ``self._lock``."""
        samples = {
            r: (int(self._cursors[r].get("samples", 0))
                if r in self._cursors
                and self._cursors[r]["epoch"] == epoch else 0)
            for r in range(world)
        }
        covered = {}
        for r in range(world):
            cur = self._cursors.get(r)
            b = int(self._leases.get(r, {}).get("batch") or 0)
            covered[r] = (
                (int(cur["acked"]) + 1) * b
                if cur is not None and cur["epoch"] == epoch and b > 0
                else 0
            )
        return samples, covered

    def _unit_watermarks(self, epoch: int, world: int, layers,
                         orphan_len: int, samples: dict):
        """Per-rank unit cumsums (shard mode) and whole base units
        STARTED: sample ``s-1`` lives in unit ``u-1``.  Call OUTSIDE the
        lock — shard mode may regenerate the epoch's shard draws."""
        shard = self.spec.mode == "shard"
        cums = {}
        if shard:
            for r in range(world):
                sizes = np.asarray(self.spec.rank_unit_sizes(
                    epoch, r, layers=layers), dtype=np.int64)
                cums[r] = np.concatenate(([0], np.cumsum(sizes)))
        units = {}
        for r in range(world):
            s = max(0, samples[r] - (orphan_len if r == 0 else 0))
            units[r] = (int(np.searchsorted(cums[r], s, side="left"))
                        if shard else s)
        return cums, units

    def _reshard_prepare(self, target_world: int):
        """Phase 1 of a cross-shard barrier (docs/SHARDING.md): freeze
        serving and report this server's consumption maximum in whole
        base units.  Unlike :meth:`_trigger_reshard`, the frozen barrier
        does NOT flip to drain — the coordinating router gathers every
        shard's maximum, takes the global max ``C``, and imposes it via
        :meth:`_reshard_commit_prepared` (or unfreezes the abandoned
        prepare via :meth:`_reshard_abort_prepared`).  Returns ``None``
        when another reshard is already in flight, else
        ``{"epoch", "world", "units_max"}``."""
        F.fire("server.reshard")
        target_world = int(target_world)
        if target_world < 1:
            raise ValueError(f"target_world must be >= 1, got {target_world}")
        t_freeze = time.perf_counter()
        with self._lock:
            if self._reshard is not None or self._draining.is_set():
                return None
            world = self.spec.world
            epochs = [c["epoch"] for c in self._cursors.values()]
            epoch = max(epochs) if epochs else self.epoch
            self._reshard = {"phase": "freeze",
                             "target_world": target_world, "epoch": epoch}
            layers = self._gen_layers_locked(epoch)
            orphan_len = self._orphan_len_locked(epoch)
            samples, covered = self._consumption_locked(epoch, world)
        try:
            _cums, units = self._unit_watermarks(epoch, world, layers,
                                                 orphan_len, samples)
            with self._lock:
                rs = self._reshard
                if rs is None:  # aborted while we computed
                    return None
                # in-memory scratch only: _state_dict_locked persists
                # drain-phase barriers, so a daemon crashed mid-prepare
                # restarts unfrozen and the router simply retries
                rs["prep"] = {"epoch": int(epoch), "world": int(world),
                              "covered": covered, "t_freeze": t_freeze}
            return {"epoch": int(epoch), "world": int(world),
                    "units_max": int(max(units.values(), default=0))}
        except BaseException:
            # a failed prepare must unfreeze, or every future GET_BATCH
            # draws an endless retry and the shard is bricked
            with self._lock:
                self._reshard = None
            telemetry.auto_dump("reshard_abort")
            raise

    def _reshard_commit_prepared(self, barrier_units: int, *,
                                 participants=None, dead=None,
                                 leaving=None) -> bool:
        """Phase 2 of a cross-shard barrier: set per-rank drain targets
        from the imposed GLOBAL barrier ``C`` and flip the prepared
        freeze to drain.  ``participants`` restricts the drain gate to
        the ranks this server actually serves (its shard slice);
        ``dead`` adds coordinator-declared dead ranks whose un-served
        allocation is re-homed here as orphan descriptors (the router
        sends those only to the shard owning rank 0, where orphan
        prefixes are served).  The commit itself then proceeds exactly
        as a local reshard — whichever request or sweep observes the
        last drain wins.  Returns False when no prepared barrier is in
        flight."""
        barrier = int(barrier_units)
        with self._lock:
            rs = self._reshard
            if (rs is None or rs.get("phase") != "freeze"
                    or "prep" not in rs):
                return False
            prep = rs["prep"]
            epoch, world = prep["epoch"], prep["world"]
            layers = self._gen_layers_locked(epoch)
            orphan_len = self._orphan_len_locked(epoch)
        # shard-mode cumsums regenerate draws — outside the lock (the
        # prepared freeze pauses serving, so watermarks cannot move)
        shard = self.spec.mode == "shard"
        cums = {}
        if shard:
            for r in range(world):
                sizes = np.asarray(self.spec.rank_unit_sizes(
                    epoch, r, layers=layers), dtype=np.int64)
                cums[r] = np.concatenate(([0], np.cumsum(sizes)))
        ranks = sorted(
            {int(r) for r in (participants if participants is not None
                              else range(world))}
            | {int(r) for r in (dead or ())}
        )
        with self._lock:
            rs = self._reshard
            if rs is None or rs.get("phase") != "freeze":
                return False
            covered = prep["covered"]
            targets = {}
            now = self._clock()
            for r in ranks:
                t = int(cums[r][barrier]) if shard else barrier
                if r == 0:
                    t += orphan_len
                targets[r] = t
                lease = self._leases.get(r)
                if lease is None or lease.get("owner") is None:
                    self._vacated.setdefault(r, now)
            rs.pop("prep", None)
            rs.update(
                phase="drain",
                barrier_units=barrier,
                targets=targets,
                drained={r for r in ranks
                         if r not in set(dead or ()) and
                         covered.get(r, 0) >= targets[r]},
                leaving=dict(leaving or {}),
                dead={int(r) for r in (dead or ())},
            )
            rs["t_drain"] = time.perf_counter()
            self.metrics.inc("reshard_triggers")
            # the freeze→drain flip ships wholesale: the standby
            # applies barriers with the snapshot-restore code path
            self._repl_append("state", state=self._state_dict_locked())
        self.metrics.registry.histogram("barrier_freeze_ms").observe(
            (rs["t_drain"] - prep["t_freeze"]) * 1e3)
        telemetry.event("reshard_drain",
                        target_world=int(rs["target_world"]),
                        barrier_units=barrier)
        with self._lock:
            try:
                self._commit_reshard_locked()
            except F.InjectedThreadDeath:
                raise
            except Exception:  # lint: allow-broad-except(injected commit fault; retried)
                pass
        self._write_snapshot(force=True)
        return True

    def _reshard_abort_prepared(self) -> bool:
        """Unfreeze a prepared (phase-1) barrier the coordinator
        abandoned — e.g. a sibling shard refused its prepare.  A
        drain-phase barrier is never aborted here: it is already
        replicated and will commit through the normal drain path."""
        with self._lock:
            rs = self._reshard
            if rs is None or rs.get("phase") != "freeze":
                return False
            self._reshard = None
        telemetry.event("reshard_prepare_aborted")
        return True

    def _trigger_reshard(self, target_world: int, *, leaving=None,
                         dead=None) -> bool:
        """Freeze a reshard barrier and enter the drain phase.

        The barrier ``C`` is the max over all ranks' consumption
        watermarks converted to whole base units (samples, or SHARDS for
        shard mode — a barrier must cut on whole shards so the remainder
        expansion is exactly the expansion of the remainder shard IDs).
        Ranks behind ``C`` keep being served their old partition, clamped
        to their per-rank sample target; ranks at it wait out the commit.
        A rank counts as drained only up to its ACKED delivery — the
        served watermark may lead it by one lost-in-flight reply, and
        that span must stay resendable past the commit.
        Returns False when another reshard is already in flight."""
        F.fire("server.reshard")
        target_world = int(target_world)
        if target_world < 1:
            raise ValueError(f"target_world must be >= 1, got {target_world}")
        t_freeze = time.perf_counter()
        with self._lock:
            if self._reshard is not None or self._draining.is_set():
                return False
            world = self.spec.world
            epochs = [c["epoch"] for c in self._cursors.values()]
            # barrier at the epoch consumption is actually happening on
            # (ranks advance epochs together — docs/RESILIENCE.md)
            epoch = max(epochs) if epochs else self.epoch
            self._reshard = {"phase": "freeze",
                             "target_world": target_world, "epoch": epoch}
            layers = self._gen_layers_locked(epoch)
            orphan_len = self._orphan_len_locked(epoch)
            samples, covered = self._consumption_locked(epoch, world)
        try:
            # unit structure may regenerate shard draws — outside the lock
            # (the freeze phase pauses serving, so watermarks cannot move:
            # new requests are refused at admission, and a request already
            # past admission is refused at its counting tail)
            shard = self.spec.mode == "shard"
            cums, units = self._unit_watermarks(epoch, world, layers,
                                                orphan_len, samples)
            barrier = max(units.values(), default=0)
            with self._lock:
                rs = self._reshard
                targets = {}
                now = self._clock()
                for r in range(world):
                    t = int(cums[r][barrier]) if shard else int(barrier)
                    if r == 0:
                        t += orphan_len
                    targets[r] = t
                    lease = self._leases.get(r)
                    if lease is None or lease.get("owner") is None:
                        # a participant with no live lease at the barrier
                        # goes on the membership_timeout clock NOW — a
                        # rank that never connected at all would otherwise
                        # never be declared dead and stall the drain
                        self._vacated.setdefault(r, now)
                rs.update(
                    phase="drain",
                    barrier_units=int(barrier),
                    targets=targets,
                    drained={r for r in range(world)
                             if r not in set(dead or ()) and
                             covered[r] >= targets[r]},
                    leaving=dict(leaving or {}),
                    dead=set(dead or ()),
                )
                rs["t_drain"] = time.perf_counter()
                self.metrics.inc("reshard_triggers")
                # the freeze→drain flip ships wholesale: the standby
                # applies barriers with the snapshot-restore code path
                self._repl_append("state", state=self._state_dict_locked())
            self.metrics.registry.histogram("barrier_freeze_ms").observe(
                (rs["t_drain"] - t_freeze) * 1e3)
            telemetry.event("reshard_drain", target_world=target_world,
                            barrier_units=int(barrier))
        except BaseException:
            # any failure between the freeze and the drain flip (shard
            # regen, target computation) must unfreeze, or every future
            # GET_BATCH draws an endless retry and the server is bricked
            with self._lock:
                self._reshard = None
            telemetry.auto_dump("reshard_abort")
            raise
        with self._lock:
            try:
                self._commit_reshard_locked()
            except F.InjectedThreadDeath:
                raise
            except Exception:  # lint: allow-broad-except(injected commit fault; retried)
                pass
        self._write_snapshot(force=True)
        return True

    def _clip_orphans_locked(self, rank: int, lo: int, hi: int, world: int,
                             layers, epoch: int) -> list[dict]:
        """Descriptors for a dead rank's un-served span ``[lo, hi)`` of its
        current-generation stream.  Rank 0's stream is composite (orphan
        prefix + partition), so the span decomposes into clips of the old
        descriptors plus a partition descriptor — each over a PURE stream
        of some earlier generation, hence regenerable forever."""
        out: list[dict] = []
        off = 0
        if rank == 0:
            for o in self._orphans:
                if int(o["epoch"]) != epoch:
                    continue
                ln = int(o["hi"]) - int(o["lo"])
                a, b = max(lo, off), min(hi, off + ln)
                if a < b:
                    out.append({**o, "lo": int(o["lo"]) + a - off,
                                "hi": int(o["lo"]) + b - off})
                off += ln
        plo, phi = max(lo - off, 0), hi - off
        if phi > plo:
            out.append({
                "epoch": int(epoch), "rank": int(rank), "world": int(world),
                "layers": [[int(w), int(c)] for w, c in layers or []],
                "lo": int(plo), "hi": int(phi),
            })
        return out

    def _commit_reshard_locked(self) -> bool:
        """Commit a fully-drained barrier: append the §6 cascade layer,
        re-partition at the target world, bump the generation.  Idempotent
        and callable from any drain participant's request or the sweep —
        whichever observes the last drain wins.  Under ``self._lock``."""
        rs = self._reshard
        if rs is None or rs.get("phase") != "drain":
            return False
        for r in rs["targets"]:
            if r not in rs["drained"] and r not in rs["dead"]:
                return False
        F.fire("server.reshard")  # before any mutation: a fault here
        # leaves the drain intact for the sweep to re-commit
        epoch = int(rs["epoch"])
        old_world = self.spec.world
        old_layers = self._gen_layers_locked(epoch) or []
        new_orphans: list[dict] = []
        for r in sorted(rs["dead"]):
            t = int(rs["targets"][r])
            cur = self._cursors.get(r)
            s = (int(cur.get("samples", 0))
                 if cur is not None and cur["epoch"] == epoch else 0)
            s = min(s, t)
            if s < t:
                new_orphans.extend(self._clip_orphans_locked(
                    r, s, t, old_world, old_layers, epoch))
        self.layers = [(int(w), int(c)) for w, c in old_layers]
        self.layers.append((old_world, int(rs["barrier_units"])))
        self.elastic_epoch = epoch
        self.spec = self.spec.with_world(int(rs["target_world"]))
        self.generation += 1
        self._orphans = new_orphans
        self._cursors = {}
        self._vacated = {}
        # revocation: every outstanding capability named the committed-
        # away generation; clients re-fetch through ``capability_stale``
        # and issuance re-populates (and re-replicates) the records
        self._cap_records = {}
        now = self._clock()
        for rank in list(self._leases):
            if rank >= self.spec.world:
                self._leases.pop(rank)
            elif rank in rs["leaving"] or rank in rs["dead"]:
                # the departed rank's slot in the NEW world must be
                # claimable (the displaced top rank rejoins into it)
                if self._leases[rank].get("owner") is not None:
                    self._leases[rank]["owner"] = None
                self._vacated[rank] = now
        self._reshard = None
        if self.streaming and epoch == self.epoch:
            # re-pin the advance barrier's per-rank targets under the
            # NEW partition (docs/STREAMING.md "Advance under reshard"):
            # post-commit arrays hold only each rank's un-delivered
            # remainder share, served from seq 0, so every cursor
            # restarts at acked=-1 with the layer-aware share as its
            # total — a rank whose share is empty passes the straggler
            # test without ever sending a request, and a rank that
            # finished the horizon pre-freeze but was dealt a share of
            # the pooled remainder blocks the advance until it re-enters
            # the horizon (rank_indices is pure spec math, so calling it
            # under the lock is deadlock-free; commits are rare)
            layers = [(int(w), int(c)) for w, c in self.layers]
            for r in range(self.spec.world):
                share = int(np.asarray(self.spec.rank_indices(
                    epoch, r, layers=layers or None)).shape[0])
                if r == 0:
                    share += self._orphan_len_locked(epoch)
                lease = self._leases.get(r) or {}
                self._cursors[r] = {
                    "epoch": epoch, "acked": -1, "hi": -1, "samples": 0,
                    "batch": int(lease.get("batch") or 0),
                    "total": share,
                }
        if new_orphans:
            self.metrics.inc("orphaned", value=sum(
                int(o["hi"]) - int(o["lo"]) for o in new_orphans))
        self.metrics.inc("reshards")
        # departed ranks' per-client counters end their tenure here: a
        # rank beyond the new world, or one that left/died at this
        # barrier, is archived so the report doesn't grow forever
        for r in range(old_world):
            if (r >= self.spec.world or r in rs["leaving"]
                    or r in rs["dead"]):
                self.metrics.drop_client(r)
        t_drain = rs.get("t_drain")
        if t_drain is not None:  # absent on a restored (snapshot) barrier
            self.metrics.registry.histogram("barrier_drain_ms").observe(
                (time.perf_counter() - t_drain) * 1e3)
        telemetry.event("reshard_commit", generation=self.generation,
                        world=self.spec.world)
        # the commit record is in the WAL before any client can observe
        # the new generation (we still hold the lock), so a standby can
        # never serve gen+1 requests against pre-commit state
        self._repl_append("state", state=self._state_dict_locked())
        return True

    def _on_leave(self, sock, conn_id, header) -> None:
        """Preemption-notice drain: the rank keeps its lease, drains its
        pre-barrier allocation, then its stream ends (a terminal EOF) and
        the world shrinks by one.  ``grace_ms`` bounds the drain — past
        it the rank is declared dead and its remainder orphaned."""
        try:
            rank = int(header["rank"])
        except (KeyError, TypeError, ValueError):
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "bad_request",
                        "detail": "LEAVE needs an int rank"})
            return
        grace_ms = header.get("grace_ms")
        deadline = (None if grace_ms is None
                    else self._clock() + float(grace_ms) / 1e3)
        with self._lock:
            lease = self._leases.get(rank)
            if lease is None or lease.get("owner") != conn_id:
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "not_owner",
                    "detail": f"rank {rank} is not leased to this "
                              "connection; HELLO first",
                })
                return
            self._touch(rank, lease)
            self.metrics.inc("leaves", rank)
            world = self.spec.world
            rs = self._reshard
            if rs is not None:
                if rs.get("phase") != "drain":
                    P.send_msg(sock, P.MSG_ERROR, {
                        "code": "reshard",
                        "retry_ms":
                            self.backpressure.retry_ms("reshard_freeze"),
                        "detail": "a reshard barrier is freezing; retry",
                    })
                    return
                # join the in-flight barrier instead of compounding a
                # second one: same targets, one fewer post-reshard rank
                if rank not in rs["leaving"]:
                    rs["leaving"][rank] = deadline
                    rs["target_world"] = max(1, int(rs["target_world"]) - 1)
                P.send_msg(sock, P.MSG_OK, {
                    "reshard": True, "generation": self.generation,
                    "target_world": rs["target_world"],
                    "target_samples": rs["targets"].get(rank),
                })
                return
            if world <= 1:
                lease["owner"] = None
                P.send_msg(sock, P.MSG_OK, {
                    "reshard": False, "generation": self.generation,
                    "detail": "world is 1; nothing to reshard down to",
                })
                return
        if not self._trigger_reshard(world - 1, leaving={rank: deadline}):
            # lost a race with a concurrent trigger; the client's retry
            # joins that barrier through the branch above
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "reshard",
                "retry_ms": self.backpressure.retry_ms("reshard_freeze"),
                "detail": "another reshard started concurrently; retry",
            })
            return
        with self._lock:
            rs = self._reshard
            hdr = {"reshard": True, "generation": self.generation,
                   "target_world": (rs["target_world"] if rs is not None
                                    else self.spec.world),
                   "target_samples": (rs["targets"].get(rank)
                                      if rs is not None else None)}
        P.send_msg(sock, P.MSG_OK, hdr)

    def _on_reshard(self, sock, conn_id, header) -> None:
        """Explicit world change.  One barrier at a time: a second
        request while one drains draws ``ERROR(code='reshard')`` and the
        retry layer waits the first one out."""
        try:
            new_world = int(header["world"])
        except (KeyError, TypeError, ValueError):
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "bad_request",
                        "detail": "RESHARD needs an int world"})
            return
        if new_world < 1:
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "bad_request",
                        "detail": f"world must be >= 1, got {new_world}"})
            return
        if not self._trigger_reshard(new_world):
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "reshard",
                "retry_ms": self.backpressure.retry_ms("reshard_conflict"),
                "detail": "a reshard is already draining; retry",
            })
            return
        with self._lock:
            rs = self._reshard
            hdr = {"generation": self.generation, "world": self.spec.world,
                   "target_world": new_world, "committed": rs is None}
            if rs is not None:
                hdr["barrier_units"] = rs.get("barrier_units")
                hdr["epoch"] = rs.get("epoch")
        P.send_msg(sock, P.MSG_OK, hdr)

    # ---------------------------------------------------------------- HELLO
    def _on_hello(self, sock, conn_id, header) -> None:
        proto = header.get("proto")
        if proto != P.PROTOCOL_VERSION:
            # explicit version negotiation: a mismatched peer gets the
            # typed error with BOTH ints, never undefined frame decoding
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "protocol_version",
                "server_proto": P.PROTOCOL_VERSION,
                "client_proto": proto,
                "detail": f"server speaks protocol {P.PROTOCOL_VERSION}, "
                          f"client sent {proto!r}",
            })
            return
        cell_refusal = self._cell_refusal(header)
        if cell_refusal is not None:
            _annotate(error_code="wrong_cell")
            P.send_msg(sock, P.MSG_ERROR, cell_refusal)
            return
        engine = self._route_hello(sock, header)
        if engine is None:
            return  # refusal already sent
        if header.get("attach"):
            # additive (docs/SHARDING.md): admit the namespace WITHOUT
            # claiming a rank lease — the shard router pre-attaches a
            # tenant on every shard that owns some of its ranks
            P.send_msg(sock, P.MSG_OK, {"tenant": engine.tenant_id})
            return
        if engine is not self:
            # bind the connection to its tenant: subsequent frames route
            # without re-stating the namespace, and the engine's sweeps
            # can close the socket it leases ranks to
            with self._lock:
                self._conn_tenant[conn_id] = engine
            with engine._lock:
                engine._conn_socks[conn_id] = sock
            _annotate(tenant=engine.tenant_id)
        engine._hello_claim(sock, conn_id, header)

    def _route_hello(self, sock, header) -> Optional["IndexServer"]:
        """Resolve a HELLO's namespace (docs/SERVICE.md "Tenancy"): no
        fingerprint or our own → the default tenant (this server), a
        known tenant fingerprint → its engine, an unknown one → admission
        (create the tenant) on a multi-tenant daemon, or the typed
        ``spec_mismatch`` refusal carrying both world-stripped
        fingerprints.  Returns the engine, or None after refusing."""
        fp = header.get("spec_fingerprint")
        ours = self.spec.fingerprint(include_world=False)
        if fp is None or fp == ours:
            return self
        eng = self._tenants.get(fp)
        if eng is not None:
            return eng
        wire = header.get("spec")
        if not self.multi_tenant or wire is None:
            detail = (
                "client and server stream specs differ; refusing to serve "
                "a different permutation than requested (this daemon is "
                "single-tenant)" if not self.multi_tenant else
                "unknown tenant fingerprint and the HELLO carried no "
                "'spec' wire form to create the tenant from"
            )
            _annotate(error_code="spec_mismatch")
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "spec_mismatch",
                "server_fingerprint": ours,
                "client_fingerprint": fp,
                "detail": detail,
            })
            return None
        return self._admit_tenant(sock, fp, wire)

    def _admit_tenant(self, sock, fp, wire) -> Optional["IndexServer"]:
        """Create-or-attach for an unknown tenant fingerprint.  The
        ``tenant.admission`` fault site fires before any state changes,
        so an injected fault is a clean retryable refusal; capacity
        refusals are terminal ``spec_mismatch`` (carrying both
        fingerprints), transient ones are ``tenant_admission`` with the
        typed ``retry_ms`` backpressure."""
        try:
            F.fire("tenant.admission")
        except F.InjectedThreadDeath:
            raise
        except Exception as exc:
            self.metrics.inc("tenant_admission_rejects")
            _annotate(error_code="tenant_admission")
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "tenant_admission",
                "retry_ms": self.backpressure.retry_ms("tenant_admission"),
                "detail": f"tenant admission refused ({exc!r}); retry",
            })
            return None
        try:
            spec = PartialShuffleSpec.from_wire(
                dict(wire), backend=self.spec.backend)
        except (TypeError, ValueError, KeyError) as exc:
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "bad_request",
                "detail": f"HELLO 'spec' wire form did not parse: {exc!r}",
            })
            return None
        if spec.fingerprint(include_world=False) != fp:
            _annotate(error_code="spec_mismatch")
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "spec_mismatch",
                "server_fingerprint": spec.fingerprint(include_world=False),
                "client_fingerprint": fp,
                "detail": "HELLO 'spec' wire form does not match the "
                          "declared fingerprint",
            })
            return None
        eng = self._make_tenant_engine(spec)
        with self._lock:
            cur = self._tenants.get(fp)
            if cur is not None:
                return cur  # concurrent creation: first registration wins
            if len(self._tenants) + 2 > self.max_tenants:
                # +2: the default tenant plus the one being created
                self.metrics.inc("tenant_admission_rejects")
                _annotate(error_code="spec_mismatch")
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "spec_mismatch",
                    "server_fingerprint":
                        self.spec.fingerprint(include_world=False),
                    "client_fingerprint": fp,
                    "tenants": len(self._tenants) + 1,
                    "max_tenants": self.max_tenants,
                    "detail": f"tenant capacity exceeded: this daemon "
                              f"serves {len(self._tenants) + 1} of "
                              f"{self.max_tenants} namespaces",
                })
                return None
            self._register_tenant_locked(fp, eng)
        self.metrics.inc("tenants_created")
        telemetry.event("tenant_created", tenant=eng.tenant_id)
        # replicate the creation with the engine's full state so a
        # standby can mirror the tenant before any of its records arrive
        self._repl_append("tenant", tenant=eng.tenant_id,
                          state=eng._state_dict())
        return eng

    def _hello_claim(self, sock, conn_id, header) -> None:
        world = header.get("world")
        if world is not None and int(world) != self.spec.world:
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "world",
                "detail": f"server world is {self.spec.world}, client "
                          f"expects {world}",
            })
            return
        batch = int(header.get("batch", 0))
        if batch < 1:
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "batch", "detail": f"batch must be >= 1, "
                                                   f"got {batch}"})
            return
        want = header.get("rank", -1)
        want = -1 if want is None else int(want)
        now = self._clock()
        front = self._parent if self._parent is not None else self
        with self._lock:
            q = self.quota
            if q is not None and q.max_ranks is not None:
                live = sum(
                    1 for r, l in self._leases.items()
                    if l.get("owner") is not None
                    and l.get("owner") != conn_id
                    and now - l["last_seen"] <= self.heartbeat_timeout)
                if live >= q.max_ranks:
                    # admission control: retryable — a lease may free
                    self.metrics.inc("tenant_admission_rejects")
                    _annotate(error_code="tenant_admission")
                    P.send_msg(sock, P.MSG_ERROR, {
                        "code": "tenant_admission",
                        "retry_ms":
                            self.backpressure.retry_ms("tenant_ranks"),
                        "tenant": self.tenant_id,
                        "detail": f"tenant {self.tenant_id} holds {live} "
                                  f"live rank leases; quota max_ranks="
                                  f"{q.max_ranks}",
                    })
                    return
            if want >= self.spec.world and self.generation > 0:
                # a pre-reshard client coming back for a rank the commit
                # removed: tell it the world changed rather than "no_rank"
                P.send_msg(sock, P.MSG_ERROR, self._resharded_err_locked(
                    f"rank {want} no longer exists at world "
                    f"{self.spec.world}; rejoin with rank=-1"))
                return
            rank = self._claim_rank_locked(want, conn_id, now)
            if rank is None:
                code = "rank_taken" if 0 <= want < self.spec.world \
                    else "no_rank"
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": code,
                    "detail": f"rank {want} is live-leased" if code ==
                              "rank_taken" else
                              f"all {self.spec.world} ranks are live-leased",
                })
                return
            self._leases[rank]["batch"] = batch
            self._repl_append("lease", rank=rank, batch=batch)
            if rank in self._cursors:
                self.metrics.inc("reconnects", rank)
            welcome = {
                "proto": P.PROTOCOL_VERSION,
                "rank": rank,
                "spec": self.spec.to_wire(),
                # term/standby are front-server facts: a tenant's client
                # fails over to the DAEMON's standby, which mirrors every
                # tenant (additive field, like ``trace`` in PR 4)
                "tenant": self.tenant_id,
                "term": int(front.term),
                "standby": (list(front._standby_addr)
                            if front._standby_addr is not None else None),
                # additive: the pipelined client bounds its lookahead by
                # the server's throttle window (docs/SERVICE.md)
                "max_inflight": int(self.max_inflight),
                **self._membership_locked(),
                # additive: shard servers ride their rank→shard map here
                # (docs/SHARDING.md); empty for a standalone daemon
                **self._welcome_extra(),
                # additive: the serving cell + global directory on a
                # federated deployment (docs/FEDERATION.md); empty
                # otherwise — front-server facts, like term/standby
                **front._cell_fields(),
                # additive: the autopilot's batch-size suggestion; the
                # field does not exist until a controller has tuned it
                # (docs/AUTOPILOT.md)
                **({"batch_hint": int(self._batch_hint)}
                   if self._advertise_knobs and self._batch_hint is not None
                   else {}),
            }
        self._write_snapshot()
        P.send_msg(sock, P.MSG_WELCOME, welcome)

    def _welcome_extra(self) -> dict:
        """Extra additive WELCOME fields; ``ShardServer`` overrides to
        attach its ``shard_map`` + ``shard`` id (docs/SHARDING.md)."""
        return {}

    # ------------------------------------------------------------ autopilot
    def set_autopilot_knobs(self, *, max_inflight=None,
                            batch_hint=None) -> None:
        """Adopt controller-tuned serving knobs (autopilot/controller.py).

        The first call flips ``_advertise_knobs``: WELCOME gains the
        additive ``batch_hint`` field and heartbeat replies gain
        ``knobs`` — before it, neither exists on the wire, which is the
        zero-protocol-bytes-while-disabled rail (docs/AUTOPILOT.md).
        The knob values themselves ride the controller's ``autopilot``
        WAL record, not this call, so mirrors adopt them there."""
        with self._lock:
            if max_inflight is not None:
                self.max_inflight = max(1, int(max_inflight))
            if batch_hint is not None:
                self._batch_hint = max(1, int(batch_hint))
            self._advertise_knobs = True

    def autopilot_state(self) -> Optional[dict]:
        """The newest controller policy state replicated to this server
        (the ``autopilot`` WAL record's ``pstate``).  A promoted standby
        hands it to its own controller so decisions RESUME from the old
        primary's trajectory instead of restarting cold."""
        with self._lock:
            st = self._autopilot_state
            return dict(st) if st is not None else None

    def _knob_fields(self) -> dict:
        """Additive knob advertisement for heartbeat replies; empty
        until ``set_autopilot_knobs`` has ever run."""
        if not self._advertise_knobs:
            return {}
        kn = {"max_inflight": int(self.max_inflight)}
        if self._batch_hint is not None:
            kn["batch_hint"] = int(self._batch_hint)
        return kn

    def _claim_rank_locked(self, want: int, conn_id: int, now: float):
        """Grant ``want`` (or the lowest free rank for -1).  Called under
        ``self._lock``.  A stale live lease is evicted on the spot."""
        candidates = ([want] if want >= 0 else range(self.spec.world))
        fresh = want < 0 and self.generation > 0
        for rank in candidates:
            if not 0 <= rank < self.spec.world:
                return None
            lease = self._leases.get(rank)
            if lease is not None and lease.get("owner") is not None:
                if now - lease["last_seen"] <= self.heartbeat_timeout:
                    continue  # genuinely live
                lease["owner"] = None
                self.metrics.inc("evictions", rank)
                self.metrics.drop_client(rank)
            if fresh:
                cur = self._cursors.get(rank)
                if cur is not None and int(cur.get("samples", 0)) > 0:
                    # post-reshard auto-claims start at seq 0, so a slot
                    # whose current-generation stream is already partly
                    # served (its previous owner completed or died after
                    # pulling batches) would be double-delivered — only
                    # unserved slots (a leaver's freed lease, a grown
                    # world's new ranks) are adoptable fresh
                    continue
            self._leases[rank] = {"owner": conn_id, "last_seen": now,
                                  "batch": self._leases.get(rank, {}).get(
                                      "batch", 0)}
            self._vacated.pop(rank, None)
            return rank
        return None

    # ------------------------------------------------------------ GET_BATCH
    def _on_get_batch(self, sock, conn_id, header) -> None:
        try:
            rank = int(header["rank"])
            epoch = int(header["epoch"])
            seq = int(header["seq"])
        except (KeyError, TypeError, ValueError):
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "bad_request",
                        "detail": "GET_BATCH needs rank/epoch/seq ints"})
            return
        if seq < 0:
            P.send_msg(sock, P.MSG_ERROR,
                       {"code": "bad_request", "detail": f"seq {seq} < 0"})
            return
        # a piggybacked previous-epoch terminal ack lands BEFORE the
        # request's own generation/epoch logic: if it completes a drain
        # (bumping the generation), this very request is then refused
        # with the fresh membership — exactly what its sender must adopt
        self._apply_piggyback_ack(conn_id, rank, header.get("hb"))
        gen = int(header.get("gen", 0))
        t_req = time.perf_counter()
        advanced = False
        with self._lock:
            if gen != self.generation:
                # the request names a stream of a committed-away
                # generation: hand the client the membership to adopt
                _annotate(error_code="resharded")
                P.send_msg(sock, P.MSG_ERROR, self._resharded_err_locked(
                    f"generation {gen} was resharded away (now at "
                    f"{self.generation})"))
                return
            rs = self._reshard
            if rs is not None and rs.get("phase") == "freeze":
                _annotate(error_code="reshard")
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "reshard",
                    "retry_ms":
                        self.backpressure.retry_ms("reshard_freeze"),
                    "detail": "reshard barrier is freezing; retry shortly",
                })
                return
            lease = self._leases.get(rank)
            if lease is None or lease.get("owner") != conn_id:
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "not_owner",
                    "detail": f"rank {rank} is not leased to this "
                              "connection; HELLO first",
                })
                return
            self._touch(rank, lease)
            if self.streaming:
                # eligibility + ack-gated advance, BEFORE the cursor
                # reset below (docs/STREAMING.md): a refused request
                # must leave every rank's horizon cursor intact so the
                # barrier's straggler test stays truthful
                refusal, advanced = self._stream_gate_locked(epoch)
                if refusal is not None:
                    P.send_msg(sock, P.MSG_ERROR, refusal)
                    return
            batch = lease["batch"]
            cur = self._cursors.get(rank)
            if cur is None or cur["epoch"] != epoch:
                cur = self._cursors[rank] = {"epoch": epoch, "acked": -1,
                                             "hi": -1, "samples": 0}
            if self.streaming:
                # the advance barrier converts acked seqs to samples
                # with this batch; keeping it on the cursor preserves
                # the conversion after the lease is gone (a finished
                # rank that disconnected before the advance), and
                # refreshing it heals a commit-re-pinned cursor created
                # before this rank held a lease
                cur["batch"] = int(batch)
            ack = header.get("ack")
            acked_advanced = False
            if ack is not None and int(ack) > cur["acked"]:
                cur["acked"] = int(ack)
                acked_advanced = True
            if seq > cur["acked"] + self.max_inflight:
                self.metrics.inc("throttled", rank)
                _annotate(error_code="throttle")
                P.send_msg(sock, P.MSG_ERROR, {
                    "code": "throttle",
                    "detail": f"seq {seq} is {seq - cur['acked']} past the "
                              f"acked cursor; max_inflight="
                              f"{self.max_inflight}",
                    "retry_ms": self.backpressure.retry_ms("throttle"),
                })
                return
            clamp = None
            reply = None
            committed = False
            if (rs is not None and rs.get("phase") == "drain"
                    and epoch == rs["epoch"] and rank in rs["targets"]):
                t = int(rs["targets"][rank])
                if seq * batch >= t:
                    if (cur["acked"] + 1) * batch < t:
                        # past the target, but delivery of the pre-barrier
                        # tail is not acked — a served-but-lost final
                        # reply must stay resendable, so the drain
                        # completes only on the client's ack
                        reply = (P.MSG_ERROR, {
                            "code": "reshard",
                            "retry_ms":
                                self.backpressure.retry_ms("reshard_freeze"),
                            "detail": f"rank {rank} reached its barrier "
                                      "target without acking the full "
                                      "pre-barrier span; retry",
                        }, b"")
                    else:
                        # the rank ACKED its full pre-barrier allocation
                        rs["drained"].add(rank)
                        leaving = rank in rs["leaving"]
                        try:
                            committed = self._commit_reshard_locked()
                        except F.InjectedThreadDeath:
                            raise
                        except Exception:  # lint: allow-broad-except(injected commit fault; retried)
                            pass
                        if not committed:
                            self._repl_append(
                                "state",
                                state=self._state_dict_locked())
                        if leaving:
                            # terminal EOF: the leaving stream ends
                            reply = (P.MSG_BATCH,
                                     {"seq": seq, "eof": True, "total": t,
                                      "end": t, "left": True}, b"")
                        elif gen != self.generation:
                            reply = (P.MSG_ERROR,
                                     self._resharded_err_locked(
                                         "reshard committed; adopt the "
                                         "new membership"), b"")
                        else:
                            reply = (P.MSG_ERROR, {
                                "code": "reshard",
                                "retry_ms": self.backpressure.retry_ms(
                                    "reshard_freeze"),
                                "detail": f"rank {rank} drained to its "
                                          "barrier target; waiting for "
                                          "the commit",
                            }, b"")
                else:
                    clamp = t
            resend = seq <= cur["hi"]
        if advanced:
            # the horizon advance this request committed seals a forced
            # checkpoint (outside the lock — the writer retakes it) so
            # the WAL truncates below the new watermark
            self._stream_advanced(t_req)
        if reply is not None:
            if committed:
                self._write_snapshot(force=True)
            mt, h, pl = reply
            P.send_msg(sock, mt, h, pl)
            return
        arr = self._rank_array(epoch, rank)
        lo = seq * batch
        total = int(arr.shape[0])
        limit = total if clamp is None else min(clamp, total)
        if lo >= limit:
            if acked_advanced or (self.streaming and clamp is None):
                # the epoch's terminal ack rides the EOF poll and no
                # slice is served below, so the usual served-slice
                # cursor append never runs — persist the advance here
                # or recovery resumes one ack behind
                with self._lock:
                    cur = self._cursors.get(rank)
                    if cur is not None and cur["epoch"] == epoch:
                        if self.streaming and clamp is None:
                            # the horizon's layer-aware end — what the
                            # advance barrier's straggler test compares
                            # acked delivery against; MUST come from
                            # _rank_array (a mid-horizon reshard shrinks
                            # remainder allocations below num_samples)
                            cur["total"] = int(total)
                        if acked_advanced:
                            self._repl_append("cursor", rank=rank, **cur)
            P.send_msg(sock, P.MSG_BATCH,
                       {"seq": seq, "eof": True, "total": total,
                        "end": limit, "gen": gen})
            return
        sl = arr[lo:min(lo + batch, limit)]
        end = lo + int(sl.shape[0])
        fields, payload = P.encode_indices(sl)
        with self._lock:
            stale = None
            rs = self._reshard
            if gen != self.generation:
                # a concurrent sweep committed while we were encoding —
                # serving old-generation bytes now could duplicate an
                # orphaned span, so refuse and hand over the membership
                stale = self._resharded_err_locked(
                    "reshard committed mid-request; adopt the new "
                    "membership")
            elif rs is not None and rs.get("phase") == "freeze":
                # a barrier froze while we were generating/encoding:
                # delivering now would outrun the watermark snapshot the
                # freeze took (the span would also ride the repartitioned
                # remainder, i.e. be served twice) — refuse; the retry is
                # served clamped once the drain opens
                stale = {"code": "reshard",
                         "retry_ms":
                             self.backpressure.retry_ms("reshard_freeze"),
                         "detail": "reshard barrier froze mid-request; "
                                   "retry shortly"}
            elif (rs is not None and rs.get("phase") == "drain"
                    and epoch == rs["epoch"] and rank in rs["targets"]
                    and clamp is None and end > int(rs["targets"][rank])):
                # same race, one tick later: the barrier froze AND opened
                # its drain mid-request, and this unclamped slice overruns
                # the rank's drain target — refuse rather than duplicate
                stale = {"code": "reshard",
                         "retry_ms":
                             self.backpressure.retry_ms("reshard_freeze"),
                         "detail": "reshard barrier cut below this batch "
                                   "mid-request; retry shortly"}
            else:
                cur = self._cursors.get(rank)
                if cur is not None and cur["epoch"] == epoch:
                    cur["hi"] = max(cur["hi"], seq)
                    cur["samples"] = max(int(cur.get("samples", 0)), end)
                    if self.streaming and clamp is None and end >= limit:
                        # last slice of the horizon: pin the layer-aware
                        # end on the cursor so the terminal ack (which
                        # may arrive piggybacked, with no further
                        # GET_BATCH for this horizon) satisfies the
                        # advance barrier — and replicates with it
                        cur["total"] = int(limit)
                    self._repl_append("cursor", rank=rank, **cur)
        if stale is not None:
            P.send_msg(sock, P.MSG_ERROR, stale)
            return
        self.metrics.inc("batches_served", rank)
        if resend:
            self.metrics.inc("resends", rank)
        self._write_snapshot()
        P.send_msg(sock, P.MSG_BATCH,
                   {"seq": seq, "eof": False, "total": total, "end": end,
                    "gen": gen, **fields},
                   payload)

    # ---------------------------------------- moving-horizon streaming
    def _on_append(self, sock, header) -> None:
        """A feeder extends the append-only index space
        (docs/STREAMING.md).  Exactly-once under retries rests on two
        invariants, never on any single WAL append landing: the appended
        total is ABSOLUTE in every ``stream`` record (replay takes the
        max), and ``stream_seq`` is monotonic per feeder id, so a
        retried APPEND whose first attempt half-landed is recognized and
        answered with the current totals instead of re-applied."""
        if not self.streaming:
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "bad_request",
                "detail": "APPEND against a non-stream spec; only "
                          "mode='stream' index spaces grow",
            })
            return
        try:
            count = int(header["count"])
            seq = int(header.get("stream_seq", 0))
            feeder = str(header.get("feeder", ""))
        except (KeyError, TypeError, ValueError):
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "bad_request",
                "detail": "APPEND needs an int count",
            })
            return
        if count < 0:
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "bad_request", "detail": f"count {count} < 0"})
            return
        try:
            F.fire("stream.append")
        except F.InjectedThreadDeath:
            raise
        except Exception as exc:
            # the site fires BEFORE any mutation: an injected append
            # fault is a clean retryable refusal, and the feeder's
            # stream_seq makes the retry exactly-once
            _annotate(error_code="stream_append")
            P.send_msg(sock, P.MSG_ERROR, {
                "code": "stream_append",
                "retry_ms": self.backpressure.retry_ms("stream_append"),
                "detail": f"append refused ({exc!r}); retry",
            })
            return
        delta = header.get("weights_delta")
        h = int(self.spec.horizon)
        with self._lock:
            if self._stream_seqs.get(feeder, -1) >= seq:
                # a retry of an APPEND that already landed: answer with
                # the current totals, mutate nothing
                P.send_msg(sock, P.MSG_OK, {
                    "appended": int(self._stream_appended),
                    "eligible": int(self.spec.eligible_horizons(
                        self._stream_appended)),
                    "epoch": int(self.epoch), "stream_seq": seq,
                    "duplicate": True,
                })
                return
            before = self._stream_appended
            self._stream_appended = before + count
            self._stream_seqs[feeder] = seq
            if delta is not None:
                cur = self._stream_pending
                self._stream_pending = (
                    [int(x) for x in delta] if cur is None
                    else [int(a) + int(b) for a, b in zip(cur, delta)])
            now = time.perf_counter()
            self._stream_first_t.setdefault(before // h, now)
            for g in range(before // h, self._stream_appended // h):
                # horizon g just completed — appended → servable
                t_open = self._stream_first_t.pop(g, now)
                self.metrics.registry.histogram(
                    "append_visible_ms").observe((now - t_open) * 1e3)
            self._repl_append(
                "stream", appended=int(self._stream_appended),
                seqs={str(k): int(v)
                      for k, v in self._stream_seqs.items()},
                pending=(list(self._stream_pending)
                         if self._stream_pending is not None else None))
            appended = self._stream_appended
            eligible = self.spec.eligible_horizons(appended)
            epoch = self.epoch
        self.metrics.inc("stream_appends")
        self._write_snapshot()
        P.send_msg(sock, P.MSG_OK, {
            "appended": int(appended), "eligible": int(eligible),
            "epoch": int(epoch), "stream_seq": seq,
        })

    def _stream_stragglers_locked(self, g: int) -> list[int]:
        """Ranks that have not ACKED their full horizon-``g`` allocation
        — the advance barrier's completion test.  The per-rank target is
        the ``total`` the serve path pinned on the rank's cursor (the
        layer-aware end of its stream: a mid-horizon reshard shrinks
        remainder allocations below ``spec.num_samples``, so the base
        spec alone would deadlock the barrier).  A rank with no cursor
        at all is excused only when its base allocation is zero; the
        ack→samples conversion batch comes from the cursor so a finished
        rank that already dropped its lease still passes.  Under
        ``self._lock``."""
        out = []
        for r in range(self.spec.world):
            cur = self._cursors.get(r)
            if cur is None:
                if int(self.spec.num_samples(r) or 0) > 0:
                    out.append(r)
                continue
            total = cur.get("total")
            if (int(cur["epoch"]) == int(g) and total is not None
                    and int(total) <= 0):
                # an empty allocation (e.g. a re-pinned zero remainder
                # share after a reshard) is complete by definition — no
                # request, lease or batch required
                continue
            b = int(cur.get("batch")
                    or self._leases.get(r, {}).get("batch") or 0)
            if (int(cur["epoch"]) != int(g) or total is None or b <= 0
                    or (int(cur["acked"]) + 1) * b < int(total)):
                out.append(r)
        return out

    def _stream_gate_locked(self, epoch: int):
        """The eligibility + ack-gated advance gate on a streaming
        request naming horizon ``epoch`` (docs/STREAMING.md).  Returns
        ``(refusal, advanced)``: a typed ERROR header to refuse with (or
        None to serve), and whether this request committed a horizon
        advance — the caller then runs :meth:`_stream_advanced` outside
        the lock.  Under ``self._lock``."""
        epoch = int(epoch)
        eligible = self.spec.eligible_horizons(self._stream_appended)
        if epoch >= eligible:
            # eligibility law: horizon g needs (g+1)*H appended samples
            # — whole horizons only, so the permutation input is always
            # the full block and the stream stays pure
            _annotate(error_code="horizon_pending")
            return ({
                "code": "horizon_pending",
                "retry_ms": self.backpressure.retry_ms("horizon_gate"),
                "appended": int(self._stream_appended),
                "eligible": int(eligible),
                "detail": f"horizon {epoch} is not fully appended "
                          f"({self._stream_appended} samples, "
                          f"{eligible} eligible horizons)",
            }, False)
        if epoch <= self.epoch:
            # the current horizon, or an earlier one — both pure
            # regenerable; resends below the watermark serve unchanged
            return None, False
        if epoch > self.epoch + 1:
            _annotate(error_code="horizon_advance")
            return ({
                "code": "horizon_advance",
                "retry_ms": self.backpressure.retry_ms("horizon_gate"),
                "epoch": int(self.epoch),
                "detail": f"horizon {epoch} is {epoch - self.epoch} "
                          f"ahead of the stream (at {self.epoch}); "
                          "advance is one horizon at a time",
            }, False)
        stragglers = self._stream_stragglers_locked(self.epoch)
        if stragglers:
            _annotate(error_code="horizon_advance")
            return ({
                "code": "horizon_advance",
                "retry_ms": self.backpressure.retry_ms("horizon_gate"),
                "epoch": int(self.epoch),
                "detail": f"ranks {stragglers} have not acked their "
                          f"full horizon-{self.epoch} allocation",
            }, False)
        try:
            F.fire("stream.advance")
        except F.InjectedThreadDeath:
            raise
        except Exception as exc:
            # the site fires BEFORE any mutation, so an injected abort
            # rolls back to exactly the pre-advance state
            _annotate(error_code="horizon_advance")
            return ({
                "code": "horizon_advance",
                "retry_ms": self.backpressure.retry_ms("horizon_gate"),
                "epoch": int(self.epoch),
                "detail": f"advance aborted ({exc!r}); retry",
            }, False)
        self._stream_advance_locked(epoch)
        return None, True

    def _stream_advance_locked(self, new_epoch: int) -> None:
        """Commit the horizon advance (caller already passed the
        straggler + eligibility gates): fold the pending weights delta
        into the spec's per-horizon weights, bump the horizon
        generation, and log the absolute stream state.  Under
        ``self._lock``."""
        from ..streaming.spec import WEIGHTS_RETAIN

        weights = None
        if self._stream_pending is not None:
            prev = self.spec.weights_for(self.epoch)
            if prev is not None:
                # additive deltas on top of the previous horizon's
                # effective weights, floored at 1 (mixture weights are
                # integer quotas — ops/mixture.py)
                weights = tuple(
                    max(1, int(a) + int(b))
                    for a, b in zip(prev, self._stream_pending))
                self.spec = self.spec.with_stream_weights(
                    {int(new_epoch): weights},
                    prune_below=int(new_epoch) - WEIGHTS_RETAIN // 2)
            self._stream_pending = None
        self.epoch = int(new_epoch)
        self._repl_append(
            "stream", appended=int(self._stream_appended),
            epoch=int(self.epoch),
            weights=(list(weights) if weights is not None else None))
        telemetry.event("horizon_advance", epoch=int(self.epoch))

    def _stream_advanced(self, t0: float) -> None:
        """Post-advance persistence, OUTSIDE ``self._lock`` (the
        snapshot writer retakes it): seal a forced checkpoint so the WAL
        GC truncates every record below the new horizon's watermark —
        server + WAL state stays O(horizon), not O(stream)
        (docs/STREAMING.md "Bounded state")."""
        wal = self._wal
        before = len(wal.segment_paths()) if wal is not None else 0
        self._write_snapshot(force=True)
        if wal is not None:
            dropped = before - len(wal.segment_paths())
            if dropped > 0:
                self.metrics.inc("stream_gc_truncations", value=dropped)
        self.metrics.inc("horizon_advances")
        self.metrics.registry.histogram("horizon_advance_ms").observe(
            (time.perf_counter() - t0) * 1e3)
