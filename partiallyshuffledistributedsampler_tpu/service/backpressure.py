"""One table for every typed ``retry_ms`` hint the serving plane emits.

Before this module the backpressure constants were scattered through
``service/server.py`` (and the sharding plane) as magic numbers — 20 ms
at the reshard-freeze sites, 50 ms at capability issuance, 100 ms on a
standby refusal, 200 ms while draining.  :class:`BackpressurePolicy`
centralizes them behind named sites so

* tests can **pin** a site (``policy.set("throttle", 5)``) instead of
  monkeypatching call sites, and
* the autopilot's shed arm (docs/AUTOPILOT.md) can **scale** every hint
  multiplicatively with observed queue depth (``policy.set_scale(4.0)``)
  before the watchdog ever fires — clients already honor whatever
  ``retry_ms`` rides the refusal, so deeper backoff needs zero protocol
  changes.

The table is immutable-by-default: a server constructs its own policy,
defaults match the historical constants exactly, and ``scale == 1.0``
keeps every hint bit-identical to the pre-table behavior (the
zero-cost-when-disabled rail).  Reads are a dict lookup + one multiply;
no lock — the scale is a single float assignment (atomic in CPython)
and a momentarily stale hint is harmless backpressure jitter.
"""

from __future__ import annotations

#: historical per-site retry hints in milliseconds; keys are the typed
#: refusal families in service/server.py + sharding/ (docs/SERVICE.md)
DEFAULT_RETRY_MS = {
    "reshard_freeze": 20,      # barrier freezing/draining; come right back
    "reshard_conflict": 50,    # a barrier is already in flight
    "capability_issue": 50,    # transient issuance refusal
    "capability_stale": 20,    # grant superseded mid-issue
    "standby": 100,            # data op at a hot standby
    "throttle": 20,            # in-flight span past max_inflight
    "draining": 200,           # graceful shutdown in progress
    "tenant_admission": 50,    # tenant creation/burst quota
    "tenant_ranks": 100,       # tenant at its max_ranks quota
    "stream_append": 25,       # injected/failed APPEND; replay dedupes
    "horizon_gate": 25,        # horizon not appended / advance pending
    "wrong_shard": 25,         # re-route via the attached shard map
    "wrong_cell": 25,          # re-route via the attached cell directory
}

#: shed-arm ceiling: scaled hints never exceed this (a runaway controller
#: must not park clients for minutes)
MAX_RETRY_MS = 5_000


class BackpressurePolicy:
    """Named ``retry_ms`` table with one multiplicative shed scale."""

    __slots__ = ("_table", "_scale")

    def __init__(self, overrides=None, scale: float = 1.0) -> None:
        self._table = dict(DEFAULT_RETRY_MS)
        for site, ms in (overrides or {}).items():
            self.set(site, ms)
        self._scale = 1.0
        self.set_scale(scale)

    def retry_ms(self, site: str) -> int:
        """The hint for ``site``, shed-scaled and clamped to
        [1, MAX_RETRY_MS].  Unknown sites raise — a typo here would
        silently un-pace a refusal path."""
        base = self._table[site]
        return max(1, min(MAX_RETRY_MS, int(round(base * self._scale))))

    def set(self, site: str, ms: int) -> None:
        """Pin one site's base hint (tests; operator overrides)."""
        if site not in DEFAULT_RETRY_MS:
            raise KeyError(f"unknown backpressure site {site!r}; sites "
                           f"are {sorted(DEFAULT_RETRY_MS)}")
        self._table[site] = int(ms)

    def set_scale(self, factor: float) -> float:
        """Set the multiplicative shed factor (autopilot's load-shedding
        arm).  Clamped to [1, 256]; returns the applied value."""
        self._scale = max(1.0, min(256.0, float(factor)))
        return self._scale

    @property
    def scale(self) -> float:
        return self._scale

    def report(self) -> dict:
        """Observability: the effective table (post-scale) + the scale."""
        return {"scale": self._scale,
                "retry_ms": {s: self.retry_ms(s) for s in self._table}}
