"""Service observability: per-client counters over the shared registry.

The daemon's numbers ride the same :class:`~..utils.metrics.MetricsRegistry`
surface the rest of the framework exports through, so ``bench.py``, the
``make service-smoke`` gate and an operator ``METRICS`` poll all read one
report produced one way.  Counter vocabulary (the ISSUE's metric set):

* ``batches_served``   — BATCH replies carrying indices
* ``resends``          — BATCH replies for a seq already served to that
                         rank (a reconnected client replaying its cursor)
* ``reconnects``       — HELLOs re-claiming a rank this server already
                         served (client came back after a drop)
* ``heartbeat_gaps``   — gaps between a client's messages that exceeded
                         the lease timeout but the client returned
* ``evictions``        — rank leases revoked for missed heartbeats
* ``throttled``        — GET_BATCHs refused by backpressure
* ``epoch_regen_ms``   — timer: per-(epoch, rank) index generation

Elastic membership (docs/RESILIENCE.md "Elastic membership"):

* ``leaves``           — LEAVE requests accepted (preemption drains)
* ``reshard_triggers`` — barriers frozen (LEAVE, RESHARD RPC, eviction)
* ``reshards``         — barriers committed (generation bumps)
* ``orphaned``         — samples converted to orphan descriptors at a
                         commit (dead ranks' un-drained allocations)

Client-side additions with the same vocabulary: ``reshards_ridden``
(memberships adopted mid-stream), ``reshard_waits`` (requests paused on
a draining barrier), ``membership_lost`` (rejoin found no free rank).

Per-client copies of the counters live under ``clients[rank]``; the
registry holds the totals.  Per-client entries are pruned when the rank
departs for good — lease eviction or a reshard commit that removes the
rank — and their totals are folded into one aggregate ``departed``
entry, so a long-lived daemon's report does not grow with every rank
that ever connected (docs/OBSERVABILITY.md).  The epoch regen timer is
the same :class:`RegenTimer` every sampler uses, so "epoch regen ms"
means the same thing here as in a local training loop.

Multi-tenant daemons (docs/SERVICE.md "Tenancy") key per-client counters
by ``(tenant, client)``: :meth:`scoped` derives one child view per tenant
with a private registry and a private ``clients``/``departed`` table, so
one tenant's churn can't pollute another's counters and a tenant METRICS
poll sees only its own numbers.  Child totals are mirrored into the
parent registry (the operator's daemon-wide view) and child reports are
rolled up under ``report()["tenants"][tenant_id]``.
"""

from __future__ import annotations

import threading

from ..utils.metrics import MetricsRegistry
from ..analysis.lockorder import new_lock

#: counter names with a per-client breakdown
_PER_CLIENT = (
    "batches_served", "resends", "reconnects", "heartbeat_gaps", "evictions",
    "throttled", "leaves",
)


class ServiceMetrics:
    """Counters for one daemon (or one client, with the same vocabulary).

    ``registry`` defaults to a private :class:`MetricsRegistry`; pass a
    shared one to fold several daemons into one report."""

    def __init__(self, registry: MetricsRegistry | None = None) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        self._lock = new_lock("service.metrics")
        self.clients: dict[int, dict[str, int]] = {}  # guarded by: self._lock
        self.departed: dict[str, int] = {}  # guarded by: self._lock
        self.tenant: str | None = None
        self._parent: ServiceMetrics | None = None
        self._tenants: dict[str, ServiceMetrics] = {}  # guarded by: self._lock

    def scoped(self, tenant: str) -> "ServiceMetrics":
        """Per-tenant child view: private registry, private ``clients``
        table (so per-client counters are effectively keyed by
        ``(tenant, client)``), totals mirrored into this parent."""
        tenant = str(tenant)
        with self._lock:
            child = self._tenants.get(tenant)
            if child is None:
                child = ServiceMetrics()
                child.tenant = tenant
                child._parent = self
                self._tenants[tenant] = child
            return child

    def inc(self, name: str, rank: int | None = None, value: int = 1) -> None:
        self.registry.inc(name, value)
        if self._parent is not None:
            # mirror tenant totals into the daemon-wide operator view
            self._parent.registry.inc(name, value)
        if rank is not None and name in _PER_CLIENT:
            with self._lock:
                per = self.clients.setdefault(
                    int(rank), {k: 0 for k in _PER_CLIENT}
                )
                per[name] += value

    def drop_client(self, rank: int) -> bool:
        """Prune rank's per-client entry, folding its counts into the
        aggregate ``departed`` entry.  Called at lease eviction and at a
        reshard commit that removes the rank; a later reconnect under the
        same rank number starts a fresh entry.  Returns True if an entry
        was dropped."""
        with self._lock:
            per = self.clients.pop(int(rank), None)
            if per is None:
                return False
            self.departed["clients"] = self.departed.get("clients", 0) + 1
            for name, v in per.items():
                if v:
                    self.departed[name] = self.departed.get(name, 0) + v
            return True

    @property
    def regen_timer(self):
        return self.registry.timer("epoch_regen_ms")

    def report(self) -> dict:
        out = self.registry.report()
        with self._lock:
            out["clients"] = {
                str(r): dict(c) for r, c in sorted(self.clients.items())
            }
            if self.departed:
                out["departed"] = dict(self.departed)
            if self.tenant is not None:
                out["tenant"] = self.tenant
            tenants = dict(self._tenants)
        if tenants:
            out["tenants"] = {t: m.report() for t, m in sorted(tenants.items())}
        return out
