"""`ServiceIndexClient`: the consumer side of the index service.

A thin, synchronous client that claims a rank, streams its epoch's index
batches, and survives server restarts: every request is idempotent (the
server is a pure function of ``(epoch, seq)`` plus the spec), so the
retry layer reconnects with exponential backoff + jitter and replays the
cursor — the delivered index stream is exactly-once and bit-identical to
a local sampler run no matter how many times the connection (or the
server) died in between.

Drop-in surfaces:

* ``epoch_indices(epoch)`` → the rank's full epoch stream as one host
  array — feed it anywhere a local ``epoch_indices`` result goes
  (``HostDataLoader(..., index_client=client)`` does exactly this).
* ``epoch_batches(epoch)`` → an iterator of index batches, resumable via
  ``start_seq`` / ``state_dict()``; wrap it in
  :class:`~..utils.stall_probe.StallProbe` to measure service-path
  starvation the same way the local loaders are measured.

Elastic membership (docs/RESILIENCE.md "Elastic membership"): the client
stamps every ``GET_BATCH`` with the server generation it believes in;
when a reshard commits underneath it, the server's typed ``resharded``
error carries the new membership (generation, world, §6 cascade layers,
orphan descriptors) and the stream *rides through*: the generator adopts
it, renegotiates a rank if its old one no longer exists, and continues
yielding the post-reshard remainder — the consumer sees one contiguous,
exactly-once stream across the world change.  ``leave(grace_ms)`` is the
preemption-notice drain (hook it to SIGTERM); while a barrier drains,
requests wait it out through the retry policy and surface a typed
:class:`ReshardInProgress` only when the deadline is exhausted.

Hot-standby failover (docs/RESILIENCE.md "Replication & failover"): the
WELCOME header carries the standby's address when the server ships its
WAL to one.  When the primary's retry budget exhausts, the client fails
over — re-HELLO to the standby with ``failover=true`` (which promotes it
once its replication feed is stale) under a FRESH retry deadline and
budget, then replays its delivered-ack cursor; the PR 3 ack machinery
makes the resumed stream exactly-once and bit-identical.  The client
adopts the fencing ``term`` from every WELCOME and stamps it on requests
after a failover; a fenced zombie primary's typed ``fenced`` refusal
(``serving=false``) routes the client to the winner, surfacing
:class:`FencedError` only when no peer at the winning term is
reachable.  A client pointed at a standby of a *healthy* pair follows
the ``standby`` error's ``primary`` redirect instead.

Multi-tenancy (docs/SERVICE.md "Tenancy"): when constructed with
``spec=``, HELLO carries the full wire spec alongside the world-stripped
fingerprint, so a multi-tenant daemon can *create* the job's namespace
on first contact instead of refusing the mismatch.  The WELCOME's
``tenant`` id is adopted and stamped on every subsequent request (so a
reconnect or failover lands back in the same namespace), a refused
attach surfaces as the typed :class:`SpecMismatchError` carrying both
fingerprints, and a ``tenant_admission`` refusal (per-tenant quota) is
retried like throttle backpressure using the server's ``retry_ms``.

Capability mode (docs/CAPABILITY.md "Serve seeds, not indices"): when
both sides share a ``capability_secret``, ``capability_epoch_batches``
streams the epoch with ZERO index bytes on the wire — the client fetches
one signed :class:`~..capability.EpochCapability`, verifies it
(signature, fingerprint, tenant, generation, epoch), regenerates its
stream on-device with the same kernels the degraded fallback uses, and
reports only ack watermarks over periodic heartbeats.  Exactly-once
cursors, elastic drain barriers, and failover replay all keep working
because issuance creates the rank's epoch cursor server-side and the
heartbeat acks drive it exactly as batch requests would.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import Iterator, Optional

import numpy as np

from .. import faults as F
from ..capability import (
    CapabilityError,
    EpochCapability,
    membership_stream,
    orphan_slice,
    replay_trail,
)
from ..telemetry import enabled as _tel_enabled, span as _span
from ..tenancy import tenant_id_for
from ..utils.retry import RetryPolicy
from . import protocol as P
from .metrics import ServiceMetrics

#: ERROR codes that indicate a configuration/contract problem — retrying
#: cannot fix them, so they raise immediately
_FATAL_CODES = frozenset(
    {"proto", "protocol_version", "world", "spec", "spec_mismatch", "batch",
     "bad_request", "unknown_type", "protocol", "no_rank"}
)

#: consecutive checksum rejects on one seq before the client gives up on
#: re-requesting (a link that corrupts every replay is not transient)
_MAX_CHECKSUM_REJECTS = 4

#: process-wide feeder-id allocator for APPEND exactly-once dedup
_FEEDER_LOCK = threading.Lock()
_FEEDER_SEQ = 0


class ServiceError(RuntimeError):
    """Server answered ERROR; ``code`` carries the protocol error code
    and ``header`` the full reply header (membership fields ride there
    on ``resharded`` errors)."""

    def __init__(self, code: str, detail: str = "",
                 header: Optional[dict] = None) -> None:
        super().__init__(f"[{code}] {detail}" if detail else code)
        self.code = code
        self.header = header if header is not None else {}


class ServiceUnavailable(ServiceError):
    """Retries exhausted without reaching a serving daemon."""

    def __init__(self, detail: str) -> None:
        super().__init__("unavailable", detail)


class ReshardInProgress(ServiceError):
    """A reshard barrier kept the server draining past the operation's
    retry deadline.  The stream is intact — retrying the same operation
    after the barrier commits continues it exactly-once."""

    def __init__(self, detail: str) -> None:
        super().__init__("reshard", detail)


class SpecMismatchError(ServiceError):
    """The server's world-stripped spec fingerprint does not match ours
    and it refused to (or could not) attach a tenant for it — a
    single-tenant daemon serving a different job, a mis-declared
    fingerprint, or a multi-tenant daemon at its ``max_tenants``
    capacity.  Carries both fingerprints so the operator can see *which*
    config each side holds."""

    def __init__(self, detail: str = "", header: Optional[dict] = None) -> None:
        super().__init__("spec_mismatch", detail, header)
        hdr = self.header
        self.server_fingerprint = hdr.get("server_fingerprint")
        self.client_fingerprint = hdr.get("client_fingerprint")


class FencedError(ServiceError):
    """Every reachable peer refused the request as fenced: a promotion
    to ``term`` superseded the server(s) this client can reach, and no
    peer serving at that term is attached.  The stream is intact — a
    retry once the new primary is reachable (or the degraded local
    fallback) continues it exactly-once."""

    def __init__(self, term: int, detail: str = "",
                 header: Optional[dict] = None) -> None:
        super().__init__("fenced", detail, header)
        self.term = int(term)


def _typed_error(code: str, detail: str, header: dict) -> ServiceError:
    """Build the most specific exception type for a server ERROR code."""
    if code == "spec_mismatch":
        return SpecMismatchError(detail, header)
    return ServiceError(code, detail, header)


def _parse_address(address):
    if isinstance(address, (tuple, list)):
        host, port = address
        return str(host), int(port)
    host, _, port = str(address).rpartition(":")
    return host or "127.0.0.1", int(port)


class ServiceIndexClient:
    """One rank's view of an :class:`~.server.IndexServer`.

    address:     ``(host, port)`` or ``"host:port"``.
    rank:        the rank to claim; ``None`` auto-claims the lowest free
                 rank (the server assigns; read ``client.rank`` after).
    batch:       transport batch size (indices per GET_BATCH) — a wire
                 chunking knob, independent of the training batch size.
    spec:        optional :class:`~.spec.PartialShuffleSpec`; when given,
                 HELLO carries its fingerprint and the server refuses a
                 mismatch (otherwise the client trusts the server and
                 exposes the served config as ``client.spec_wire``).
    timeout:     per-request socket timeout (seconds).
    reconnect_timeout: total time the retry layer keeps trying to reach a
                 server before raising :class:`ServiceUnavailable`.
    backoff_base/backoff_max: exponential-backoff bounds, consumed by the
                 default :class:`~..utils.retry.RetryPolicy` (full
                 jitter, so N clients dropped by one restart don't
                 reconnect in lockstep).
    retry_policy: a :class:`~..utils.retry.RetryPolicy` overriding the
                 one built from the three knobs above; carries the
                 circuit breaker that makes a dead daemon fail fast
                 between operations instead of paying the full deadline
                 on every call.
    lookahead:   how many GET_BATCH requests ``epoch_batches`` keeps in
                 flight on a healthy connection (docs/SERVICE.md
                 "Serve-path fusion").  The effective window is clamped
                 by the server's WELCOME-advertised ``max_inflight`` so
                 pipelining never trips the throttle gate; ``1``
                 restores the strictly request-reply serve path.
    capability_secret: per-deployment HMAC key for verifying signed
                 epoch capabilities (docs/CAPABILITY.md); ``None``
                 disables ``capability_epoch_batches``.
    capability_heartbeat_s: keepalive cadence for capability-mode
                 (batchless) streams — a HEARTBEAT carrying the
                 delivered-ack cursor goes out at least this often, so
                 lease eviction and lazy drain commits behave
                 identically with and without batch flow.
    clock:       injectable monotonic clock for that cadence (tests).
    cell_directory: optional seed for the federation's tenant → cell
                 namespace (a ``CellDirectory``, or its wire dict); the
                 live one is adopted from WELCOMEs and ``wrong_cell``
                 refusals, version-gated (docs/FEDERATION.md).
    """

    def __init__(
        self,
        address,
        *,
        rank: Optional[int] = None,
        batch: int = 65536,
        spec=None,
        timeout: float = 10.0,
        reconnect_timeout: float = 30.0,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        metrics: Optional[ServiceMetrics] = None,
        retry_policy: Optional[RetryPolicy] = None,
        lookahead: int = 4,
        capability_secret=None,
        capability_heartbeat_s: float = 1.0,
        clock=None,
        attach: bool = False,
        auto_batch: bool = False,
        cell_directory=None,
    ) -> None:
        self.address = _parse_address(address)
        self.rank = None if rank is None else int(rank)
        self.batch = int(batch)
        if self.batch < 1:
            raise ValueError(f"batch must be >= 1, got {batch}")
        self.expected_spec = spec
        self.timeout = float(timeout)
        self.reconnect_timeout = float(reconnect_timeout)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy(
                base=self.backoff_base, max_delay=self.backoff_max,
                deadline=self.reconnect_timeout,
                # open only after enough consecutive failures to have
                # exhausted a typical _rpc deadline, and re-probe quickly:
                # the breaker exists to fail FAST between operations, not
                # to delay recovery
                breaker_threshold=12, breaker_reset=1.0,
            )
        self.metrics = metrics if metrics is not None else ServiceMetrics()
        self.lookahead = int(lookahead)
        if self.lookahead < 1:
            raise ValueError(f"lookahead must be >= 1, got {lookahead}")
        #: opt in to the server's autopilot batch suggestion: a WELCOME
        #: ``batch_hint`` (or a heartbeat ``knobs`` field) is adopted at
        #: the next epoch boundary (docs/AUTOPILOT.md)
        self.auto_batch = bool(auto_batch)
        self._batch_hint: Optional[int] = None
        #: per-deployment HMAC key for verifying signed epoch
        #: capabilities (docs/CAPABILITY.md); None disables the
        #: capability-mode stream entirely
        self.capability_secret = capability_secret
        self.capability_heartbeat_s = float(capability_heartbeat_s)
        self._clock = clock if clock is not None else time.monotonic
        #: the latest HEARTBEAT/CAPABILITY reply's drain notice for this
        #: capability-mode rank: ``{"epoch", "target_samples"}`` while a
        #: barrier drains, else None (docs/CAPABILITY.md "Drain law")
        self._cap_drain: Optional[dict] = None
        #: resume point from the latest grant: the slot's server-side
        #: acked cursor + 1, in seq units — a takeover of a partly-
        #: served slot regenerates from here, never from seq 0
        self._cap_resume_seq = 0
        #: the server's throttle window, adopted from WELCOME (additive
        #: field); bounds the pipelined lookahead so a full window of
        #: un-acked requests is never refused as out-of-window
        self._server_max_inflight: Optional[int] = None
        #: learned cap after a throttle refusal mid-pipeline (an old
        #: server that does not advertise ``max_inflight``)
        self._pipe_cap: Optional[int] = None
        #: a deferred delivered-ack cursor ``[epoch, ack]`` — the
        #: previous epoch's terminal ack, piggybacked (header field
        #: ``hb``) on the next GET_BATCH/HEARTBEAT instead of costing a
        #: dedicated EOF poll; re-application is idempotent server-side
        self._pending_hb: Optional[list] = None
        #: namespace id adopted from WELCOME (docs/SERVICE.md "Tenancy");
        #: stamped on every request so a re-dial of a multi-tenant daemon
        #: lands back in the same tenant even before the re-HELLO binds us
        self.tenant: Optional[str] = None
        #: the deployment's rank→shard map (raw wire dict), adopted from
        #: a router WELCOME or a ``wrong_shard`` refusal; ``None`` on an
        #: unsharded deployment (docs/SHARDING.md)
        self.shard_map: Optional[dict] = None
        #: where the router listens, remembered at the first router
        #: WELCOME — the fallback re-route target when an adopted map
        #: carries no address for our shard
        self._router_address: Optional[tuple] = None
        #: the federation's cell directory (raw wire dict), seeded from
        #: the ctor and refreshed by WELCOME / ``wrong_cell`` refusals;
        #: ``None`` on an unfederated deployment (docs/FEDERATION.md)
        self.cell_directory: Optional[dict] = (
            None if cell_directory is None
            else (cell_directory.to_wire()
                  if hasattr(cell_directory, "to_wire")
                  else dict(cell_directory)))
        #: which cell the current connection serves in, from WELCOME
        self.cell: Optional[str] = None
        self.spec_wire: Optional[dict] = None
        self.server_epoch: Optional[int] = None
        self._sock: Optional[socket.socket] = None
        self._cursor = {"epoch": None, "seq": 0}  # next undelivered batch
        # -------- elastic membership (docs/RESILIENCE.md) --------
        # The server's view of the world, adopted from WELCOME and from
        # ``resharded`` errors.  ``layers`` is the §6 cascade (outermost
        # first); ``orphans`` the descriptors rank 0 serves as a prefix.
        self.generation = 0
        self.world: Optional[int] = None if spec is None else int(spec.world)
        self.layers: list = []
        self.elastic_epoch: Optional[int] = None
        self.orphans: list = []
        #: memberships this client already streamed part of the current
        #: epoch under: ``{"rank","world","layers","orphans","samples"}``
        #: per generation ridden through — the degraded fallback replays
        #: exactly these prefixes (``local_epoch_indices``).
        self._trail: list = []
        self._epoch_samples = 0          # delivered watermark, current gen
        self._samples_epoch: Optional[int] = None
        self._leaving = False            # set by leave(): boundary = eof
        # -------- hot-standby failover (docs/RESILIENCE.md) --------
        #: the primary's standby, learned from WELCOME; the failover peer
        self.standby_address: Optional[tuple] = None
        #: highest fencing term seen; stamped on requests once > 0
        self.term = 0
        #: next HELLO asks the peer to promote (we are failing over)
        self._promote_on_connect = False
        #: perf_counter at failover start — observed into ``failover_ms``
        #: at the first successful WELCOME after it
        self._failover_t0: Optional[float] = None
        # -------- moving-horizon streaming (docs/STREAMING.md) --------
        #: attach-only (feeder) mode: HELLO binds the namespace without
        #: claiming a rank lease — a feeder holding a lease would count
        #: as a permanent straggler and deadlock the advance barrier
        self._attach = bool(attach)
        #: stable feeder id + monotonic per-append sequence: every retry
        #: of one logical APPEND carries the same ``(feeder, stream_seq)``
        #: pair, so a reply lost on the wire is re-answered as a
        #: duplicate, never double-counted.  The id must never repeat
        #: within a process lifetime — ``id(self)`` would, once a dead
        #: feeder is collected and its address reused, silently dedup a
        #: NEW feeder's first append as a replay
        with _FEEDER_LOCK:
            global _FEEDER_SEQ
            _FEEDER_SEQ += 1
            self._feeder = f"{os.getpid()}-{_FEEDER_SEQ}"
        self._stream_seq = -1

    # ----------------------------------------------------------- connection
    #: dial → redirect hops one ``_connect`` tolerates before handing the
    #: churn (a staggered cross-shard commit ping-pongs a migrating rank
    #: between the old and new owner) to the retry layer's paced loop
    _MAX_REDIRECT_HOPS = 6

    def _adopt_shard_map(self, wire) -> bool:
        """Version-gated map adoption: during a staggered cross-shard
        commit both the old and the new owner refuse a migrating rank,
        each attaching its own map — only a version >= ours may replace
        the adopted one (docs/SHARDING.md)."""
        if not wire:
            return False
        cur = self.shard_map
        if cur is not None and \
                int(wire.get("version", 1)) < int(cur.get("version", 1)):
            return False
        self.shard_map = dict(wire)
        return True

    def _shard_owner_addr(self, rank) -> Optional[tuple]:
        """The owning shard's address per the adopted map (``None``
        without a map, or when the map has no address for it); rankless
        auto-claim clients go to the first non-empty slice."""
        m = self.shard_map
        if m is None:
            return None
        for sh in m.get("shards", ()):
            lo, hi = int(sh["ranks"][0]), int(sh["ranks"][1])
            if hi <= lo:
                continue
            a = sh.get("addr")
            if rank is None:
                if a is not None:
                    return _parse_address(tuple(a))
                continue
            if lo <= int(rank) < hi:
                return None if a is None else _parse_address(tuple(a))
        return None

    def _on_wrong_shard(self, hdr: dict) -> None:
        """A shard refused our rank: adopt the attached (fresh) map and
        re-point at the owner — falling back to the router when the map
        carries no address for it."""
        self._adopt_shard_map(hdr.get("shard_map"))
        self.metrics.inc("wrong_shard_redirects", self.rank)
        target = self._shard_owner_addr(self.rank)
        if target is None:
            target = self._router_address
        if target is not None and target != self.address:
            self.close()
            self.address = target

    def _adopt_cell_directory(self, wire) -> bool:
        """Version-gated directory adoption — the ``_adopt_shard_map``
        rule one layer up: a stale wire copy riding a delayed refusal
        must never roll the global namespace back."""
        if not wire:
            return False
        cur = self.cell_directory
        if cur is not None and \
                int(wire.get("version", 1)) < int(cur.get("version", 1)):
            return False
        self.cell_directory = dict(wire)
        return True

    def _cell_addr(self, cell) -> Optional[tuple]:
        d = self.cell_directory
        if d is None or cell is None:
            return None
        a = (d.get("cells") or {}).get(str(cell))
        return None if a is None else _parse_address(tuple(a))

    def _home_cell(self) -> Optional[str]:
        """Our tenant's home cell per the adopted directory (the
        directory default when no explicit row names us)."""
        d = self.cell_directory
        if d is None:
            return None
        tenant = self.tenant
        if tenant is None and self.expected_spec is not None:
            tenant = tenant_id_for(
                self.expected_spec.fingerprint(include_world=False))
        if tenant is not None:
            home = (d.get("tenants") or {}).get(str(tenant))
            if home is not None:
                return home
        return d.get("default")

    def _on_wrong_cell(self, hdr: dict) -> None:
        """A cell refused our tenant: adopt the attached (fresh)
        directory and re-point at the home cell's entry address
        (docs/FEDERATION.md "Cell directory")."""
        self._adopt_cell_directory(hdr.get("cell_directory"))
        self.metrics.inc("wrong_cell_redirects", self.rank)
        target = self._cell_addr(hdr.get("home")) or \
            self._cell_addr(self._home_cell())
        if target is not None and target != self.address:
            self.close()
            self.address = target

    def _connect(self) -> None:
        last_refusal = None
        for _ in range(self._MAX_REDIRECT_HOPS):
            done, last_refusal = self._connect_once()
            if done:
                return
        if last_refusal is not None:
            # still ping-ponging (a staggered commit in flight): surface
            # the typed refusal so the retry layer paces the re-route
            raise _typed_error(last_refusal.get("code", "wrong_shard"),
                               last_refusal.get("detail", ""), last_refusal)
        raise ServiceUnavailable(
            f"still redirected toward {self.address} after "
            f"{self._MAX_REDIRECT_HOPS} hops; the shard map may be "
            "missing addresses")

    def _connect_once(self):
        """One dial + HELLO.  Returns ``(True, None)`` once a data-plane
        WELCOME is adopted; ``(False, refusal-or-None)`` when a router
        WELCOME or a ``wrong_shard`` refusal re-pointed ``self.address``
        at the owning shard (the caller loops, bounded)."""
        sock = socket.create_connection(self.address, timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self.timeout)
        hello = {
            "proto": P.PROTOCOL_VERSION,
            "rank": -1 if self.rank is None else self.rank,
            "batch": self.batch,
        }
        if self.term > 0:
            hello["term"] = self.term
        if self._promote_on_connect:
            # failing over: ask the standby to promote (it will, once its
            # replication feed has been stale for repl_feed_timeout)
            hello["failover"] = True
        if self.expected_spec is not None:
            # world-stripped: under elastic membership the server's world
            # drifts legitimately; only the stream-shaping config must match
            hello["spec_fingerprint"] = \
                self.expected_spec.fingerprint(include_world=False)
            # the full wire spec lets a multi-tenant daemon CREATE the
            # tenant on first contact (docs/SERVICE.md "Tenancy"); a
            # single-tenant daemon ignores it
            hello["spec"] = self.expected_spec.to_wire()
        if self.tenant is not None:
            hello["tenant"] = self.tenant
        if self._attach:
            # feeder mode: admit the namespace only — no rank lease
            hello["attach"] = True
        try:
            P.send_msg(sock, P.MSG_HELLO, hello)
            msg, header, _ = P.recv_msg(sock)
        except BaseException:
            sock.close()
            raise
        if msg == P.MSG_ERROR:
            sock.close()
            if header.get("code") == "wrong_shard":
                self._on_wrong_shard(header)
                return False, header
            if header.get("code") == "wrong_cell":
                # our tenant is homed at another cell: re-point at its
                # entry address and loop (docs/FEDERATION.md)
                self._on_wrong_cell(header)
                return False, header
            raise _typed_error(header.get("code", "error"),
                               header.get("detail", ""), header)
        if self._attach and msg == P.MSG_OK:
            # attach-only HELLO is answered OK (not WELCOME): adopt the
            # tenant binding and keep the leaseless connection
            t = header.get("tenant")
            if t is not None:
                self.tenant = str(t)
            self._sock = sock
            self._promote_on_connect = False
            return True, None
        if msg != P.MSG_WELCOME:
            sock.close()
            raise P.ProtocolError(
                f"expected WELCOME, got {P.msg_name(msg)}"
            )
        c = header.get("cell")
        if c is not None:
            # federated deployment: remember the serving cell and adopt
            # the directory BEFORE the router early-return — a router
            # WELCOME carries the namespace too (docs/FEDERATION.md)
            self.cell = str(c)
            self._adopt_cell_directory(header.get("cell_directory"))
        if header.get("router"):
            # a ShardRouter answered: it never serves data — remember it,
            # adopt the map it carries and direct-connect the owning
            # shard (docs/SHARDING.md)
            sock.close()
            self._router_address = self.address
            self._adopt_shard_map(header.get("shard_map"))
            target = self._shard_owner_addr(self.rank)
            if target is None or target == self.address:
                raise ServiceUnavailable(
                    f"router at {self.address} advertised no shard "
                    f"address for rank {self.rank}")
            self.address = target
            return False, None
        sm = header.get("shard_map")
        if sm is not None:
            self._adopt_shard_map(sm)
        self.rank = int(header["rank"])
        t = header.get("tenant")
        if t is not None:
            self.tenant = str(t)
        self.spec_wire = header.get("spec")
        self.server_epoch = header.get("epoch")
        sb = header.get("standby")
        if sb is not None:
            self.standby_address = _parse_address(sb)
        t = header.get("term")
        if t is not None:
            self.term = max(self.term, int(t))
        mi = header.get("max_inflight")
        if mi is not None:
            self._server_max_inflight = max(1, int(mi))
        bh = header.get("batch_hint")
        if bh is not None:
            # autopilot-tuned batch suggestion (docs/AUTOPILOT.md);
            # adopted at the next clean epoch boundary, never mid-epoch
            # — the seq unit IS the batch size
            self._batch_hint = max(1, int(bh))
        self._adopt_membership(header)
        self._sock = sock
        self._promote_on_connect = False
        if self._failover_t0 is not None:
            self.metrics.registry.histogram("failover_ms").observe(
                (time.perf_counter() - self._failover_t0) * 1e3)
            self._failover_t0 = None
        return True, None

    def _adopt_membership(self, header: dict) -> None:
        """Take on the membership a WELCOME or ``resharded`` error carries.

        When the generation advanced past ours and we had already
        delivered part of the current epoch, the outgoing membership is
        pushed onto the trail with its exact delivered watermark — the
        degraded fallback later replays precisely those prefixes."""
        if "generation" not in header:
            return
        gen = int(header["generation"])
        if gen < self.generation:
            # a behind peer (a standby promoted before the dead primary
            # shipped its last commit): keep our newer membership — the
            # stream loop flushes our acks so the peer catches up
            return
        if gen > self.generation:
            if self.world is not None and self.rank is not None:
                self._trail.append({
                    "rank": self.rank, "world": self.world,
                    "layers": [tuple(map(int, l)) for l in self.layers],
                    "orphans": list(self.orphans),
                    "samples": int(self._epoch_samples),
                })
            self._epoch_samples = 0
            if self._samples_epoch is not None:
                # only a client that was already streaming rode through;
                # a fresh HELLO adopting a resharded server's membership
                # didn't cross a world change
                self.metrics.inc("reshards_ridden", self.rank)
        self.generation = gen
        self.world = int(header["world"])
        self.layers = [tuple(map(int, l)) for l in header.get("layers", [])]
        ee = header.get("elastic_epoch")
        self.elastic_epoch = None if ee is None else int(ee)
        self.orphans = list(header.get("orphans", []))

    def _ensure_connected(self) -> None:
        if self._sock is None:
            self._connect()

    def probe(self) -> bool:
        """One connection attempt, no retries: is a daemon serving right
        now?  The degraded-mode loader polls this to decide when to
        re-attach; a False answer leaves the client closed and costs one
        refused TCP dial."""
        if self._sock is not None:
            return True
        try:
            self._connect()
            self.retry_policy.record_success()
            return True
        except (OSError, ServiceError, P.ProtocolError):
            # includes ConnectionError/timeout; fatal config mismatches
            # also read as "not attachable" here — the next real stream
            # attempt surfaces them loudly
            self.close()
            return False

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def __enter__(self) -> "ServiceIndexClient":
        self._ensure_connected()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------------- retry
    def _rpc(self, msg_type: int, header: dict):
        """One request → reply, retrying across connection loss.

        Every message this client sends is idempotent, so a reconnect +
        replay can never double-deliver.  All waiting rides the unified
        :class:`RetryPolicy` (full-jittered exponential backoff under one
        per-operation deadline) — reconnects and lease races alike, so N
        ranks dropped by one restart never retry in lockstep.  A server
        ``throttle``/``draining`` reply sleeps at least the
        server-suggested interval.  The policy's circuit breaker makes a
        freshly-exhausted dependency fail fast at the *next* operation's
        entry instead of burning its full deadline again.

        One telemetry span covers the whole operation — retries included —
        so its ``trace`` context, stamped into the request header, is by
        construction the same across every attempt of one logical request
        (docs/OBSERVABILITY.md).  The ``rpc_ms`` histogram observes the
        operation wall time whether or not tracing is on."""
        t0 = time.perf_counter()
        if not _tel_enabled():
            # tracing off: skip span construction entirely — no kwargs
            # dict, no msg-name lookup, no thread-local push on the
            # per-request hot path
            try:
                return self._rpc_attempts(msg_type, header)
            finally:
                self.metrics.registry.histogram("rpc_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
        with _span("client.rpc", msg=P.msg_name(msg_type),
                   rank=self.rank) as sp:
            ctx = sp.ids
            if ctx is not None:
                header["trace"] = ctx
            try:
                return self._rpc_attempts(msg_type, header)
            finally:
                self.metrics.registry.histogram("rpc_ms").observe(
                    (time.perf_counter() - t0) * 1e3)

    def _rpc_attempts(self, msg_type: int, header: dict):
        pol = self.retry_policy
        if not pol.allow():
            raise ServiceUnavailable(
                f"circuit open toward {self.address} (recent operations "
                "exhausted their retry deadlines); next probe after "
                f"{pol.breaker_reset}s"
            )
        op = pol.begin()
        # peers whose retry budget this operation already opened: the
        # current one now, the standby if we fail over to it.  Failover
        # is a per-PEER budget (``_begin_failover`` calls ``begin()``
        # again) — the dead primary's exhausted deadline never bills the
        # standby.
        tried = {self.address}
        while True:
            try:
                try:
                    self._ensure_connected()
                except ServiceError as exc:
                    if exc.code == "standby":
                        op = self._on_standby(exc, op, tried)
                        continue
                    if exc.code == "fenced":
                        op = self._on_fenced(exc.header, op, tried)
                        continue
                    if exc.code == "tenant_admission":
                        # typed admission backpressure: the tenant is at a
                        # quota (ranks / creation burst) — wait at least
                        # the server-suggested interval and re-HELLO
                        self.metrics.inc("admission_waits", self.rank)
                        retry_s = float(
                            exc.header.get("retry_ms", 50)) / 1e3
                        if not op.pause(min_delay=retry_s):
                            raise
                        continue
                    if exc.code in ("wrong_shard", "router_route",
                                    "wrong_cell"):
                        # shard-map churn (a staggered cross-shard commit
                        # ping-pongs a migrating rank between owners), an
                        # injected route fault, or a cross-cell redirect
                        # mid-flip: the re-route already happened in
                        # _connect — pace and re-dial
                        retry_s = float(
                            exc.header.get("retry_ms", 25)) / 1e3
                        if not op.pause(min_delay=retry_s):
                            raise
                        continue
                    if exc.code not in ("rank_taken", "not_owner"):
                        raise
                    # our own just-dropped lease may not have been released
                    # yet (the server notices the dead conn asynchronously);
                    # back off and re-HELLO like any other lease race
                    if not op.pause():
                        raise
                    continue
                if "rank" in header:
                    # the lazy connect (or a re-HELLO after lease loss) is
                    # what assigns auto-claimed ranks — stamp the current
                    # one on every attempt
                    header["rank"] = self.rank
                if self.term > 0:
                    # the fencing term rides every post-promotion request:
                    # a zombie primary must refuse, not serve, it
                    header["term"] = self.term
                if self.tenant is not None:
                    # the tenant binding rides every request: a server-side
                    # conn that lost its HELLO binding (or a promoted
                    # standby) still routes to the right namespace
                    header["tenant"] = self.tenant
                P.send_msg(self._sock, msg_type, header,
                           site="service.send")
                reply, rheader, payload = P.recv_msg(self._sock,
                                                     site="service.recv")
            except (ConnectionError, socket.timeout, OSError,
                    P.ProtocolError) as exc:
                self.close()
                self.metrics.inc("reconnects", self.rank)
                pol.record_failure()
                if not op.pause():
                    peer = self._failover_peer(tried)
                    if peer is None:
                        raise ServiceUnavailable(
                            f"no server at {self.address} after "
                            f"{op.attempts} attempts ({exc!r})"
                        ) from None
                    op = self._begin_failover(peer, tried)
                continue
            pol.record_success()
            if reply == P.MSG_ERROR:
                code = rheader.get("code", "error")
                if code == "throttle":
                    self.metrics.inc("throttled", self.rank)
                    time.sleep(float(rheader.get("retry_ms", 20)) / 1e3)
                    continue
                if code == "draining":
                    # graceful shutdown in progress: drop the conn and come
                    # back after (at least) the server-suggested interval
                    self.close()
                    self.metrics.inc("drain_redirects", self.rank)
                    retry_s = float(rheader.get("retry_ms", 100)) / 1e3
                    if not op.pause(min_delay=retry_s):
                        raise ServiceUnavailable(
                            f"server at {self.address} is draining and did "
                            "not return within the retry deadline"
                        )
                    continue
                if code == "not_owner" or code == "rank_taken":
                    # lease lost (eviction or a racing claimant): re-HELLO
                    # once the stale claimant's lease clears; fatal only if
                    # it never does within the deadline
                    self.close()
                    if not op.pause():
                        raise ServiceError(code, rheader.get("detail", ""),
                                           rheader)
                    continue
                if code == "reshard":
                    # a barrier is freezing/draining: wait it out on this
                    # side of the retry deadline — the post-commit replay
                    # of the same request is exactly-once by construction
                    self.metrics.inc("reshard_waits", self.rank)
                    retry_s = float(rheader.get("retry_ms", 50)) / 1e3
                    if not op.pause(min_delay=retry_s):
                        raise ReshardInProgress(
                            f"reshard barrier at {self.address} did not "
                            "commit within the retry deadline"
                        )
                    continue
                if code == "wrong_shard":
                    # our rank moved shards (a cross-shard reshard
                    # commit): adopt the attached map, re-point at the
                    # owner and re-HELLO there (docs/SHARDING.md)
                    self.close()
                    self._on_wrong_shard(rheader)
                    retry_s = float(rheader.get("retry_ms", 25)) / 1e3
                    if not op.pause(min_delay=retry_s):
                        raise ServiceError(code, rheader.get("detail", ""),
                                           rheader)
                    continue
                if code == "wrong_cell":
                    # our tenant migrated cells mid-stream: adopt the
                    # fresh directory, re-point at the new home cell and
                    # re-HELLO there — the cursor law makes the replay
                    # exactly-once (docs/FEDERATION.md)
                    self.close()
                    self._on_wrong_cell(rheader)
                    retry_s = float(rheader.get("retry_ms", 25)) / 1e3
                    if not op.pause(min_delay=retry_s):
                        raise ServiceError(code, rheader.get("detail", ""),
                                           rheader)
                    continue
                if code in ("horizon_pending", "horizon_advance",
                            "stream_append"):
                    # moving-horizon backpressure (docs/STREAMING.md):
                    # the horizon is not fully appended yet, the advance
                    # barrier is waiting on straggler ranks (or an
                    # injected abort rolled it back), or an injected
                    # append fault fired.  All retryable: GET_BATCH/
                    # GET_CAPABILITY replays are exactly-once by the
                    # cursor law, and APPEND replays are deduplicated by
                    # ``(feeder, stream_seq)``.
                    self.metrics.inc("stream_waits", self.rank)
                    retry_s = float(rheader.get("retry_ms", 25)) / 1e3
                    if not op.pause(min_delay=retry_s):
                        raise ServiceError(code, rheader.get("detail", ""),
                                           rheader)
                    continue
                if code == "capability_issue":
                    # transient issuance refusal (an injected fault, or
                    # a daemon mid-hiccup): GET_CAPABILITY is idempotent
                    # — pace by the server's hint and replay
                    retry_s = float(rheader.get("retry_ms", 50)) / 1e3
                    if not op.pause(min_delay=retry_s):
                        raise ServiceError(code, rheader.get("detail", ""),
                                           rheader)
                    continue
                if code in ("router_route", "shard_barrier"):
                    # transient control-plane trouble (an injected route
                    # fault, or a cross-shard barrier fan-out that did
                    # not complete): every frame we send is idempotent,
                    # so pace and replay
                    retry_s = float(rheader.get("retry_ms", 50)) / 1e3
                    if not op.pause(min_delay=retry_s):
                        raise ServiceError(code, rheader.get("detail", ""),
                                           rheader)
                    continue
                if code == "standby":
                    # the peer demoted/never promoted under us
                    self.close()
                    op = self._on_standby(
                        ServiceError(code, rheader.get("detail", ""),
                                     rheader), op, tried)
                    continue
                if code == "fenced":
                    self.close()
                    op = self._on_fenced(rheader, op, tried)
                    continue
                raise _typed_error(code, rheader.get("detail", ""), rheader)
            return reply, rheader, payload

    # ----------------------------------------------------------- failover
    def _failover_peer(self, tried) -> Optional[tuple]:
        """The peer this operation has not yet spent a budget on (the
        standby learned at WELCOME), or None when every peer is spent —
        the caller's signal that both peers are down.  On a sharded
        deployment the router is the peer of last resort: a merged-out
        shard's address dies for good, but the router's fresh map
        re-points us at whichever shard owns our rank now."""
        sb = self.standby_address
        if sb is not None and sb not in tried:
            return sb
        ra = self._router_address
        if ra is not None and ra not in tried and ra != self.address:
            return ra
        # cell-aware dial ladder (docs/FEDERATION.md): past the in-cell
        # peers, re-look-up our home cell in the adopted directory, then
        # knock on its DR partner — the whole home cell may be gone
        home = self._home_cell()
        for cell in (home, self._dr_cell(home)):
            a = self._cell_addr(cell)
            if a is not None and a not in tried and a != self.address:
                return a
        return None

    def _dr_cell(self, cell) -> Optional[str]:
        d = self.cell_directory
        if d is None or cell is None:
            return None
        return (d.get("dr") or {}).get(str(cell))

    def _begin_failover(self, peer: tuple, tried: set):
        """Point the client at ``peer`` under a FRESH retry deadline and
        budget — the whole point of per-peer budgets: a standby must get
        its full window, not the dead primary's leftovers."""
        self.close()
        self.address = peer
        tried.add(peer)
        self._promote_on_connect = True
        if self._failover_t0 is None:
            self._failover_t0 = time.perf_counter()
        self.metrics.inc("failovers", self.rank)
        # the new peer gets a clean breaker slate too: the consecutive
        # failures that exhausted the old peer say nothing about this one
        self.retry_policy.record_success()
        return self.retry_policy.begin()

    def _on_standby(self, exc: ServiceError, op, tried):
        """The peer answered ``standby``.  A healthy pair redirects us to
        its primary; mid-failover we keep knocking (the standby promotes
        once its feed goes stale) until this peer's budget is spent."""
        hdr = exc.header
        t = hdr.get("term")
        if t is not None:
            self.term = max(self.term, int(t))
        primary = hdr.get("primary")
        if not self._promote_on_connect and primary is not None:
            redirect = _parse_address(primary)
            if redirect != self.address and redirect not in tried:
                self.close()
                self.address = redirect
                tried.add(redirect)
                return op
        if not op.pause(min_delay=float(hdr.get("retry_ms", 100)) / 1e3):
            peer = self._failover_peer(tried)
            if peer is None:
                raise exc
            return self._begin_failover(peer, tried)
        return op

    def _on_fenced(self, hdr: dict, op, tried):
        """The peer answered ``fenced``: a promotion happened.  Adopt the
        winning term; when the refuser itself keeps serving at that term
        (``serving=true`` — our stamp was merely stale) just retry it,
        otherwise it is a zombie and we fail over to the winner."""
        t = int(hdr.get("term", 0))
        if t > self.term:
            self.term = t
        self.metrics.inc("fenced_replies", self.rank)
        if hdr.get("serving"):
            return op
        peer = self._failover_peer(tried)
        if peer is None:
            raise FencedError(
                t, f"every reachable peer is fenced below term {t} and "
                   "no serving primary is attached", hdr)
        return self._begin_failover(peer, tried)

    # ------------------------------------------------------------- batches
    def _pipe_limit(self) -> int:
        """The effective lookahead window: the ``lookahead`` knob,
        clamped by the server's WELCOME-advertised ``max_inflight`` and
        by any cap learned from a throttle refusal mid-pipeline."""
        lim = self.lookahead
        if self._server_max_inflight is not None:
            lim = min(lim, self._server_max_inflight)
        if self._pipe_cap is not None:
            lim = min(lim, self._pipe_cap)
        return max(1, lim)

    def _pipe_header(self, epoch: int, seqno: int, ack: int,
                     gen: int) -> dict:
        h = {"rank": self.rank, "epoch": epoch, "seq": seqno,
             "ack": ack, "gen": gen}
        if self.term > 0:
            h["term"] = self.term
        if self.tenant is not None:
            h["tenant"] = self.tenant
        return h

    def _drain_replies(self, sock, n: int) -> None:
        """Read and discard the replies to still-in-flight pipelined
        requests — the server answers every request exactly once, in
        order, so the count is known.  Discarded batches are *unacked*:
        re-requesting them through the guarded path is exactly-once by
        construction (the cursor only advances on yield)."""
        for _ in range(n):
            P.recv_msg(sock)

    def _pipelined_batches(self, epoch: int, seq: int, gen: int,
                           rejects: int):
        """The fused steady-state serve path: keep up to
        ``_pipe_limit()`` GET_BATCH requests in flight, topping the
        window up with ONE coalesced send per delivered batch
        (``P.send_msgs``) so the next reply is already in the socket
        buffer while the consumer holds the current batch.

        Exactly-once survives any failure here because the cursor
        advances only when a batch is yielded: every in-flight request
        past the cursor is unacked, so tearing the connection (or
        discarding queued replies after a typed error) merely re-requests
        those seqs through the guarded `_rpc` path.

        Returns ``(done, seq, rejects)``; ``done`` means the epoch
        stream completed.  Any error/typed refusal returns ``done=False``
        and lets ``epoch_batches`` recover through the guarded path.
        The terminal EOF poll is ALWAYS left to the guarded path: its
        ack (the epoch's last delivered batch) gates elastic drain
        barriers, so it must ride `_rpc`'s reshard-wait machinery, not a
        fire-and-forget pipeline slot."""
        sock = self._sock
        hist = self.metrics.registry.histogram("step_serve_ms")
        pending = deque()        # requested-but-unconsumed seqs, in order
        next_req = seq
        bound = None             # request-seq bound once total is known
        hb_seq = None            # seq of the request carrying _pending_hb
        ramp = 1                 # slow-start: the window grows one per
        #                          delivered batch, so a cold epoch is
        #                          never one indivisible burst (and the
        #                          stream total is learned before more
        #                          than one request is committed)
        try:
            while True:
                # re-read the clamp every iteration: a failover re-HELLO
                # can adopt a SMALLER max_inflight mid-stream, and an
                # already-ramped window must shrink to it — no new
                # request is sent until the in-flight span drains below
                # the new limit, so the standby never sees a window the
                # dead primary negotiated
                w = self._pipe_limit()
                if ramp > w:
                    ramp = w
                msgs = []
                while len(pending) < min(w, ramp) and (bound is None
                                                       or next_req < bound):
                    h = self._pipe_header(epoch, next_req, seq - 1, gen)
                    if self._pending_hb is not None and hb_seq is None:
                        h["hb"] = list(self._pending_hb)
                        hb_seq = next_req
                    msgs.append((P.MSG_GET_BATCH, h))
                    pending.append(next_req)
                    next_req += 1
                if msgs:
                    F.fire("client.pipeline")
                    self.metrics.inc("rpcs_per_step", self.rank,
                                     value=len(msgs))
                    P.send_msgs(sock, msgs, site="service.send")
                if not pending:
                    # every real batch is delivered: hand the terminal
                    # EOF poll (and its drain-gating ack) to the guarded
                    # path
                    return False, seq, rejects
                t0 = time.perf_counter()
                reply, rheader, payload = P.recv_msg(sock,
                                                     site="service.recv")
                expect = pending.popleft()
                if reply == P.MSG_ERROR:
                    code = rheader.get("code", "error")
                    if code == "throttle":
                        # server window smaller than ours (a peer that
                        # predates the WELCOME advertisement): shrink
                        # and let the guarded path resume
                        self.metrics.inc("throttled", self.rank)
                        self._pipe_cap = max(1, (len(pending) + 1) // 2)
                    self._drain_replies(sock, len(pending))
                    return False, seq, rejects
                if reply != P.MSG_BATCH or int(rheader.get("seq",
                                                           -1)) != expect:
                    raise P.ProtocolError(
                        f"pipelined reply out of order: expected BATCH "
                        f"seq {expect}, got {P.msg_name(reply)} seq "
                        f"{rheader.get('seq')}")
                if expect == hb_seq:
                    # the piggybacked previous-epoch ack landed
                    self._pending_hb = None
                    hb_seq = None
                if rheader.get("end") is not None:
                    self._epoch_samples = max(self._epoch_samples,
                                              int(rheader["end"]))
                if rheader.get("eof"):
                    # only an entry-point request (resume at the epoch
                    # tail) can draw an EOF here — its own ack was the
                    # terminal one, so the stream is complete
                    self._drain_replies(sock, len(pending))
                    return True, seq, rejects
                try:
                    arr = P.decode_indices(rheader, payload)
                except P.ChecksumError:
                    rejects += 1
                    self.metrics.inc("checksum_rejects", self.rank)
                    if rejects > _MAX_CHECKSUM_REJECTS:
                        raise
                    # unacked: the guarded path re-requests this seq and
                    # everything queued behind it
                    self._drain_replies(sock, len(pending))
                    return False, seq, rejects
                rejects = 0
                ramp = min(w, ramp + 1)
                if bound is None and rheader.get("total") is not None:
                    # cap requests at the last REAL batch; the EOF poll
                    # stays on the guarded path (see docstring)
                    bound = -(-int(rheader["total"]) // self.batch)
                self.metrics.inc("batches_served", self.rank)
                seq += 1
                self._cursor = {"epoch": epoch, "seq": seq}
                hist.observe((time.perf_counter() - t0) * 1e3)
                yield arr
        except P.ChecksumError:
            raise
        except (ConnectionError, socket.timeout, OSError,
                P.ProtocolError):
            # the connection (and every queued reply) is gone; all of it
            # was unacked, so the guarded path replays it exactly-once
            self.close()
            self.metrics.inc("reconnects", self.rank)
            return False, seq, rejects

    def epoch_batches(self, epoch: int, *,
                      start_seq: int = 0) -> Iterator[np.ndarray]:
        """Stream the rank's batches for ``epoch`` from ``start_seq`` on.

        On a healthy connection the stream is *pipelined*: up to
        ``lookahead`` GET_BATCH requests ride in flight (clamped by the
        server's ``max_inflight``), topped up with one coalesced send
        per delivered batch, so the per-step cost is one socket read of
        an already-buffered reply.  Each request still acks everything
        this generator already yielded — the in-flight window is exactly
        the unacked span the server's throttle gate admits — and the
        previous epoch's terminal ack piggybacks on the next epoch's
        first request (header field ``hb``) instead of a dedicated EOF
        poll.  Any fault or typed refusal drops to the guarded
        request-reply path below, which re-requests from the cursor:
        delivery stays exactly-once because the cursor advances only on
        yield, never on receipt.

        Rides through reshards: a ``resharded`` reply (or reconnect) makes
        the generator adopt the new membership, renegotiate a rank if its
        old one no longer exists, and continue with the post-reshard
        remainder — one contiguous exactly-once stream across the world
        change.  It ends early (without error) only when the rank *left*
        (terminal drain eof) or the shrunken world has no free slot left
        (``membership_lost`` in the metrics)."""
        epoch, seq = int(epoch), int(start_seq)
        if (self.auto_batch and seq == 0 and self._batch_hint is not None
                and int(self._batch_hint) != self.batch):
            # clean boundary: nothing is delivered at this batch
            # geometry yet.  The lease's batch is bound at HELLO, so
            # adopt by re-dialing — the next request re-HELLOs with the
            # new size, and the queued previous-epoch ``hb`` ack still
            # rides that first request (docs/AUTOPILOT.md)
            self.close()
            self.batch = int(self._batch_hint)
        self._cursor = {"epoch": epoch, "seq": seq}
        if self._samples_epoch != epoch:
            # new epoch: the trail describes the previous epoch's
            # deliveries — start fresh
            self._trail = []
            self._epoch_samples = 0
            self._samples_epoch = epoch
        rejects = 0
        gen = self.generation
        behind_t0 = None
        hist = self.metrics.registry.histogram("step_serve_ms")
        while True:
            if self.generation != gen:
                # a reconnect inside _rpc adopted a newer membership
                # (WELCOME on our still-valid rank): continue from the
                # head of the post-reshard remainder
                gen, seq = self.generation, 0
                self._cursor = {"epoch": epoch, "seq": seq}
            if (self._sock is not None and not self._leaving
                    and self._pipe_limit() > 1 and not _tel_enabled()):
                # fused fast path (tracing keeps the one-span-per-RPC
                # guarded path for attribution; a leaving rank must see
                # its terminal drain eof, served by the guarded path)
                done, seq, rejects = yield from self._pipelined_batches(
                    epoch, seq, gen, rejects)
                if done:
                    return
                if self.generation != gen:
                    continue
            # guarded request-reply path: recovery, lookahead=1, tracing
            t_req = time.perf_counter()
            req = {"rank": self.rank, "epoch": epoch, "seq": seq,
                   "ack": seq - 1, "gen": gen}
            if self._pending_hb is not None:
                req["hb"] = list(self._pending_hb)
            self.metrics.inc("rpcs_per_step", self.rank)
            try:
                reply, header, payload = self._rpc(P.MSG_GET_BATCH, req)
            except ServiceError as exc:
                if exc.code == "resharded":
                    if self._leaving:
                        # we asked to LEAVE and the barrier committed:
                        # our pre-barrier allocation is fully served
                        # (the commit required our drain), so this is
                        # the stream's end, not a membership to ride
                        return
                    # the world changed underneath us: adopt the carried
                    # membership and continue the stream under it
                    prev_gen = self.generation
                    self._adopt_membership(exc.header)
                    if self.generation == prev_gen:
                        # a failover raced a commit the dead primary never
                        # shipped: the promoted standby is still draining
                        # the barrier we already rode through.  Flush our
                        # pre-barrier delivered-ack watermark so its drain
                        # can complete, then retry at the SAME cursor —
                        # resetting seq here would double-serve.
                        if behind_t0 is None:
                            behind_t0 = time.monotonic()
                        elif (time.monotonic() - behind_t0
                                > self.reconnect_timeout):
                            raise ReshardInProgress(
                                f"peer at {self.address} stayed a "
                                "generation behind past the reconnect "
                                "deadline") from None
                        self._queue_trail_ack(epoch)
                        time.sleep(min(0.05, self.backoff_base))
                        continue
                    behind_t0 = None
                    if not (self.rank is not None and self.world is not None
                            and self.rank < self.world):
                        if not exc.header.get("vacated"):
                            # shrunk out with no slot vacated for a
                            # rejoin: the commit already drained (or
                            # orphaned) our whole pre-barrier span, and
                            # claiming a slot a survivor merely finished
                            # and freed would re-serve its stream — seen
                            # at failover, when the survivor finishes
                            # before our reconnect budget sends us here
                            self.metrics.inc("membership_lost")
                            return
                        # our rank no longer exists — auto-claim the
                        # vacated slot (typically the leaver's)
                        self.close()
                        self.rank = None
                    gen, seq = self.generation, 0
                    self._cursor = {"epoch": epoch, "seq": seq}
                    continue
                if exc.code == "no_rank" and self.rank is None:
                    # the world shrank past us and every surviving slot is
                    # claimed: our share of the epoch belongs to others now
                    self.metrics.inc("membership_lost")
                    return
                raise
            if reply != P.MSG_BATCH:
                raise P.ProtocolError(
                    f"expected BATCH, got {P.msg_name(reply)}"
                )
            if "hb" in req:
                # the piggybacked previous-epoch ack landed server-side
                self._pending_hb = None
            if header.get("eof"):
                # a terminal drain eof additionally carries left=True; in
                # both cases the stream for this rank is complete
                if header.get("end") is not None:
                    self._epoch_samples = max(self._epoch_samples,
                                              int(header["end"]))
                return
            try:
                arr = P.decode_indices(header, payload)
            except P.ChecksumError:
                # the payload arrived corrupted; the reply is idempotent,
                # so reject it and re-request the SAME seq — the delivered
                # stream stays exact.  Persistent corruption is a broken
                # link, not a transient: give up after a few replays.
                rejects += 1
                self.metrics.inc("checksum_rejects", self.rank)
                if rejects > _MAX_CHECKSUM_REJECTS:
                    raise
                continue
            rejects = 0
            self.metrics.inc("batches_served", self.rank)
            # advance BEFORE yielding: once the consumer holds the batch it
            # counts as delivered, so a state_dict() taken between batches
            # resumes at the next one (exactly-once, not at-least-once)
            seq += 1
            self._cursor = {"epoch": epoch, "seq": seq}
            if header.get("end") is not None:
                # exact delivered watermark in the current generation's
                # stream — what the trail records at the next adoption
                self._epoch_samples = max(self._epoch_samples,
                                          int(header["end"]))
            hist.observe((time.perf_counter() - t_req) * 1e3)
            yield arr

    def epoch_indices(self, epoch: int) -> np.ndarray:
        """The rank's full epoch stream as one array — the drop-in for a
        local sampler's ``epoch_indices`` (``HostDataLoader`` consumes
        this when constructed with ``index_client=``)."""
        parts = list(self.epoch_batches(epoch))
        if not parts:
            return np.empty(0, dtype=np.int64)
        return np.concatenate(parts)

    # ----------------------------------------------------------- control ops
    def set_epoch(self, epoch: int, *, weights_delta=None) -> int:
        """Move the server to ``epoch``.  ``weights_delta`` (prioritized
        sampling specs only, docs/SAMPLING.md) is an additive per-source
        re-weight folded into the weights effective at the new epoch —
        the streaming ``weights_delta`` law applied at an epoch
        boundary.  Zero protocol bytes when omitted."""
        body = {"epoch": int(epoch)}
        if weights_delta is not None:
            body["weights_delta"] = [int(x) for x in weights_delta]
        _, header, _ = self._rpc(P.MSG_SET_EPOCH, body)
        self.server_epoch = int(header["epoch"])
        return self.server_epoch

    def heartbeat(self) -> int:
        """Keepalive; also carries the delivered-ack cursor, so an idle
        client still completes an elastic drain — the barrier commits on
        *acked* delivery, not on served bytes.  A queued ``hb`` ack (a
        trail-ack a behind peer still needs) piggybacks here too.
        Returns the server's current generation — the cheap
        membership-freshness probe the loader's boundary prefetch uses."""
        header = {"rank": self.rank}
        if self._cursor["epoch"] is not None:
            header["epoch"] = int(self._cursor["epoch"])
            header["ack"] = int(self._cursor["seq"]) - 1
        if self._pending_hb is not None:
            header["hb"] = list(self._pending_hb)
        _, rheader, _ = self._rpc(P.MSG_HEARTBEAT, header)
        if "hb" in header:
            self._pending_hb = None
        # capability-mode drain discovery: while a barrier drains, the
        # reply names this rank's drain watermark (additive field;
        # served-batch clients never see it)
        self._cap_drain = rheader.get("cap_drain")
        kn = rheader.get("knobs")
        if kn:
            self._adopt_knobs(kn)
        return int(rheader.get("generation", self.generation))

    def _adopt_knobs(self, kn: dict) -> None:
        """Adopt autopilot-tuned knobs riding a heartbeat reply
        (docs/AUTOPILOT.md).  ``max_inflight`` applies live — the
        pipelined top-up re-reads it on every send — while a batch
        hint waits for the next epoch boundary, because mid-epoch the
        seq unit is the batch and re-slicing delivered spans would
        break exactly-once."""
        mi = kn.get("max_inflight")
        if mi is not None:
            self._server_max_inflight = max(1, int(mi))
        bh = kn.get("batch_hint")
        if bh is not None:
            self._batch_hint = max(1, int(bh))

    def _queue_trail_ack(self, epoch: int) -> None:
        """Queue the pre-barrier ack watermark (the trail's last recorded
        delivery) as a piggybacked ``hb`` on the next request, so a
        generation-behind peer's inherited drain gate — which commits on
        *acked* delivery — can complete the barrier the dead primary
        never shipped the commit of, without a dedicated heartbeat RPC
        (the server applies ``hb`` before its generation check)."""
        if not self._trail:
            return
        samples = int(self._trail[-1].get("samples", 0))
        ack = -(-samples // self.batch) - 1  # ceil(samples/batch) - 1
        if ack < 0:
            return
        self._pending_hb = [int(epoch), ack]

    def snapshot(self) -> dict:
        _, header, _ = self._rpc(P.MSG_SNAPSHOT, {})
        return header["state"]

    def server_metrics(self) -> dict:
        _, header, _ = self._rpc(P.MSG_METRICS, {})
        return header["report"]

    def trace_dump(self, limit: int = 256) -> dict:
        """Pull the server's recent telemetry — the flight-recorder ring
        plus its open spans (docs/OBSERVABILITY.md).  Returns the
        TRACE_REPORT header: ``{"enabled": bool, "entries": [...]}``.
        ``entries`` is empty (not an error) when the server runs with
        tracing off."""
        _, header, _ = self._rpc(P.MSG_TRACE_DUMP, {"limit": int(limit)})
        return header

    # ------------------------------------------------------------- elastic
    def leave(self, grace_ms: Optional[int] = None) -> dict:
        """Preemption-notice drain (hook this to SIGTERM): ask the server
        to reshard the world down by one and drain this rank out.

        Returns the server's OK header; when its ``reshard`` field is
        True it carries ``target_world`` and this rank's
        ``target_samples`` drain watermark — keep consuming
        ``epoch_batches`` until the terminal eof so the barrier can
        commit.  ``grace_ms`` bounds how long the server waits for that
        drain before declaring this rank dead and orphaning the
        un-served remainder (``None`` = wait indefinitely)."""
        F.fire("client.leave")
        header = {"rank": self.rank}
        if grace_ms is not None:
            header["grace_ms"] = int(grace_ms)
        _, rheader, _ = self._rpc(P.MSG_LEAVE, header)
        self.metrics.inc("leaves", self.rank)
        if rheader.get("reshard"):
            # commit requires our drain, so by the time the generation
            # moves on we have served the full pre-barrier allocation —
            # the boundary IS our terminal eof, whether it arrives as the
            # drain eof or as a post-commit ``resharded`` reply
            self._leaving = True
        return rheader

    def reshard(self, new_world: int) -> dict:
        """Explicit mid-epoch world change: freeze a barrier at every
        rank's consumption watermark and repartition the remainder over
        ``new_world`` ranks (SPEC.md §6 cascade).  Returns the server's
        OK header (``committed`` is True when the barrier already
        drained — e.g. all ranks idle — and the new generation is live)."""
        _, rheader, _ = self._rpc(P.MSG_RESHARD, {"world": int(new_world)})
        return rheader

    # ----------------------------------------------------------- streaming
    def append(self, count: int, *, weights_delta=None) -> dict:
        """Feeder op (docs/STREAMING.md): extend the stream's append-only
        index space by ``count`` samples.  Exactly-once under the retry
        layer — one logical append carries one ``(feeder, stream_seq)``
        pair across every wire attempt, and the server answers a replay
        as ``duplicate`` without re-counting.  ``weights_delta`` is an
        additive per-source mixture re-weighting, folded in at the next
        horizon advance.  Feeders should connect with ``attach=True`` so
        they never hold a rank lease (a leased feeder would stall the
        advance barrier as a permanent straggler).  Returns the OK
        header: ``appended`` (absolute total), ``eligible`` (servable
        horizons) and ``epoch`` (the stream's current horizon)."""
        self._stream_seq += 1
        header = {"count": int(count), "stream_seq": int(self._stream_seq),
                  "feeder": self._feeder}
        if weights_delta is not None:
            header["weights_delta"] = [int(x) for x in weights_delta]
        _, rheader, _ = self._rpc(P.MSG_APPEND, header)
        return rheader

    def stream_batches(self, *, start_horizon: int = 0,
                       horizons: Optional[int] = None,
                       start_seq: int = 0) -> Iterator[np.ndarray]:
        """The epochless consumption loop: serve horizon generations
        ``start_horizon, start_horizon + 1, ...`` back to back, each via
        :meth:`epoch_batches`.  No explicit advance call exists — the
        first request naming the next horizon *is* the ack-gated advance
        barrier, and the typed ``horizon_pending``/``horizon_advance``
        refusals pace this generator until the horizon is appended and
        every rank has drained the previous one (docs/STREAMING.md).
        Unbounded when ``horizons`` is None; yields stay exactly-once
        across faults, failover and mid-stream reshards exactly as one
        ``epoch_batches`` stream does.  A reshard that commits around a
        horizon boundary re-deals the horizon's pooled remainder over
        the NEW world — possibly to a rank that already finished it —
        so this loop re-enters the horizon whenever the generation moved
        under it (the post-commit array holds only the un-delivered
        share, making the re-entry exactly-once by construction)."""
        g = int(start_horizon)
        seq = int(start_seq)
        end = None if horizons is None else g + int(horizons)
        regen_retry = -1  # generation already backed up for, at most once
        while end is None or g < end:
            g_gen = self.generation
            try:
                yield from self.epoch_batches(g, start_seq=seq)
            except ServiceError as exc:
                if (exc.code == "horizon_advance" and g > 0
                        and self.generation != g_gen
                        and self.generation != regen_retry):
                    # a reshard was adopted while we waited to advance
                    # into g: the previous horizon's remainder was
                    # re-dealt and this rank may hold an unserved share
                    # — back up one horizon (the post-commit array is
                    # only the remainder, so the replay is exactly-once
                    # by construction), then retry the advance (once per
                    # generation, so a genuinely-stuck peer still
                    # surfaces the error).  A reshard epoch_batches rode
                    # through internally needs none of this: it already
                    # served the re-dealt share before returning.
                    regen_retry = self.generation
                    yield from self.epoch_batches(g - 1, start_seq=0)
                    seq = 0
                    continue
                raise
            seq = 0
            g += 1

    def capability_stream_batches(self, *, spec=None,
                                  start_horizon: int = 0,
                                  horizons: Optional[int] = None,
                                  start_seq: int = 0
                                  ) -> Iterator[np.ndarray]:
        """The zero-index-bytes epochless loop: one signed grant per
        horizon generation (its ``epoch`` IS the horizon gen, its
        ``stream_weights`` the horizon's effective mixture weights),
        regenerated on-device via :meth:`capability_epoch_batches`.  A
        horizon advance surfaces exactly like a membership change —
        ``capability_stale``-style re-fetch — and the typed streaming
        refusals pace the first grant of each new horizon
        (docs/STREAMING.md).  Horizon re-entry after a mid-stream
        reshard mirrors :meth:`stream_batches`: a moved generation means
        the remainder was re-dealt, so the horizon is replayed (the
        fresh grant regenerates only the rank's new share)."""
        g = int(start_horizon)
        seq = int(start_seq)
        end = None if horizons is None else g + int(horizons)
        regen_retry = -1
        while end is None or g < end:
            g_gen = self.generation
            try:
                yield from self.capability_epoch_batches(
                    g, spec=spec, start_seq=seq)
            except ServiceError as exc:
                if (exc.code == "horizon_advance" and g > 0
                        and self.generation != g_gen
                        and self.generation != regen_retry):
                    regen_retry = self.generation
                    yield from self.capability_epoch_batches(
                        g - 1, spec=spec, start_seq=0)
                    seq = 0
                    continue
                raise
            seq = 0
            g += 1

    # ---------------------------------------------------------- capability
    def _fetch_capability(self, epoch: int, spec) -> EpochCapability:
        """Obtain and verify the signed epoch capability for ``epoch``.

        ``capability_stale`` is the revocation surface: the typed
        retryable error already carries the FRESH membership and
        capability, so adopting them here costs no second round trip.
        ``capability_unsupported`` (a daemon running without a signing
        secret) surfaces as :class:`CapabilityError` — the loader's
        fallback ladder drops to the served-batch path on it
        (docs/CAPABILITY.md "Fallback ladder")."""
        req = {"rank": self.rank, "epoch": int(epoch),
               "gen": self.generation}
        try:
            reply, rheader, _ = self._rpc(P.MSG_GET_CAPABILITY, req)
        except ServiceError as exc:
            if exc.code == "capability_stale":
                self.metrics.inc("capability_stale", self.rank)
                self._adopt_membership(exc.header)
                wire = exc.header.get("capability")
                if wire is None:
                    raise CapabilityError(
                        "capability_stale reply carried no fresh "
                        "capability") from exc
                cap = EpochCapability.from_wire(wire)
                rheader = exc.header
            elif exc.code == "capability_unsupported":
                raise CapabilityError(
                    exc.header.get("detail")
                    or "server does not issue capabilities") from exc
            else:
                raise
        else:
            if reply != P.MSG_CAPABILITY:
                raise P.ProtocolError(
                    f"expected CAPABILITY, got {P.msg_name(reply)}")
            self._adopt_membership(rheader)
            cap = EpochCapability.from_wire(rheader["capability"])
        ts = rheader.get("target_samples")
        if ts is not None:
            # issued mid-drain: the reply names our drain watermark
            self._cap_drain = {"epoch": int(epoch),
                               "target_samples": int(ts)}
        # the slot's server-side acked cursor: a takeover of a
        # partly-served slot resumes regeneration AFTER it (the
        # capability-mode half of the double-delivery guard)
        self._cap_resume_seq = int(rheader.get("ack", -1)) + 1
        self._verify_capability(cap, int(epoch), spec)
        return cap

    def _verify_capability(self, cap: EpochCapability, epoch: int,
                           spec) -> None:
        """Client-side admission of a received capability: signature,
        spec fingerprint, tenant scope, epoch, generation.  ANY failure
        is a loud :class:`CapabilityError` (counted in
        ``capability_rejects``), never a silently-different stream."""
        rule = F.draw("capability.verify")
        if rule is not None:
            if rule.kind == "corrupt":
                # deterministic tamper: the HMAC check below must refuse
                cap = cap.tampered()
            else:
                try:
                    F.perform(rule)
                except F.InjectedThreadDeath:
                    raise
                except Exception as exc:
                    self.metrics.inc("capability_rejects", self.rank)
                    raise CapabilityError(
                        f"capability verification failed ({exc!r})"
                    ) from exc
        problem = None
        if self.capability_secret is None:
            problem = "client has no capability_secret to verify with"
        elif not self._cap_signature_ok(cap):
            problem = "HMAC signature check failed"
        elif spec is not None and \
                cap.fingerprint != spec.fingerprint(include_world=False):
            problem = (f"fingerprint {cap.fingerprint!r} is not this "
                       "job's spec")
        elif cap.tenant != self.tenant:
            problem = (f"grant is scoped to tenant {cap.tenant!r}, "
                       f"this client is bound to {self.tenant!r}")
        elif int(cap.epoch) != int(epoch):
            problem = f"grant is for epoch {cap.epoch}, not {epoch}"
        elif int(cap.generation) != int(self.generation):
            problem = (f"grant names generation {cap.generation}; the "
                       f"adopted membership is {self.generation}")
        if problem is not None:
            self.metrics.inc("capability_rejects", self.rank)
            raise CapabilityError(f"capability refused: {problem}")

    def _cap_signature_ok(self, cap: EpochCapability) -> bool:
        """Dispatch the HMAC check on the secret's shape: a federated
        ``TrustBundle``/``CellKeyring`` resolves ``(cap.cell, cap.kid)``
        to a per-cell key (an unknown cell or a retired kid raises the
        loud re-issue ``CapabilityError``); a plain secret verifies
        directly (docs/FEDERATION.md "Federated capabilities")."""
        secret = self.capability_secret
        if hasattr(secret, "secret_for"):
            from ..federation.keys import verify_capability
            return verify_capability(secret, cap)
        return cap.verify(secret)

    def capability_epoch_batches(self, epoch: int, *, spec=None,
                                 start_seq: int = 0
                                 ) -> Iterator[np.ndarray]:
        """Stream ``epoch``'s batches with ZERO index bytes on the wire
        (docs/CAPABILITY.md).

        One GET_CAPABILITY fetches the signed grant; after verification
        the stream is regenerated on-device with the same shared-law
        kernels the degraded fallback uses
        (:func:`~..capability.regen.membership_stream`), bit-identical
        to what ``epoch_batches`` would have served.  Only ack
        watermarks go back — flushed as HEARTBEATs whenever the locally
        delivered span would exceed the server's ``max_inflight`` window
        (the issuance slack floor covers exactly that span, so an
        elastic barrier can never freeze BEHIND what we delivered) and
        at least every ``capability_heartbeat_s`` as the batchless
        keepalive.

        Rides through reshards like the served path: a heartbeat that
        returns a bumped generation (or a ``cap_drain`` drain notice)
        makes the generator deliver exactly to the frozen watermark,
        flush the gate-satisfying ack, re-fetch through the
        ``capability_stale`` flow, and continue with the post-reshard
        remainder — one contiguous exactly-once stream.  Ends early
        (``membership_lost``) only when the shrunken world has no slot
        for this rank."""
        spec = spec if spec is not None else self.expected_spec
        if spec is None:
            raise CapabilityError(
                "capability mode needs the stream-shaping spec: pass "
                "spec= here or construct the client with one")
        epoch, seq = int(epoch), int(start_seq)
        if self._samples_epoch != epoch:
            # new epoch: the trail describes the previous epoch's
            # deliveries — start fresh (same law as epoch_batches)
            self._trail = []
            self._epoch_samples = 0
            self._samples_epoch = epoch
        self._cursor = {"epoch": epoch, "seq": seq}
        self._ensure_connected()
        cap = self._fetch_capability(epoch, spec)
        # a partly-served slot (takeover of a vacated rank) resumes
        # after the server-side acked watermark the grant reported
        seq = max(seq, self._cap_resume_seq)
        self._cursor = {"epoch": epoch, "seq": seq}
        acked = seq - 1              # watermark last flushed server-side
        last_hb = self._clock()
        while True:                  # one iteration per membership
            if not (self.rank is not None and self.world is not None
                    and int(self.rank) < int(self.world)):
                # shrunk out: our share of the epoch belongs to others
                self.metrics.inc("membership_lost")
                return
            mi = self._server_max_inflight or self.lookahead
            layers = self.layers if (
                self.elastic_epoch is not None
                and int(self.elastic_epoch) == epoch) else []
            sw = getattr(cap, "stream_weights", None)
            regen_spec = spec
            if sw is not None and hasattr(spec, "with_stream_weights"):
                # moving-horizon mixture stream: the signed grant carries
                # the horizon's EFFECTIVE weights (base + every delta
                # folded at advances <= epoch), so on-device regen folds
                # the re-weighted horizon bit-identically to the served
                # path (docs/STREAMING.md "Weight-update protocol")
                regen_spec = spec.with_stream_weights({epoch: tuple(sw)})
            arr = membership_stream(regen_spec, epoch, self.rank,
                                    self.world, layers, self.orphans)
            total = int(arr.shape[0])
            refetch = False
            while not refetch:
                cd = self._cap_drain
                target = None
                if cd is not None and int(cd.get("epoch", -1)) == epoch:
                    target = int(cd["target_samples"])
                stop = total if target is None else min(total, target)
                lo = seq * self.batch
                if lo >= stop:
                    # delivered everything this membership owes — the
                    # epoch tail, or the frozen drain watermark.  Flush
                    # the terminal ack NOW (a lazy piggyback could
                    # deadlock a barrier gated on it), then finish or
                    # wait out the commit.
                    g = self.heartbeat()
                    acked, last_hb = seq - 1, self._clock()
                    if int(g) == int(cap.generation) \
                            and self.generation == cap.generation:
                        if target is None:
                            return
                        # drain-wait: the barrier needs other ranks too
                        time.sleep(min(0.05, self.backoff_base))
                        continue
                    cap = self._fetch_capability(epoch, spec)
                    seq = self._cap_resume_seq
                    acked = seq - 1
                    self._cursor = {"epoch": epoch, "seq": seq}
                    refetch = True
                    continue
                if seq - acked > mi or (self._clock() - last_hb
                                        >= self.capability_heartbeat_s):
                    # client half of the slack law / batchless keepalive
                    g = self.heartbeat()
                    acked, last_hb = seq - 1, self._clock()
                    if int(g) != int(cap.generation) \
                            or self.generation != cap.generation:
                        # revoked mid-stream; by the slack law our
                        # delivered watermark is <= the frozen target,
                        # so the trail entry the stale flow records is
                        # exactly the prefix the cascade preserved
                        cap = self._fetch_capability(epoch, spec)
                        seq = self._cap_resume_seq
                        acked = seq - 1
                        self._cursor = {"epoch": epoch, "seq": seq}
                        refetch = True
                    # re-enter the loop either way: the reply may have
                    # carried a ``cap_drain`` notice, and delivering the
                    # next batch against the pre-heartbeat ``stop``
                    # would run past a freshly frozen drain watermark
                    continue
                hi = min(lo + self.batch, stop)
                batch_arr = arr[lo:hi]
                # advance BEFORE yielding: once the consumer holds the
                # batch it counts as delivered (exactly-once on resume)
                seq += 1
                self._cursor = {"epoch": epoch, "seq": seq}
                self._epoch_samples = max(self._epoch_samples, int(hi))
                yield batch_arr

    def capability_epoch_indices(self, epoch: int, *,
                                 spec=None) -> np.ndarray:
        """The rank's full epoch stream via the capability path — the
        drop-in for ``epoch_indices`` when both sides share a
        ``capability_secret``."""
        parts = list(self.capability_epoch_batches(epoch, spec=spec))
        if not parts:
            return np.empty(0, dtype=np.int64)
        return np.concatenate(parts)

    def local_epoch_indices(self, spec, epoch: int) -> np.ndarray:
        """Compose this client's epoch stream LOCALLY from its adopted
        membership — the degraded-mode fallback's source of truth.

        For a non-elastic epoch this is simply the rank's stream under
        the current membership.  For the elastic epoch it is the exact
        trail of memberships this client delivered under: each
        ridden-through generation contributes the prefix it actually
        served (its recorded watermark), and the current membership
        contributes its full remainder stream — together bit-identical
        to what the service would have gone on to serve.  ``spec`` is
        the stream-shaping spec (any world; each membership entry
        re-bases it via ``with_world``).

        The composition law itself lives in
        :func:`~..capability.regen.replay_trail` — ONE implementation
        shared with capability-mode regeneration, so the two local
        paths cannot drift."""
        epoch = int(epoch)
        return replay_trail(
            spec, epoch, rank=self.rank, world=self.world,
            layers=self.layers, orphans=self.orphans,
            elastic_epoch=self.elastic_epoch,
            trail=self._trail if self._samples_epoch == epoch else (),
        )

    @staticmethod
    def _orphan_slice(spec, o: dict) -> np.ndarray:
        """Materialise one orphan descriptor against ``spec`` — the same
        law the server applies when serving rank 0's prefix (delegates
        to the shared :func:`~..capability.regen.orphan_slice`)."""
        return orphan_slice(spec, o)

    # ---------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        """The resume cursor: drop it into ``utils/checkpoint`` alongside
        the trainer state to continue a killed *client* exactly-once."""
        return {"kind": "service_client", "rank": self.rank,
                "epoch": self._cursor["epoch"], "seq": self._cursor["seq"]}

    def load_state_dict(self, state: dict) -> None:
        if state.get("kind") != "service_client":
            raise ValueError(
                f"state kind {state.get('kind')!r} is not a service_client "
                "checkpoint"
            )
        self.rank = None if state["rank"] is None else int(state["rank"])
        self._cursor = {"epoch": state["epoch"], "seq": int(state["seq"])}

    def resume_batches(self) -> Iterator[np.ndarray]:
        """Continue the loaded/current cursor's epoch from where it left."""
        if self._cursor["epoch"] is None:
            raise RuntimeError("no cursor to resume; call epoch_batches or "
                               "load_state_dict first")
        return self.epoch_batches(self._cursor["epoch"],
                                  start_seq=self._cursor["seq"])
