"""`PartialShuffleSpec`: one serializable description of an index stream.

The server process and every loader client must agree on exactly which
stream a ``(seed, epoch, rank)`` names — the same dispatch
``HostDataLoader`` performs locally (plain §3/§4 stream, §8 mixture
stream, §7 shard-expansion stream, each through the cpu/native/xla
backends).  This class is that dispatch factored into one value object:

* ``rank_indices(epoch, rank)`` — the rank's full epoch stream as a host
  array, bit-identical to a local ``HostDataLoader`` of the same config
  (the loader now *delegates here*, so server and local streams cannot
  drift);
* ``to_wire()`` / ``from_wire()`` — a JSON-safe dict that rides in the
  HELLO handshake and the server snapshot, so a client (or a restarted
  server) can refuse a config mismatch instead of serving a silently
  different permutation;
* ``fingerprint()`` — a stable string of the wire form for cheap
  equality checks.

The backend field is resolved at construction (``'auto'`` → the shared
host-side rule) and is deliberately *excluded* from the fingerprint:
every backend evaluates the same normative stream, so a cpu client may
talk to a native server.
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

from ..ops import core

_MODES = ("plain", "mixture", "shard")


class PartialShuffleSpec:
    """Immutable-by-convention description of one partial-shuffle stream."""

    def __init__(
        self,
        mode: str,
        *,
        seed: int = 0,
        world: int = 1,
        backend: str = "cpu",
        n: Optional[int] = None,
        window: Optional[int] = None,
        mixture_key=None,
        epoch_samples: Optional[int] = None,
        shard_sizes=None,
        within_shard_shuffle=True,
        **kwargs,
    ) -> None:
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
        self.mode = mode
        self.seed, self.world = int(seed), int(world)
        if self.world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if backend == "auto":
            from ..ops import resolve_host_backend

            backend = resolve_host_backend()
        from ..ops import ensure_index_backend

        ensure_index_backend(backend)  # fail at construction, not epoch 1
        self.backend = backend
        # the sampler kwargs every stream threads through to the core;
        # use_pallas rides along for the xla backend but is a pure speed
        # knob (bit-identical output), so it stays out of the wire form
        self.kwargs = {
            k: kwargs.pop(k)
            for k in ("shuffle", "drop_last", "order_windows", "partition",
                      "rounds", "use_pallas")
            if k in kwargs
        }
        if kwargs:
            raise TypeError(f"unknown spec kwargs: {sorted(kwargs)}")
        self.n = None if n is None else int(n)
        self.window = None if window is None else int(window)
        self.mixture_key = mixture_key
        self.epoch_samples = (
            None if epoch_samples is None else int(epoch_samples)
        )
        self.shard_sizes = (
            None if shard_sizes is None
            else np.asarray(shard_sizes, dtype=np.int64)
        )
        self.within_shard_shuffle = (
            within_shard_shuffle if isinstance(within_shard_shuffle, bool)
            else int(within_shard_shuffle)
        )
        self._mixture_spec = None
        if mode == "plain":
            if self.n is None or self.window is None:
                raise ValueError("plain mode needs n and window")
        elif mode == "mixture":
            if mixture_key is None:
                raise ValueError("mixture mode needs mixture_key")
            self._mixture_spec = self._build_mixture()
        else:  # shard
            if self.shard_sizes is None:
                raise ValueError("shard mode needs shard_sizes")
            if self.window is None:
                self.window = 64  # the shard sampler's locality default

    # ----------------------------------------------------------- builders
    @classmethod
    def plain(cls, n: int, *, window: int, seed: int = 0, world: int = 1,
              backend: str = "cpu", **kwargs) -> "PartialShuffleSpec":
        """The single-source §3/§4 stream (what the torch shim serves)."""
        return cls("plain", n=n, window=window, seed=seed, world=world,
                   backend=backend, **kwargs)

    @classmethod
    def mixture(cls, mixture, *, seed: int = 0, world: int = 1,
                epoch_samples: Optional[int] = None, backend: str = "cpu",
                **kwargs) -> "PartialShuffleSpec":
        """The §8 weighted-mixture stream; ``mixture`` is a ``MixtureSpec``
        or its :meth:`~..ops.mixture.MixtureSpec.key` tuple."""
        from ..ops.mixture import MixtureSpec

        key = mixture.key() if isinstance(mixture, MixtureSpec) else mixture
        return cls("mixture", mixture_key=tuple(key), seed=seed, world=world,
                   epoch_samples=epoch_samples, backend=backend, **kwargs)

    @classmethod
    def shard(cls, shard_sizes, *, window: int = 64, seed: int = 0,
              world: int = 1, within_shard_shuffle=True, backend: str = "cpu",
              **kwargs) -> "PartialShuffleSpec":
        """The §7 shard-index stream, expanded to global sample indices."""
        return cls("shard", shard_sizes=shard_sizes, window=window, seed=seed,
                   world=world, within_shard_shuffle=within_shard_shuffle,
                   backend=backend, **kwargs)

    def _build_mixture(self):
        from ..ops.mixture import MixtureSpec

        key = self.mixture_key
        # wire form arrives as nested lists; from_key wants tuples
        key = (tuple(key[0]), tuple(key[1]), tuple(key[2]), key[3], key[4])
        self.mixture_key = key
        return MixtureSpec.from_key(key)

    @property
    def mixture_spec(self):
        return self._mixture_spec

    # -------------------------------------------------------------- sizing
    def num_samples(self, rank: int = 0) -> Optional[int]:
        """Per-rank epoch length; ``None`` for shard mode (the expansion
        length follows the rank's shard draw — serve and count)."""
        if self.mode == "plain":
            return core.shard_sizes(
                self.n, self.world, self.kwargs.get("drop_last", False)
            )[0]
        if self.mode == "mixture":
            from ..ops.mixture import mixture_epoch_sizes

            _, ns, _ = mixture_epoch_sizes(
                self._mixture_spec, self.epoch_samples, self.world,
                self.kwargs.get("drop_last", False),
            )
            return ns
        return None

    # ------------------------------------------------------------- streams
    def rank_indices(self, epoch: int, rank: int, *,
                     layers=None) -> np.ndarray:
        """The rank's full epoch stream as host sample indices — the
        normative stream every consumer surface of this config serves.

        ``layers`` names a §6 elastic reshard cascade
        (``[(old_world, consumed), ...]`` outermost first, consumed counted
        in this spec's base units: samples for plain/mixture, SHARDS for
        shard mode); the stream is then the epoch's remainder after the
        cascade, partitioned at this spec's (new) ``world``."""
        if not 0 <= rank < self.world:
            raise ValueError(f"rank must be in [0, {self.world}), got {rank}")
        epoch = int(epoch)
        layers = None if not layers else [(int(w), int(c)) for w, c in layers]
        if self.mode == "mixture":
            return self._mixture_indices(epoch, rank, layers)
        n = self.n if self.mode == "plain" else len(self.shard_sizes)
        if layers is not None:
            from ..ops.cpu import elastic_indices_np

            # the numpy reference derivation is normative and bit-identical
            # across backends, and remainder domains are small — no reason
            # to route the cascade through per-backend evaluators
            base = elastic_indices_np(
                n, self.window, self.seed, epoch, rank, self.world, layers,
                shuffle=self.kwargs.get("shuffle", True),
                drop_last=self.kwargs.get("drop_last", False),
                order_windows=self.kwargs.get("order_windows", True),
                partition=self.kwargs.get("partition", "strided"),
                rounds=self.kwargs.get("rounds", core.DEFAULT_ROUNDS),
            )
        else:
            from ..ops import epoch_indices_host

            base = epoch_indices_host(
                self.backend, n, self.window, self.seed, epoch, rank,
                self.world, **self.kwargs,
            )
        if self.mode == "plain":
            return base
        if self.backend == "native":
            from ..ops.native import expand_shard_indices_native as expand
        else:
            from ..sampler.shard_mode import expand_shard_indices_np as expand
        return expand(
            base, self.shard_sizes, seed=self.seed, epoch=epoch,
            within_shard_shuffle=self.within_shard_shuffle,
            rounds=self.kwargs.get("rounds", core.DEFAULT_ROUNDS),
        )

    def rank_unit_sizes(self, epoch: int, rank: int, *, layers=None):
        """Per-base-unit sample counts of the rank's stream, or ``None``
        when units ARE samples (plain/mixture).  For shard mode this is
        ``shard_sizes[shard_draw]`` — what a consumption watermark in
        samples needs to be converted to whole consumed SHARDS (the unit
        an elastic barrier must cut on, service/server.py)."""
        if self.mode != "shard":
            return None
        if layers is not None:
            from ..ops.cpu import elastic_indices_np

            ids = elastic_indices_np(
                len(self.shard_sizes), self.window, self.seed, int(epoch),
                rank, self.world, [(int(w), int(c)) for w, c in layers],
                shuffle=self.kwargs.get("shuffle", True),
                drop_last=self.kwargs.get("drop_last", False),
                order_windows=self.kwargs.get("order_windows", True),
                partition=self.kwargs.get("partition", "strided"),
                rounds=self.kwargs.get("rounds", core.DEFAULT_ROUNDS),
            )
        else:
            from ..ops import epoch_indices_host

            ids = epoch_indices_host(
                self.backend, len(self.shard_sizes), self.window, self.seed,
                int(epoch), rank, self.world, **self.kwargs,
            )
        return np.asarray(self.shard_sizes)[np.asarray(ids)]

    def _mixture_indices(self, epoch: int, rank: int,
                         layers=None) -> np.ndarray:
        from ..ops import mixture as M

        kw = dict(
            epoch_samples=self.epoch_samples,
            shuffle=self.kwargs.get("shuffle", True),
            drop_last=self.kwargs.get("drop_last", False),
            order_windows=self.kwargs.get("order_windows", True),
            partition=self.kwargs.get("partition", "strided"),
            rounds=self.kwargs.get("rounds", core.DEFAULT_ROUNDS),
        )
        if layers is not None:
            if self.backend == "xla":
                return np.asarray(M.mixture_elastic_indices_jax(
                    self._mixture_spec, self.seed, epoch, rank, self.world,
                    layers, **kw,
                ))
            if self.backend == "native":
                from ..ops.native import mixture_elastic_indices_native

                return mixture_elastic_indices_native(
                    self._mixture_spec, self.seed, epoch, rank, self.world,
                    layers, **kw,
                )
            return M.mixture_elastic_indices_np(
                self._mixture_spec, self.seed, epoch, rank, self.world,
                layers, **kw,
            )
        if self.backend == "xla":
            return np.asarray(M.mixture_epoch_indices_jax(
                self._mixture_spec, self.seed, epoch, rank, self.world, **kw,
            ))
        if self.backend == "native":
            from ..ops.native import mixture_epoch_indices_native

            return mixture_epoch_indices_native(
                self._mixture_spec, self.seed, epoch, rank, self.world, **kw,
            )
        return M.mixture_epoch_indices_np(
            self._mixture_spec, self.seed, epoch, rank, self.world, **kw,
        )

    # ----------------------------------------------------------------- wire
    def to_wire(self) -> dict:
        """JSON-safe dict naming the stream (NOT the backend — every
        backend serves the same normative stream)."""
        d = {
            "mode": self.mode,
            "seed": self.seed,
            "world": self.world,
            "kwargs": {k: self.kwargs[k] for k in sorted(self.kwargs)
                       if k != "use_pallas"},
        }
        if self.mode == "plain":
            d["n"] = self.n
            d["window"] = self.window
        elif self.mode == "mixture":
            k = self.mixture_key
            d["mixture_key"] = [list(k[0]), list(k[1]), list(k[2]),
                                k[3], k[4]]
            d["epoch_samples"] = self.epoch_samples
        else:
            d["shard_sizes"] = [int(s) for s in self.shard_sizes]
            d["window"] = self.window
            d["within_shard_shuffle"] = self.within_shard_shuffle
        return d

    @classmethod
    def from_wire(cls, d: dict, *, backend: str = "cpu") -> "PartialShuffleSpec":
        if d.get("mode") == "stream" and cls is PartialShuffleSpec:
            # the moving-horizon stream (docs/STREAMING.md) rides the same
            # wire surface; its subclass owns the round-trip
            from ..streaming.spec import StreamSpec

            return StreamSpec.from_wire(d, backend=backend)
        if (d.get("mode") in ("weighted", "prioritized", "dedup")
                and cls is PartialShuffleSpec):
            # non-uniform sampling modes (docs/SAMPLING.md) likewise
            from ..sampling.spec import SamplingSpec

            return SamplingSpec.from_wire(d, backend=backend)
        d = dict(d)
        kwargs = d.pop("kwargs", {})
        mk = d.pop("mixture_key", None)
        if mk is not None:
            d["mixture_key"] = (tuple(mk[0]), tuple(mk[1]), tuple(mk[2]),
                                mk[3], mk[4])
        return cls(d.pop("mode"), backend=backend, **d, **kwargs)

    def with_world(self, world: int) -> "PartialShuffleSpec":
        """The same stream identity re-partitioned at a different world —
        what an elastic reshard commit produces (the fingerprint modulo
        ``world`` is unchanged)."""
        world = int(world)
        if world == self.world:
            return self
        wire = self.to_wire()
        wire["world"] = world
        out = self.from_wire(wire, backend=self.backend)
        # pure speed knob, excluded from the wire form — carry it across
        if "use_pallas" in self.kwargs:
            out.kwargs["use_pallas"] = self.kwargs["use_pallas"]
        return out

    def fingerprint(self, *, include_world: bool = True) -> str:
        """Stable string of the wire form.  ``include_world=False`` names
        the stream identity independent of the current partition width —
        the membership-aware comparison elastic peers use (the world is
        authoritative server state once resharding is possible)."""
        wire = self.to_wire()
        if not include_world:
            wire.pop("world")
        return json.dumps(wire, sort_keys=True, separators=(",", ":"))

    def __eq__(self, other) -> bool:
        return (isinstance(other, PartialShuffleSpec)
                and self.fingerprint() == other.fingerprint())

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"PartialShuffleSpec({self.fingerprint()})"
