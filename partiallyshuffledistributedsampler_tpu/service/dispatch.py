"""Shared listener plumbing: one accept/dispatch loop, many daemons.

:class:`DispatchListener` is the per-connection accept/serve machinery
extracted from ``IndexServer`` so the rank-space :class:`~..sharding.ShardRouter`
and the shard servers run the *same* framing/CRC/trace code path instead
of a third copy (docs/SHARDING.md).  The mixin owns exactly the
transport-facing loop — bind, accept, spawn a serve thread per
connection, frame in, dispatch, frame out — and delegates everything
policy-shaped through small hooks:

* ``_dispatch(sock, conn_id, msg, header, payload)`` — the one required
  override: route a decoded frame to a handler.
* ``_on_accept_tick()`` — the 0.2 s accept timeout tick (``IndexServer``
  runs its lease/membership sweeps here).
* ``_conn_engine(conn_id)`` / ``_span_extra(eng)`` — who owns the
  request (tenant routing) and what extra attributes its telemetry span
  carries.
* ``_observe_dispatch(eng, msg, t0)`` — post-dispatch timing
  (``batch_service_ms`` on the index server).
* ``_conn_cleanup(conn_id)`` — connection teardown (lease release).

Host classes must provide ``host``/``port``, ``_stop`` (Event),
``_lock``, ``_listener``, ``_threads``, ``_conn_socks`` and
``_next_conn_id``.  The loop bytes are unchanged from the pre-extraction
``IndexServer`` — frames on the wire are bit-identical.
"""

from __future__ import annotations

import socket
import threading
import time

from .. import faults as F
from .. import telemetry
from ..telemetry import span as _span
from . import protocol as P


class DispatchListener:
    """Accept-loop + per-connection dispatch mixin (transport only)."""

    #: thread names; subclasses override for operator-legible dumps
    _ACCEPT_THREAD_NAME = "psds-service-accept"
    _CONN_THREAD_PREFIX = "psds-service-conn"
    #: telemetry span prefix for dispatched frames
    _SPAN_PREFIX = "server."

    # ------------------------------------------------------------ listener
    def _listener_bind(self) -> tuple:
        """Bind ``(self.host, self.port)``, start the accept thread, and
        return the bound address (``port=0`` resolves to an ephemeral
        port)."""
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.host, self.port))
        ls.listen(128)
        ls.settimeout(0.2)  # the accept loop doubles as the sweep tick
        self.host, self.port = ls.getsockname()[:2]
        self._listener = ls
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=self._ACCEPT_THREAD_NAME)
        t.start()
        self._threads.append(t)
        return self.host, self.port

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            ls = self._listener
            if ls is None:
                return
            try:
                sock, _addr = ls.accept()
            except socket.timeout:
                self._on_accept_tick()
                continue
            except OSError:
                return  # listener closed by stop()
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                conn_id = self._next_conn_id
                self._next_conn_id += 1
                self._conn_socks[conn_id] = sock
            t = threading.Thread(
                target=self._serve_conn, args=(sock, conn_id), daemon=True,
                name=f"{self._CONN_THREAD_PREFIX}-{conn_id}",
            )
            t.start()
            # prune finished serve threads while appending: a long-lived
            # daemon churning reconnects must not accumulate dead Thread
            # objects (and stop() must not re-join them)
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    # ------------------------------------------------------- per-connection
    def _serve_conn(self, sock: socket.socket, conn_id: int) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg, header, payload = P.recv_msg(sock)
                except P.ProtocolError as exc:
                    # best-effort complaint, then drop the broken peer
                    try:
                        P.send_msg(sock, P.MSG_ERROR,
                                   {"code": "protocol", "detail": str(exc)})
                    except OSError:
                        pass
                    return
                t0 = time.perf_counter()
                eng = self._conn_engine(conn_id)
                try:
                    if telemetry.enabled():
                        # the span wraps the fault-injection point too,
                        # so a dump triggered by an injected dispatch
                        # fault shows the request being served when it
                        # fired
                        with _span(self._SPAN_PREFIX + P.msg_name(msg),
                                   trace=header.get("trace"), conn=conn_id,
                                   rank=header.get("rank"),
                                   **self._span_extra(eng)):
                            F.fire("server.dispatch")
                            self._dispatch(sock, conn_id, msg, header,
                                           payload)
                    else:
                        # tracing off: no span, no kwargs dict, no name
                        # concat on the per-request hot path
                        F.fire("server.dispatch")
                        self._dispatch(sock, conn_id, msg, header, payload)
                except OSError:
                    return  # peer vanished mid-reply
                self._observe_dispatch(eng, msg, t0)
        except (ConnectionError, OSError):
            return
        except F.InjectedThreadDeath:
            return  # injected serve-thread death; cleanup below still runs
        finally:
            self._conn_cleanup(conn_id)
            try:
                sock.close()
            except OSError:
                pass

    # ----------------------------------------------------------------- hooks
    def _dispatch(self, sock, conn_id, msg, header, payload) -> None:
        raise NotImplementedError

    def _on_accept_tick(self) -> None:
        """Called on every accept-timeout tick (~0.2 s)."""

    def _conn_engine(self, conn_id: int):
        """The engine owning this connection's requests (tenant routing)."""
        return self

    def _span_extra(self, eng) -> dict:
        """Extra telemetry-span attributes for a dispatched frame."""
        return {}

    def _observe_dispatch(self, eng, msg, t0: float) -> None:
        """Post-dispatch timing hook (``t0`` is a ``perf_counter``)."""

    def _conn_cleanup(self, conn_id: int) -> None:
        """Teardown when a serve thread exits (crash or close)."""
        self._release_conn(conn_id)

    def _release_conn(self, conn_id: int) -> None:
        with self._lock:
            self._conn_socks.pop(conn_id, None)
