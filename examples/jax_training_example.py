"""JAX-native usage: mesh-sharded sampler feeding a sharded training step —
indices are generated and consumed entirely in HBM (driver config #3 shape:
token shards + GPT, scaled down to run anywhere).

Run: python examples/jax_training_example.py
(Uses the virtual CPU mesh if fewer than 2 real devices are present.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    # Demo default: an 8-device virtual CPU mesh, set up BEFORE the first
    # backend query (flags are ignored once XLA initializes).  Export
    # PSDS_EXAMPLE_REAL=1 to use whatever real devices are present instead.
    use_real = os.environ.get("PSDS_EXAMPLE_REAL") == "1"
    if not use_real:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if not use_real:
        jax.config.update("jax_platforms", "cpu")

    from partiallyshuffledistributedsampler_tpu.models import (
        GPTConfig, demo_training_run, make_mesh,
    )

    mesh = make_mesh()
    print(f"mesh: {dict(mesh.shape)} over {mesh.devices.size} devices")
    losses = demo_training_run(
        mesh,
        GPTConfig(),
        n_samples=2048, window=256, batch_per_dp=8,
        steps_per_epoch=4, epochs=3,
    )
    print("losses:", [round(l, 3) for l in losses])
    assert losses[-1] < losses[0], "loss should decrease on synthetic data"
    print("ok: sharded sampler -> sharded train step, indices never left HBM")

    # Single-device variant: the scan runner executes a WHOLE epoch in one
    # compiled program (zero per-step dispatches) — the recommended shape
    # for simple per-device loops.
    import jax.numpy as jnp

    from partiallyshuffledistributedsampler_tpu.sampler import (
        DeviceEpochIterator,
    )

    it = DeviceEpochIterator(n=4096, window=256, batch=64, seed=0,
                             rank=0, world=1)

    def step(carry, idx_batch):
        # stand-in for a train step: consume the batch, count steps
        return (carry[0] + 1, carry[1] + idx_batch.sum()), idx_batch[0]

    (steps_done, _), firsts = it.run_epoch(
        0, step, (jnp.int32(0), jnp.int32(0)), collect=True
    )
    print(f"ok: run_epoch scanned {int(steps_done)} steps in one dispatch")

    # Host-resident data (tokenized shards, memmaps): HostDataLoader
    # gathers data[idx] per step and ships it with an async device_put,
    # one step ahead on a background thread — DataLoader-worker overlap
    # without processes.
    import numpy as np

    from partiallyshuffledistributedsampler_tpu.sampler import HostDataLoader

    tokens = np.arange(4096 * 8).reshape(4096, 8)  # stand-in corpus
    loader = HostDataLoader({"tokens": tokens}, window=256, batch=64,
                            seed=0, rank=0, world=1)
    total = 0
    for batch in loader.epoch(0):  # {"tokens": device int[64, 8]}
        total += int(batch["tokens"].sum())
    expect = int(tokens[np.concatenate(
        [np.asarray(b) for b in DeviceEpochIterator(
            n=4096, window=256, batch=64, seed=0, rank=0, world=1).epoch(0)]
    )].sum())
    assert total == expect  # same stream as every other consumer surface
    print(f"ok: HostDataLoader prefetched {loader.steps_per_epoch} "
          f"gathered batches to the device")

    # Multi-corpus pretrain (BASELINE config 3's real shape: C4 + code +
    # books at fixed proportions, SPEC.md §8) — the WHOLE run as one
    # compiled program: the mesh-sharded mixture regen (ICI seed
    # agreement + per-source seeds + fused §8 evaluation) scans
    # in-program around the sharded train steps; zero host round-trips.
    import jax

    from partiallyshuffledistributedsampler_tpu.models import (
        GPTConfig, create_sharded_state, make_mesh, make_mixture_run_runner,
    )
    from partiallyshuffledistributedsampler_tpu.ops.mixture import (
        MixtureSpec,
    )
    from partiallyshuffledistributedsampler_tpu.parallel import (
        make_seed_triple,
    )

    cfg = GPTConfig(vocab_size=128, seq_len=16, d_model=64, n_layers=1,
                    n_heads=2, d_ff=128)
    spec = MixtureSpec([120, 80, 56], [70, 20, 10], windows=16, block=16)
    mesh = make_mesh()
    corpus = jax.random.randint(
        jax.random.PRNGKey(1), (spec.total_sources_len, cfg.seq_len + 1),
        0, cfg.vocab_size, dtype=jnp.int32,
    )
    params, opt, tx = create_sharded_state(cfg, mesh, seed=0)
    run = make_mixture_run_runner(cfg, tx, mesh, 2, 2, 2, spec)
    params, opt, losses = run(params, opt, corpus,
                              make_seed_triple(mesh, 7, 0, axis="dp"),
                              jnp.int32(0))
    losses = np.asarray(losses).reshape(-1)
    assert np.isfinite(losses).all()
    print(f"ok: mixture whole-run program trained "
          f"{losses.size} steps over {spec.num_sources} corpora in one "
          f"dispatch (losses {[round(float(l), 2) for l in losses]})")


if __name__ == "__main__":
    main()
