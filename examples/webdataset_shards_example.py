"""Driver config #4 shape: WebDataset-style tar shards, partial shuffle over
*shard indices* (BASELINE.json configs[3]) — the pipeline a ViT-L/16 data
loader runs at scale.

The shuffle unit is the shard file: shard order is windowed-shuffled per
epoch (reads stay clustered within a storage prefix), each rank reads only
its own shards sequentially, samples inside a shard pass through the spec'd
bounded shuffle buffer (SPEC.md §7.3).  Everything is deterministic in
(seed, epoch), so the stream checkpoints/resumes like the index path.

Run: python examples/webdataset_shards_example.py
(Simulates the tar layer with in-memory "shards"; swap _read_shard for a
real tarfile/webdataset reader 1:1.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from partiallyshuffledistributedsampler_tpu.sampler import (
    PartialShuffleShardSampler,
    shard_sample_order,
    shuffle_buffer,
)

NUM_SHARDS, WORLD, WINDOW, EPOCHS = 128, 4, 16, 2
SHARD_SIZES = [200 + (37 * s) % 100 for s in range(NUM_SHARDS)]
OFFSETS = np.concatenate([[0], np.cumsum(SHARD_SIZES)[:-1]])


def _read_shard(sid: int):
    """Stand-in for a sequential tar read: yields (global_id, sample)."""
    for local in range(SHARD_SIZES[sid]):
        yield int(OFFSETS[sid]) + local, f"sample-{sid}-{local}"


def _make_sampler(rank: int, epoch: int, seed: int):
    sampler = PartialShuffleShardSampler(
        NUM_SHARDS, num_replicas=WORLD, rank=rank, window=WINDOW, seed=seed,
        backend="cpu",
    )
    sampler.set_epoch(epoch)
    return sampler


def rank_stream(rank: int, epoch: int, seed: int = 11):
    """One rank's epoch: shards in partial-shuffle order; within each shard a
    *bounded* in-shard shuffle (window=64 of the §3 law, so a tar reader
    needs only a 64-sample decode buffer); then a 256-sample §7.3 shuffle
    buffer across shard boundaries."""
    sampler = _make_sampler(rank, epoch, seed)

    def samples():
        for sid in sampler:
            # bounded within-shard order: permutes the *read* order while
            # the tar layer still streams (displacement < 64)
            order = shard_sample_order(
                sid, SHARD_SIZES[sid], seed=seed, epoch=epoch,
                within_shard_shuffle=64,
            )
            shard = list(_read_shard(sid))
            for local in order:
                yield shard[int(local)]

    yield from shuffle_buffer(samples(), 256, seed=seed, epoch=epoch)


def device_rank_indices(rank: int, epoch: int, seed: int = 11):
    """The JAX-native variant: the rank's shard stream expanded to global
    sample indices ON DEVICE (expand_shard_indices_jax — ~46 ms for 1e8
    indices on the bench rig vs 51 s host, BASELINE.md), left in HBM for a
    jitted input pipeline (gather + train step).  Bit-identical to the
    host expansion.  Returns (shard_ids, device_array)."""
    sampler = _make_sampler(rank, epoch, seed)
    return sampler.epoch_indices().tolist(), sampler.device_epoch_indices(
        SHARD_SIZES, within_shard_shuffle=64
    )


if __name__ == "__main__":
    for epoch in range(EPOCHS):
        seen = set()
        shards_touched = set()
        for rank in range(WORLD):  # in production: one process per rank
            for gid, _payload in rank_stream(rank, epoch):
                seen.add(gid)
                shards_touched.add(int(np.searchsorted(OFFSETS, gid, "right")) - 1)
        total = sum(SHARD_SIZES)
        print(
            f"epoch {epoch}: {len(seen)}/{total} distinct samples across "
            f"{len(shards_touched)} shards "
            f"(wrap-pad duplicates: {-(-NUM_SHARDS // WORLD) * WORLD - NUM_SHARDS} shards)"
        )
        assert len(seen) == total  # every sample served despite shard padding

    # the device path serves the same shards' samples without the §7.3
    # buffer stage (that is a host-stream tool); check it bit-for-bit
    # against the host expansion of the same shard stream
    from partiallyshuffledistributedsampler_tpu.sampler import (
        expand_shard_indices_np,
    )

    shard_ids, dev = device_rank_indices(0, 0)
    host = expand_shard_indices_np(
        shard_ids, SHARD_SIZES, seed=11, epoch=0, within_shard_shuffle=64
    )
    np.testing.assert_array_equal(np.asarray(dev), host)
    print(f"device expansion: rank 0 epoch 0 -> {len(host)} indices in HBM,"
          " bit-identical to the host expansion")

    # variable-length document shards (hundreds of DISTINCT sizes — the
    # case that used to force the host path): past 16 distinct sizes the
    # device expansion buckets shards into pow2-padded traced-size
    # programs and scatters straight into the stream, so a variable-size
    # corpus expands on device too, bit-identically
    from partiallyshuffledistributedsampler_tpu.sampler import (
        expand_shard_indices_jax,
    )

    rng = np.random.default_rng(5)
    var_sizes = rng.integers(20, 400, 800)
    var_stream = rng.permutation(800)[:300]
    vdev = np.asarray(expand_shard_indices_jax(
        var_stream, var_sizes, seed=11, epoch=0))
    vhost = expand_shard_indices_np(
        var_stream, var_sizes, seed=11, epoch=0)
    np.testing.assert_array_equal(vdev, vhost)
    print(f"variable-size expansion: {len(set(var_sizes.tolist()))} "
          f"distinct shard sizes -> {len(vhost)} indices on device, "
          "bit-identical (bucketed pow2 programs)")
