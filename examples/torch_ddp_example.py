"""Driver config #1 shape: CIFAR-10-sized dataset, DDP-style 2 ranks,
window=512 — the reference's canonical usage, unchanged except for
``backend='xla'`` (BASELINE.json north star: "existing DDP DataLoader
pipelines are unchanged").

Run: python examples/torch_ddp_example.py
(Uses a synthetic 50k-sample tensor dataset so it runs with no downloads;
swap in torchvision.datasets.CIFAR10 1:1.)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import torch
from torch.utils.data import DataLoader, TensorDataset

from partiallyshuffledistributedsampler_tpu import (
    PartiallyShuffleDistributedSampler,
)
from partiallyshuffledistributedsampler_tpu.utils import StallProbe

N, WORLD, WINDOW, BATCH, EPOCHS = 50_000, 2, 512, 256, 2


def run_rank(rank: int) -> None:
    data = TensorDataset(
        torch.randn(N, 3 * 32 * 32), torch.randint(0, 10, (N,))
    )
    model = torch.nn.Sequential(
        torch.nn.Linear(3 * 32 * 32, 64), torch.nn.ReLU(),
        torch.nn.Linear(64, 10),
    )
    opt = torch.optim.SGD(model.parameters(), lr=0.01)
    sampler = PartiallyShuffleDistributedSampler(
        data, num_replicas=WORLD, rank=rank, window=WINDOW, backend="auto"
    )
    loader = DataLoader(data, batch_size=BATCH, sampler=sampler, num_workers=0)

    for epoch in range(EPOCHS):
        sampler.set_epoch(epoch)  # on-device regen dispatched here (async)
        probe = StallProbe(loader)
        t0 = time.perf_counter()
        for x, y in probe:
            loss = torch.nn.functional.cross_entropy(model(x), y)
            opt.zero_grad(); loss.backward(); opt.step()
        print(
            f"rank {rank} epoch {epoch}: {time.perf_counter()-t0:.2f}s, "
            f"loss {loss.item():.3f}, stall {probe.report()['stall_pct']}%, "
            f"regen {sampler.regen_timer.last_ms:.2f} ms "
            f"[backend={sampler.backend}]"
        )


if __name__ == "__main__":
    for r in range(WORLD):  # in real DDP each rank is its own process
        run_rank(r)
