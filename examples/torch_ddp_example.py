"""Driver config #1 shape: CIFAR-10-sized dataset, DDP 2 ranks,
window=512 — the reference's canonical usage, unchanged except for
``backend='xla'`` (BASELINE.json north star: "existing DDP DataLoader
pipelines are unchanged").

Real DDP launch (one process per rank, gloo; sampler identity discovered
from the process group exactly as with torch's own DistributedSampler):

    torchrun --nproc_per_node=2 examples/torch_ddp_example.py

Single-process demo (no torchrun; iterates the ranks sequentially):

    python examples/torch_ddp_example.py

(Uses a synthetic 50k-sample tensor dataset so it runs with no downloads;
swap in torchvision.datasets.CIFAR10 1:1.)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import torch
from torch.utils.data import DataLoader, TensorDataset

from partiallyshuffledistributedsampler_tpu import (
    PartiallyShuffleDistributedSampler,
)
from partiallyshuffledistributedsampler_tpu.utils import StallProbe

N, WORLD, WINDOW, BATCH, EPOCHS = 50_000, 2, 512, 256, 2


def run_rank(rank: int, ddp: bool = False) -> None:
    torch.manual_seed(0)  # same synthetic data on every rank
    data = TensorDataset(
        torch.randn(N, 3 * 32 * 32), torch.randint(0, 10, (N,))
    )
    model = torch.nn.Sequential(
        torch.nn.Linear(3 * 32 * 32, 64), torch.nn.ReLU(),
        torch.nn.Linear(64, 10),
    )
    if ddp:
        model = torch.nn.parallel.DistributedDataParallel(model)
        # identity comes from the process group — same call a torch
        # DistributedSampler user writes, just the class swapped
        sampler = PartiallyShuffleDistributedSampler(
            data, window=WINDOW, backend="auto"
        )
    else:
        sampler = PartiallyShuffleDistributedSampler(
            data, num_replicas=WORLD, rank=rank, window=WINDOW, backend="auto"
        )
    opt = torch.optim.SGD(model.parameters(), lr=0.01)
    loader = DataLoader(data, batch_size=BATCH, sampler=sampler, num_workers=0)

    for epoch in range(EPOCHS):
        sampler.set_epoch(epoch)  # on-device regen dispatched here (async)
        probe = StallProbe(loader)
        t0 = time.perf_counter()
        for x, y in probe:
            loss = torch.nn.functional.cross_entropy(model(x), y)
            opt.zero_grad(); loss.backward(); opt.step()
        # raw_wait_pct is the UN-attributed StallProbe reading: it counts
        # DataLoader tensor collation and (on emulated rigs) transfer-tunnel
        # latency as "wait" — the sampler-attributable stall is what
        # benchmarks/stall_native.py measures by subtraction (~0 for this
        # backend at real epoch lengths)
        print(
            f"rank {rank} epoch {epoch}: {time.perf_counter()-t0:.2f}s, "
            f"loss {loss.item():.3f}, "
            f"raw_wait {probe.report()['stall_pct']}%, "
            f"regen {sampler.regen_timer.last_ms:.2f} ms "
            f"[backend={sampler.backend}]"
        )


if __name__ == "__main__":
    if "RANK" in os.environ and "WORLD_SIZE" in os.environ:  # torchrun
        import torch.distributed as dist

        dist.init_process_group(backend="gloo")
        try:
            run_rank(dist.get_rank(), ddp=True)
        finally:
            dist.destroy_process_group()
    else:  # single-process demo: iterate the ranks sequentially
        for r in range(WORLD):
            run_rank(r)
