"""Driver config #2 shape: ImageNet-1k ResNet-50 DDP, window=8192, 8 chips
(BASELINE.json configs[1]).

Two tiers, both runnable anywhere:

1. **Real scale, real sampler**: the actual ImageNet-1k index space
   (n=1,281,167) partially shuffled with window=8192 across 8 ranks — the
   multi-rank-without-a-cluster trick (SURVEY.md §4): 8 sampler instances
   in one process.  Asserts the DDP partition invariant and the read
   locality the windowed shuffle sells (every 8192-aligned block of the
   global stream draws from exactly ONE source window — sequential storage
   stays sequential), and times the per-rank regen.

2. **Scaled-down training slice**: a residual conv net (ResNet stand-in)
   on synthetic 32x32 images through a real DataLoader with
   ``StatefulDataLoader`` — including a mid-epoch checkpoint/resume that is
   exact despite ``num_workers`` prefetch.

Run: python examples/imagenet_resnet_example.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

IMAGENET_N = 1_281_167  # ImageNet-1k train split size
WINDOW = 8192
WORLD = 8  # 8 TPU v4 chips in the driver config


def real_scale_index_tier() -> None:
    from partiallyshuffledistributedsampler_tpu import (
        PartiallyShuffleDistributedSampler,
    )

    samplers = [
        PartiallyShuffleDistributedSampler(
            IMAGENET_N, num_replicas=WORLD, rank=r, window=WINDOW,
            seed=17, backend="auto",
        )
        for r in range(WORLD)
    ]
    for s in samplers:
        s.set_epoch(1)
    t0 = time.perf_counter()
    shards = [s.epoch_indices() for s in samplers]
    regen_ms = (time.perf_counter() - t0) * 1e3
    backend = samplers[0].backend

    # DDP partition invariant: equal shards tiling the padded index space
    num_samples = len(samplers[0])
    assert all(len(sh) == num_samples for sh in shards)
    union = np.concatenate(shards)
    assert len(np.unique(union)) == IMAGENET_N  # every sample served
    total = num_samples * WORLD

    # read locality: reinterleave the strided rank shards back into the
    # global stream; every full 8192-aligned block must draw from exactly
    # one source window (SPEC.md §3 windowing law) — the property that
    # keeps sequentially-stored JPEG shards streaming sequentially
    stream = np.empty(total, dtype=union.dtype)
    for r, sh in enumerate(shards):
        stream[r::WORLD] = sh
    full = IMAGENET_N // WINDOW * WINDOW
    blocks = stream[:full].reshape(-1, WINDOW)
    src_windows = blocks // WINDOW
    assert (src_windows == src_windows[:, :1]).all(), "window locality broken"
    print(
        f"tier 1: n={IMAGENET_N:,} window={WINDOW} world={WORLD} "
        f"[backend={backend}]\n"
        f"  all-rank regen {regen_ms:.1f} ms host-side "
        f"({regen_ms / WORLD:.1f} ms/rank); partition + window locality OK "
        f"({full // WINDOW} full windows, each an intact storage extent)"
    )


def training_slice_tier() -> None:
    import torch
    import torch.nn as nn
    import torch.nn.functional as F
    from torch.utils.data import TensorDataset

    from partiallyshuffledistributedsampler_tpu import (
        PartiallyShuffleDistributedSampler,
        StatefulDataLoader,
    )

    torch.manual_seed(0)
    n, batch = 2048, 64
    images = torch.randn(n, 3, 32, 32)
    labels = torch.randint(0, 10, (n,))
    ds = TensorDataset(images, labels)

    class TinyResNet(nn.Module):
        """Residual conv block + classifier — ResNet-50's shape, pocket size."""

        def __init__(self):
            super().__init__()
            self.stem = nn.Conv2d(3, 16, 3, padding=1)
            self.c1 = nn.Conv2d(16, 16, 3, padding=1)
            self.c2 = nn.Conv2d(16, 16, 3, padding=1)
            self.head = nn.Linear(16, 10)

        def forward(self, x):
            x = F.relu(self.stem(x))
            x = F.relu(x + self.c2(F.relu(self.c1(x))))  # residual block
            return self.head(x.mean(dim=(2, 3)))

    def make(rank):
        s = PartiallyShuffleDistributedSampler(
            ds, num_replicas=2, rank=rank, window=256, backend="cpu")
        return s, StatefulDataLoader(ds, batch_size=batch, sampler=s,
                                     num_workers=0)

    # rank 0 trains, checkpoints mid-epoch, and a "restarted process"
    # (fresh sampler + loader + model state) finishes the epoch exactly
    model = TinyResNet()
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    sampler, loader = make(rank=0)
    sampler.set_epoch(0)
    state = None
    for step, (xb, yb) in enumerate(loader):
        loss = F.cross_entropy(model(xb), yb)
        opt.zero_grad(), loss.backward(), opt.step()
        if step == 7:
            state = {"loader": loader.state_dict(),
                     "model": model.state_dict()}
            break
    model2 = TinyResNet()
    model2.load_state_dict(state["model"])
    opt2 = torch.optim.SGD(model2.parameters(), lr=0.05)
    sampler2, loader2 = make(rank=0)
    loader2.load_state_dict(state["loader"])
    expect = -(-len(sampler2) // batch)  # remaining batches (len counts
    steps, last = 0, None                # from the resumed offset)
    for xb, yb in loader2:
        last = F.cross_entropy(model2(xb), yb)
        opt2.zero_grad(), last.backward(), opt2.step()
        steps += 1
    assert steps == expect, (steps, expect)
    print(f"tier 2: trained 8 steps, checkpointed mid-epoch, resumed "
          f"{steps} remaining steps exactly; final loss {last.item():.3f}")


def jax_native_vit_tier() -> None:
    """The same image-pipeline shape JAX-native: mesh-sharded sampler →
    sharded mini-ViT train step, indices never leaving HBM (the ViT-L/16
    consumer of config 4, pocket-sized)."""
    import jax

    if jax.device_count() < 2:
        # the demo wants a mesh; the real-device run of this example has
        # one chip — the 8-virtual-device path is exercised in CI
        # (tests/test_models_vit.py) and dryrun_multichip
        print("tier 3: skipped (single device; see tests/test_models_vit.py)")
        return
    from partiallyshuffledistributedsampler_tpu.models import (
        ViTConfig, demo_vit_run, make_mesh,
    )

    mesh = make_mesh()
    losses = demo_vit_run(
        mesh, ViTConfig(image_size=16, patch_size=4, d_model=64,
                        n_layers=1, n_heads=2, d_ff=128, num_classes=8),
        n_samples=256, window=32, batch_per_dp=4, steps_per_epoch=4,
        epochs=2,
    )
    assert losses[-1] < losses[0]
    print(f"tier 3: JAX-native ViT on {dict(mesh.shape)} mesh — "
          f"loss {losses[0]:.3f} -> {losses[-1]:.3f}, indices never "
          "left HBM")


def main() -> None:
    # Demo default: an 8-device virtual CPU mesh, pinned BEFORE the first
    # backend use (the axon PJRT plugin prepends itself to jax_platforms
    # even when JAX_PLATFORMS=cpu is exported — cf. jax_training_example).
    # PSDS_EXAMPLE_REAL=1 uses whatever real devices are present.
    if os.environ.get("PSDS_EXAMPLE_REAL") != "1":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    real_scale_index_tier()
    training_slice_tier()
    jax_native_vit_tier()
    print("ok: config-2 shape end to end")


if __name__ == "__main__":
    main()
