"""Shared index-serving daemon, end to end on one host.

The deployment shape from docs/SERVICE.md in miniature, in two phases:

1. **Loader integration** — one `IndexServer` owns the epoch streams for
   a 4-rank job; four loader "processes" (threads here — the wire
   protocol is identical) each claim a rank and feed a `HostDataLoader`
   through ``index_client=``.  The served batches are asserted
   bit-identical to a purely local loader.

2. **Crash recovery** — a client streams an epoch batch-by-batch while
   the daemon is killed mid-stream and restarted from its snapshot.  The
   client reconnects with jittered backoff and resumes from its cursor;
   the delivered stream still equals the local sampler run, exactly
   once, no gaps, no duplicates.

Run: ``python examples/index_service_example.py``
"""

import os
import sys
import tempfile
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from partiallyshuffledistributedsampler_tpu.sampler import HostDataLoader
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    PartialShuffleSpec,
    ServiceIndexClient,
)

N, WINDOW, WORLD, BATCH, EPOCH = 12_000, 256, 4, 128, 3


def phase_1_loaders(spec, data) -> None:
    streams: dict[int, np.ndarray] = {}
    errors: list = []

    def loader_process(host, port, rank: int) -> None:
        try:
            with ServiceIndexClient((host, port), rank=rank,
                                    batch=512) as client:
                loader = HostDataLoader(data, window=WINDOW, seed=11,
                                        rank=rank, world=WORLD, batch=BATCH,
                                        index_client=client)
                streams[rank] = np.concatenate(
                    [np.asarray(b["label"]) for b in loader.epoch(EPOCH)])
        except BaseException as exc:
            errors.append((rank, exc))

    with IndexServer(spec) as server:
        host, port = server.address
        print(f"phase 1: daemon up on {host}:{port}, {WORLD} loader ranks")
        workers = [threading.Thread(target=loader_process,
                                    args=(host, port, r))
                   for r in range(WORLD)]
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=120.0)
        assert not errors, errors
        report = server.metrics.report()

    # the served streams must be the local sampler streams, exactly —
    # HostDataLoader truncates to whole batches, so compare that prefix
    for rank in range(WORLD):
        ref = spec.rank_indices(EPOCH, rank)
        ref = ref[: (len(ref) // BATCH) * BATCH]
        assert np.array_equal(streams[rank], ref), f"rank {rank} drifted"
    print(f"  {WORLD} ranks x {len(streams[0])} samples: bit-identical to "
          "the local sampler")
    print("  batches served by rank:",
          {r: c["batches_served"]
           for r, c in sorted(report["clients"].items())})


def phase_2_crash_recovery(spec) -> None:
    with tempfile.TemporaryDirectory() as td:
        snap = os.path.join(td, "index_service.json")
        server = IndexServer(spec, snapshot_path=snap, snapshot_interval=1)
        host, port = server.start()
        print(f"phase 2: daemon on {host}:{port}, snapshot at {snap}")

        client = ServiceIndexClient((host, port), rank=0, batch=256,
                                    reconnect_timeout=30.0)
        delivered = []
        for i, batch in enumerate(client.epoch_batches(EPOCH)):
            delivered.append(batch)
            if i == 3:  # mid-stream: kill the daemon, restart from snapshot
                server.stop()
                print("  daemon killed after batch 3; restarting...")
                server = IndexServer(spec, host=host, port=port,
                                     snapshot_path=snap, snapshot_interval=1)
                server.start()
        stream = np.concatenate(delivered)
        reconnects = client.metrics.report()["counters"].get("reconnects", 0)
        client.close()
        server.stop()

    assert np.array_equal(stream, spec.rank_indices(EPOCH, 0)), \
        "stream across restart drifted from the local sampler"
    assert reconnects >= 1, "restart was never exercised"
    print(f"  {len(stream)} indices across the restart ({reconnects} "
          "reconnects): exactly-once, bit-identical")


def main() -> None:
    data = {"tokens": np.arange(N * 8, dtype=np.int32).reshape(N, 8),
            "label": np.arange(N, dtype=np.int64)}
    spec = PartialShuffleSpec.plain(N, window=WINDOW, seed=11, world=WORLD)
    phase_1_loaders(spec, data)
    phase_2_crash_recovery(spec)
    print("ok: index service end to end")


if __name__ == "__main__":
    main()
