"""Driver config #5 shape: Llama-3 8B pretrain, 10B-sample index space,
v5p-256 — epoch reseed + ICI broadcast stress (BASELINE.json configs[4]).

What this config stresses and how this example drives it:

1. **>=2^31 index space**: 10B samples overflow int32; the framework's
   uint64 position math is enabled with ``enable_big_index_space()`` and
   indices beyond 2^31 must actually appear.  Verified here by random
   access (``stream_indices_at_jax``) — O(probe) spot reads into the 10B
   stream at true scale, bit-identical to the numpy reference — plus a
   full per-rank shard regen at the v5p-256 world size.
2. **Epoch reseed + ICI broadcast**: Llama-scale pretrain reseeds every
   epoch; the seed must be agreed across the mesh WITHOUT a host barrier.
   The fused ``shard_map`` program (rank-0-masked psum + regen in ONE
   dispatch) is driven for many consecutive reseeds on a mesh, with
   deliberately divergent non-rank-0 seed inputs to prove the collective
   (rank 0 wins), and the per-reseed dispatch cost is reported.

Run: python examples/llama3_10b_index_example.py
(Uses an 8-virtual-device CPU mesh unless PSDS_EXAMPLE_REAL=1; the 10B
index math itself is identical on any backend — SPEC.md.
PSDS_EXAMPLE_FAST=1 shrinks the shard/reseed tiers for CI smoke.)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = 10_000_000_000  # 10B-sample index space
WINDOW = 8192
WORLD = 256  # v5p-256


def main() -> None:
    use_real = os.environ.get("PSDS_EXAMPLE_REAL") == "1"
    if not use_real:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if not use_real:
        jax.config.update("jax_platforms", "cpu")

    import partiallyshuffledistributedsampler_tpu as psds

    psds.enable_big_index_space()  # uint64 positions: BEFORE the first jit

    # --- tier 1: the 10B stream at true scale, via random access ---------
    from partiallyshuffledistributedsampler_tpu.ops.cpu import (
        stream_indices_at_np,
    )

    fast = os.environ.get("PSDS_EXAMPLE_FAST") == "1"
    rng = np.random.default_rng(0)
    probes = np.sort(rng.integers(0, N, size=512 if fast else 4096))
    dev = np.asarray(psds.stream_indices_at_jax(probes, N, WINDOW,
                                                seed=7, epoch=3))
    ref = stream_indices_at_np(probes, N, WINDOW, 7, 3)
    assert (dev == ref).all(), "device random access != numpy reference"
    assert dev.dtype == np.int64 and int(dev.max()) > 2**31, (
        "a 10B stream must produce indices beyond int32 range"
    )
    assert len(np.unique(dev)) == len(dev)  # a bijection can't collide
    print(f"tier 1: {len(probes)} random probes into the 10B stream OK "
          f"(int64, max index {int(dev.max()):,} > 2^31, bit-identical "
          f"to numpy)")

    # one rank's full shard at the v5p-256 world size: ~39M int64 indices
    # (fast mode widens world so the shard stays CI-sized; same code path)
    world = 4096 * 16 if fast else WORLD
    t0 = time.perf_counter()
    shard = psds.epoch_indices_jax(N, WINDOW, 7, 3, rank=0, world=world)
    shard.block_until_ready()
    ms = (time.perf_counter() - t0) * 1e3
    ns = shard.shape[0]
    assert ns == -(-N // world)
    # the rank slice law, spot-checked against random access: entry j of
    # rank r's shard is stream position j*world + r
    j = np.asarray([0, 1, ns // 2, ns - 1], dtype=np.int64)
    expect = stream_indices_at_np(j * world + 0, N, WINDOW, 7, 3)
    got = np.asarray(shard)[j]
    assert (got == expect).all()
    print(f"tier 2: rank-0 shard of world={world}: {ns:,} int64 indices "
          f"in {ms:.0f} ms (incl. first compile) on "
          f"{jax.devices()[0].platform}")

    # --- tier 3: reseed stress over the mesh (ICI broadcast each epoch) --
    from partiallyshuffledistributedsampler_tpu.parallel import (
        data_mesh, make_regen_fn, make_seed_triple,
    )

    mesh = data_mesh()
    world = mesh.shape["data"]
    # scaled n so the demo runs anywhere; the PROGRAM is the production
    # one — rank-0-masked psum seed agreement fused with regen
    n_small = 1_000_000
    fn, num = make_regen_fn(mesh, n_small, WINDOW)
    epochs = 4 if fast else 32
    rows = []
    t0 = time.perf_counter()
    for e in range(epochs):
        # divergent non-rank-0 seed inputs: the collective must make
        # rank 0's (seed, epoch) win silently, every reseed
        local = np.asarray(
            [[7, 0, e]] + [[9999 + r, r, e + 100] for r in range(1, world)],
            dtype=np.uint32,
        )
        triple = make_seed_triple(mesh, 7, e, local_seeds=local)
        rows.append(fn(triple))
    rows[-1].block_until_ready()
    per_reseed_ms = (time.perf_counter() - t0) * 1e3 / epochs
    first = np.asarray(rows[0])
    from partiallyshuffledistributedsampler_tpu.ops.cpu import (
        epoch_indices_np,
    )

    for r in range(world):
        assert (first[r] == epoch_indices_np(
            n_small, WINDOW, 7, 0, r, world)).all(), (
            "rank-0 seed did not win the agreement collective"
        )
    assert not (first == np.asarray(rows[1])).all()  # reseed reshuffles
    print(f"tier 3: {epochs} consecutive reseeds over a {world}-device "
          f"mesh, seed agreed by the in-program collective each time "
          f"(divergent inputs, rank 0 won), {per_reseed_ms:.1f} ms/reseed "
          f"wall incl. dispatch")

    # --- tier 4: the pretrain DATA MIXTURE (SPEC.md §8) over the mesh ----
    # Llama-style corpus mixing: web/code/books at fixed proportions, each
    # source partially shuffled, interleaved at exact per-block quotas,
    # served shard-per-device with the same in-program seed agreement.
    from partiallyshuffledistributedsampler_tpu.ops.mixture import (
        MixtureSpec, mixture_epoch_indices_np,
    )
    from partiallyshuffledistributedsampler_tpu.parallel import (
        sharded_mixture_indices,
    )

    spec = MixtureSpec(
        sources=[700_000, 200_000, 100_000],  # web / code / books
        weights=[70, 20, 10],
        windows=WINDOW,
    )
    mids = np.asarray(sharded_mixture_indices(mesh, spec, 7, 0))
    for r in range(world):
        assert (mids[r] == mixture_epoch_indices_np(
            spec, 7, 0, r, world)).all()
    src, _ = spec.decompose(mids.reshape(-1))
    counts = np.bincount(src, minlength=3) / len(src)
    print(f"tier 4: 70/20/10 corpus mixture over the mesh — realized "
          f"proportions {counts.round(4).tolist()} (exact per 1024-block), "
          f"per-device shards bit-identical to the numpy law")
    print("ok: config-5 shape end to end")


if __name__ == "__main__":
    main()
