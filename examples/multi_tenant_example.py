"""One shared index daemon, many jobs: the multi-tenant deployment shape.

The docs/SERVICE.md "Tenancy" story in miniature, in three phases:

1. **Namespaces** — one `IndexServer(multi_tenant=True)` hosts a plain-
   mode job and a mixture-mode job at once.  Each client HELLOs with its
   own spec; the daemon creates/attaches the matching namespace keyed by
   the world-stripped spec fingerprint.  Both jobs' streams are asserted
   bit-identical to dedicated single-job daemons.

2. **Admission** — a `TenantQuota(max_ranks=1)` tenant refuses its
   second rank with a retryable ``tenant_admission`` error; the client
   waits the ``retry_ms`` hint out and is admitted the moment the first
   lease frees.  The co-resident default tenant never notices.

3. **Fair share** — both tenants regenerate epochs through one
   concurrency-1 `FairShareScheduler`; the ``regen_queue_ms`` histogram
   shows the stride queue actually arbitrated, and the streams stay
   exact.

Run: ``python examples/multi_tenant_example.py``
"""

import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from partiallyshuffledistributedsampler_tpu.ops.mixture import MixtureSpec
from partiallyshuffledistributedsampler_tpu.service import (
    FairShareScheduler,
    IndexServer,
    PartialShuffleSpec,
    ServiceIndexClient,
    TenantQuota,
)

N, WINDOW = 12_000, 256


def make_specs():
    plain = PartialShuffleSpec.plain(N, window=WINDOW, seed=11, world=1)
    mixture = PartialShuffleSpec.mixture(
        MixtureSpec([N // 2, N // 4], [3, 1], windows=WINDOW),
        epoch_samples=N // 2, seed=23, world=1)
    return plain, mixture


def phase_1_namespaces(plain, mixture) -> None:
    refs = {tag: np.asarray(s.rank_indices(1, 0))
            for tag, s in (("plain", plain), ("mixture", mixture))}
    got, errors = {}, []

    def job(tag, spec, address):
        try:
            with ServiceIndexClient(address, rank=0, batch=512,
                                    spec=spec) as client:
                got[tag] = client.epoch_indices(1)
        except BaseException as exc:
            errors.append((tag, exc))

    with IndexServer(plain, multi_tenant=True) as server:
        print(f"phase 1: multi-tenant daemon on {server.address[0]}:"
              f"{server.address[1]}")
        workers = [threading.Thread(target=job, args=(t, s, server.address))
                   for t, s in (("plain", plain), ("mixture", mixture))]
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=120.0)
        assert not errors, errors
        tenants = sorted(server.tenants())

    for tag, ref in refs.items():
        assert np.array_equal(got[tag], ref), f"tenant {tag} drifted"
    print(f"  2 jobs, 1 daemon, {len(tenants)} namespaces: both streams "
          "bit-identical to dedicated daemons")


def phase_2_admission(plain, mixture) -> None:
    m2 = mixture.with_world(2)
    with IndexServer(plain, multi_tenant=True,
                     tenant_quota=TenantQuota(max_ranks=1)) as server:
        holder = ServiceIndexClient(server.address, rank=0, batch=512,
                                    spec=m2)
        try:
            holder.epoch_indices(0)  # rank 0 holds the tenant's only slot

            # rank 1 is over quota: the typed tenant_admission refusal is
            # waited out (inside the RPC retry loop — no eager connect)
            # until holder.close() frees the lease
            release = threading.Timer(0.4, holder.close)
            release.start()
            waiter = ServiceIndexClient(server.address, rank=1, batch=512,
                                        spec=m2, reconnect_timeout=30.0)
            try:
                stream = waiter.epoch_indices(0)
                waits = waiter.metrics.report()["counters"].get(
                    "admission_waits", 0)
            finally:
                release.cancel()
                waiter.close()
        finally:
            holder.close()

    ref = np.asarray(m2.rank_indices(0, 1))
    assert np.array_equal(stream, ref), "post-admission stream drifted"
    assert waits >= 1, "the quota never pushed back"
    print(f"phase 2: rank over quota waited out {waits} admission "
          "refusal(s), then streamed exactly")


def phase_3_fair_share(plain, mixture) -> None:
    sched = FairShareScheduler(concurrency=1)
    got, errors = {}, []

    def job(tag, spec, address):
        try:
            with ServiceIndexClient(address, rank=0, batch=512,
                                    spec=spec) as client:
                got[tag] = client.epoch_indices(2)
        except BaseException as exc:
            errors.append((tag, exc))

    with IndexServer(plain, multi_tenant=True,
                     regen_scheduler=sched) as server:
        workers = [threading.Thread(target=job, args=(t, s, server.address))
                   for t, s in (("plain", plain), ("mixture", mixture))]
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=120.0)
        assert not errors, errors
        queue = server.metrics.report()["histograms"].get(
            "regen_queue_ms", {})

    for tag, spec in (("plain", plain), ("mixture", mixture)):
        assert np.array_equal(got[tag],
                              np.asarray(spec.rank_indices(2, 0))), \
            f"tenant {tag} drifted under the fair-share queue"
    assert queue.get("count", 0) >= 2, "the regen queue never arbitrated"
    print(f"phase 3: {queue['count']} regens arbitrated through the "
          "concurrency-1 fair-share queue, streams exact")


def main() -> None:
    plain, mixture = make_specs()
    phase_1_namespaces(plain, mixture)
    phase_2_admission(plain, mixture)
    phase_3_fair_share(plain, mixture)
    print("ok: multi-tenant service end to end")


if __name__ == "__main__":
    main()
