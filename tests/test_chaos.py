"""Deterministic fault-injection matrix for the served-index stack.

The contract under test (docs/RESILIENCE.md): for every fault site and
every stream mode, the consumer sees either a bit-identical stream or a
typed error within its deadline — never a hang, never silently-wrong
indices.  Every test asserts ``plan.fired(...) > 0``: a chaos test whose
fault never fired is vacuous and must fail.

These run inside tier-1 (they are fast and fully deterministic) and are
also the first leg of the ``make chaos-smoke`` gate (``-m chaos``).
"""

from __future__ import annotations

import random
import socket
import threading
import time
import warnings

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu import faults as F
from partiallyshuffledistributedsampler_tpu.durability import WriteAheadLog
from partiallyshuffledistributedsampler_tpu.ops.mixture import MixtureSpec
from partiallyshuffledistributedsampler_tpu.sampler.host_loader import (
    HostDataLoader,
)
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    PartialShuffleSpec,
    ServiceIndexClient,
)
from partiallyshuffledistributedsampler_tpu.service import protocol as P
from partiallyshuffledistributedsampler_tpu.service.client import (
    ServiceUnavailable,
)
from partiallyshuffledistributedsampler_tpu.utils import (
    RetryPolicy,
    StallError,
)

pytestmark = pytest.mark.chaos


# ----------------------------------------------------------- stream builders
def plain_spec(world=1):
    return PartialShuffleSpec.plain(530, window=32, seed=7, world=world)


def mixture_spec(world=1):
    ms = MixtureSpec([100, 200, 50], [5, 3, 2], block=16)
    return PartialShuffleSpec.mixture(ms, seed=3, world=world,
                                      epoch_samples=300)


def shard_spec(world=1):
    return PartialShuffleSpec.shard([17, 5, 29, 11, 40, 8, 23, 9], window=4,
                                    seed=9, world=world,
                                    within_shard_shuffle=True)


SPECS = {"plain": plain_spec, "mixture": mixture_spec, "shard": shard_spec}


def make_loader(mode, **kw):
    """A small HostDataLoader in each stream mode (world=1, rank 0)."""
    if mode == "plain":
        X = np.arange(530, dtype=np.int64)
        return HostDataLoader(X, window=32, batch=64, seed=7, rank=0,
                              world=1, **kw)
    if mode == "mixture":
        ms = MixtureSpec([100, 200, 50], [5, 3, 2], block=16)
        data = [np.arange(100, dtype=np.int64),
                np.arange(200, dtype=np.int64),
                np.arange(50, dtype=np.int64)]
        return HostDataLoader(data, mixture=ms, epoch_samples=300, batch=64,
                              seed=3, rank=0, world=1, **kw)
    sizes = [17, 5, 29, 11, 40, 8, 23, 9]
    X = np.arange(sum(sizes), dtype=np.int64)
    return HostDataLoader(X, shard_sizes=sizes, window=4, batch=32, seed=9,
                          rank=0, world=1, **kw)


def collect(loader, epoch=0):
    return [np.asarray(b) for b in loader.epoch(epoch)]


def _raw_hello(addr, rank, batch=32):
    sock = socket.create_connection(addr, timeout=5.0)
    P.send_msg(sock, P.MSG_HELLO,
               {"proto": P.PROTOCOL_VERSION, "rank": rank, "batch": batch})
    msg, header, _ = P.recv_msg(sock)
    return sock, msg, header


# ------------------------------------------------------- plan/rule mechanics
def test_fault_rule_validation():
    with pytest.raises(ValueError):
        F.FaultRule(site="nope", kind="error")
    with pytest.raises(ValueError):
        F.FaultRule(site="loader.regen", kind="nope")
    with pytest.raises(ValueError):
        F.FaultRule(site="loader.regen", kind="error", nth=0)
    with pytest.raises(ValueError):
        F.FaultRule(site="loader.regen", kind="error", every=0)


def test_fault_plan_counters_are_deterministic():
    def run():
        plan = F.FaultPlan([F.FaultRule(site="loader.regen", kind="error",
                                        nth=2, every=3, count=2)])
        return [plan.draw("loader.regen") is not None for _ in range(12)]

    a, b = run(), run()
    assert a == b
    # nth=2, every=3, count=2 -> fires at exactly hits 2 and 5
    assert [i + 1 for i, fired in enumerate(a) if fired] == [2, 5]


def test_fault_plan_probabilistic_is_seed_reproducible():
    def run(seed):
        plan = F.FaultPlan([F.FaultRule(site="loader.regen", kind="error",
                                        p=0.5, count=0)], seed=seed)
        return [plan.draw("loader.regen") is not None for _ in range(64)]

    assert run(1) == run(1)
    assert run(1) != run(2)
    assert 0 < sum(run(3)) < 64  # actually probabilistic, not all-or-nothing


def test_fault_plan_json_and_env_roundtrip(monkeypatch):
    plan = F.FaultPlan([F.FaultRule(site="service.send", kind="torn_frame",
                                    nth=3)], seed=5)
    back = F.FaultPlan.from_json(plan.to_json())
    assert back.rules == plan.rules and back.seed == plan.seed
    monkeypatch.setenv("PSDS_FAULT_PLAN", plan.to_json())
    env_plan = F.FaultPlan.from_env()
    assert env_plan is not None and env_plan.rules == plan.rules
    monkeypatch.delenv("PSDS_FAULT_PLAN")
    assert F.FaultPlan.from_env() is None


def test_plans_nest_lifo_and_unarmed_draw_is_none():
    assert F.draw("loader.regen") is None  # fast path: no plan, no effect
    outer = F.FaultPlan([F.FaultRule(site="loader.regen", kind="error")])
    inner = F.FaultPlan([F.FaultRule(site="loader.prefetch", kind="delay")])
    with outer:
        assert F.active() is outer
        with inner:
            assert F.active() is inner
        assert F.active() is outer
    assert F.draw("loader.regen") is None


# ------------------------------------------------------------- retry policy
class FakeTime:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def clock(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


def test_retry_backoff_jitter_stays_inside_envelope():
    ft = FakeTime()
    pol = RetryPolicy(base=0.1, max_delay=0.4, deadline=None,
                      clock=ft.clock, sleep=ft.sleep,
                      rng=random.Random(0))
    for k in range(10):
        d = pol.backoff(k)
        assert 0.0 <= d <= min(0.4, 0.1 * 2.0 ** k)


def test_retry_deadline_refuses_to_oversleep():
    ft = FakeTime()
    pol = RetryPolicy(base=0.1, max_delay=0.4, deadline=1.0,
                      clock=ft.clock, sleep=ft.sleep,
                      rng=random.Random(1))
    op = pol.begin()
    while op.pause():
        pass
    assert ft.t <= 1.0  # never slept past the operation deadline
    assert op.attempts >= 1


def test_retry_budget_caps_attempts():
    ft = FakeTime()
    pol = RetryPolicy(base=0.0, max_delay=0.0, deadline=None, budget=3,
                      clock=ft.clock, sleep=ft.sleep)
    op = pol.begin()
    assert [op.pause() for _ in range(4)] == [True, True, True, False]


def test_retry_pause_honors_server_suggested_minimum():
    ft = FakeTime()
    pol = RetryPolicy(base=0.0, max_delay=0.0, deadline=None,
                      clock=ft.clock, sleep=ft.sleep)
    op = pol.begin()
    assert op.pause(min_delay=0.2)
    assert ft.sleeps == [0.2]


def test_circuit_breaker_open_halfopen_reopen_close():
    ft = FakeTime()
    pol = RetryPolicy(breaker_threshold=2, breaker_reset=1.0,
                      clock=ft.clock, sleep=ft.sleep)
    assert pol.allow()
    pol.record_failure()
    assert pol.allow()  # below threshold
    pol.record_failure()
    assert not pol.allow()  # open
    ft.t += 1.0
    assert pol.allow()  # half-open probe admitted
    pol.record_failure()
    assert not pol.allow()  # failed probe re-opens a fresh interval
    ft.t += 1.0
    assert pol.allow()
    pol.record_success()
    assert pol.allow() and not pol.circuit_open  # closed


# ------------------------------------------------- service-side fault matrix
# (site, kind, rule kwargs) — nth skips the handshake so faults land mid-
# stream; counts are finite so every scenario must terminate
_SERVICE_FAULTS = [
    ("service.send", "torn_frame", dict(nth=2, count=1)),
    ("service.send", "reset", dict(nth=2, count=1)),
    ("service.send", "delay", dict(nth=2, count=2, delay_s=0.01)),
    ("service.recv", "reset", dict(nth=2, count=1)),
    ("service.recv", "corrupt", dict(nth=2, count=1)),
    ("server.dispatch", "thread_death", dict(nth=2, count=1)),
    ("server.snapshot_write", "disk_full", dict(nth=1, count=2)),
    # the pipelined window's coalesced top-up send: a reset tears the
    # connection with a full lookahead of unacked requests in flight,
    # a delay stretches it — either way the guarded path must replay
    # the window exactly-once
    ("client.pipeline", "reset", dict(nth=1, count=1)),
    ("client.pipeline", "delay", dict(nth=2, count=2, delay_s=0.01)),
]


@pytest.mark.parametrize("mode", sorted(SPECS))
@pytest.mark.parametrize(
    "site,kind,rule_kw", _SERVICE_FAULTS,
    ids=[f"{s}-{k}" for s, k, _ in _SERVICE_FAULTS])
def test_service_fault_matrix_stream_bit_identical(mode, site, kind, rule_kw,
                                                   tmp_path):
    spec = SPECS[mode](world=1)
    ref = np.asarray(spec.rank_indices(1, 0))
    plan = F.FaultPlan([F.FaultRule(site=site, kind=kind, **rule_kw)])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with plan:
            with IndexServer(spec, snapshot_path=str(tmp_path / "snap.json"),
                             snapshot_interval=1) as srv:
                with ServiceIndexClient(srv.address, rank=0, batch=37,
                                        backoff_base=0.01,
                                        reconnect_timeout=10.0) as client:
                    got = client.epoch_indices(1)
    assert plan.fired(site) > 0, "fault never fired; the test is vacuous"
    assert np.array_equal(got, ref), f"stream diverged under {kind} at {site}"
    if kind == "corrupt":
        counters = client.metrics.report()["counters"]
        assert counters.get("checksum_rejects", 0) >= 1
    if kind == "disk_full":
        assert srv.metrics.report()["counters"].get("snapshot_errors", 0) >= 1


def test_persistent_corruption_is_a_typed_error():
    spec = plain_spec(world=1)
    # every reply corrupted: re-requesting cannot help; the client must
    # give up with the typed checksum error, not loop forever
    plan = F.FaultPlan([F.FaultRule(site="service.recv", kind="corrupt",
                                    count=0)])
    t0 = time.monotonic()
    with IndexServer(spec) as srv, plan:
        with ServiceIndexClient(srv.address, rank=0, batch=37) as client:
            with pytest.raises(P.ChecksumError):
                client.epoch_indices(1)
    assert plan.fired("service.recv") >= 2
    assert time.monotonic() - t0 < 10.0


@pytest.mark.parametrize("mode", sorted(SPECS))
@pytest.mark.parametrize("kind", ["torn_frame", "reset"])
def test_ack_carrying_request_torn_mid_flight_exactly_once(mode, kind):
    """The coalesced GET_BATCH frames each carry the delivered-ack
    cursor.  Tearing that send mid-flight (after epoch 1 delivered, so
    the lost frames carry real ack state) must not double-serve or drop
    anything: the cursor only advanced on yield, so the replay through
    the guarded path keeps both epochs bit-identical."""
    spec = SPECS[mode](world=1)
    # nth high enough to land mid-stream of the second epoch's window
    plan = F.FaultPlan([F.FaultRule(site="service.send", kind=kind,
                                    nth=4, count=1)])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with plan:
            with IndexServer(spec) as srv:
                with ServiceIndexClient(srv.address, rank=0, batch=37,
                                        lookahead=4, backoff_base=0.01,
                                        reconnect_timeout=10.0) as client:
                    got1 = client.epoch_indices(1)
                    got2 = client.epoch_indices(2)
    assert plan.fired("service.send") > 0, "fault never fired; vacuous"
    assert np.array_equal(got1, np.asarray(spec.rank_indices(1, 0)))
    assert np.array_equal(got2, np.asarray(spec.rank_indices(2, 0)))


# --------------------------------------------------- loader-side fault matrix
@pytest.mark.parametrize("mode", sorted(SPECS))
def test_loader_prefetch_delay_stream_identical(mode):
    ref = collect(make_loader(mode))
    plan = F.FaultPlan([F.FaultRule(site="loader.prefetch", kind="delay",
                                    nth=2, count=2, delay_s=0.01)])
    with plan:
        got = collect(make_loader(mode))
    assert plan.fired("loader.prefetch") > 0
    assert len(got) == len(ref)
    for a, b in zip(got, ref):
        assert np.array_equal(a, b)


@pytest.mark.parametrize("mode", sorted(SPECS))
def test_loader_prefetch_thread_death_raises_stall(mode):
    loader = make_loader(mode, stall_timeout=2.0)
    plan = F.FaultPlan([F.FaultRule(site="loader.prefetch",
                                    kind="thread_death", nth=2)])
    t0 = time.monotonic()
    with plan:
        with pytest.raises(StallError) as ei:
            collect(loader)
    assert plan.fired("loader.prefetch") == 1
    assert time.monotonic() - t0 < 10.0  # typed error, not a hang
    assert ei.value.thread_alive is False


@pytest.mark.parametrize("mode", sorted(SPECS))
def test_loader_regen_fault_is_typed(mode):
    loader = make_loader(mode)
    with F.FaultPlan([F.FaultRule(site="loader.regen",
                                  kind="error")]) as plan:
        with pytest.raises(F.InjectedFault) as ei:
            loader.epoch_indices(0)
    assert plan.fired("loader.regen") == 1
    assert ei.value.site == "loader.regen"


@pytest.mark.parametrize("mode", sorted(SPECS))
@pytest.mark.parametrize("kind", ["thread_death", "error", "delay"])
def test_loader_boundary_prefetch_fault_recomputes_foreground(mode, kind):
    """The epoch-boundary prefetch worker is advisory: killing it,
    failing it, or delaying it must leave every epoch's stream identical
    — the boundary just recomputes in the foreground."""
    ref_loader = make_loader(mode)
    ref = [collect(ref_loader, e) for e in range(3)]
    kw = dict(nth=1, count=2)
    if kind == "delay":
        kw["delay_s"] = 0.01
    plan = F.FaultPlan([F.FaultRule(site="loader.boundary", kind=kind,
                                    **kw)])
    with plan:
        loader = make_loader(mode)
        got = [collect(loader, e) for e in range(3)]
    assert plan.fired("loader.boundary") > 0, "fault never fired; vacuous"
    for e in range(3):
        assert len(got[e]) == len(ref[e])
        for a, b in zip(got[e], ref[e]):
            assert np.array_equal(a, b), (
                f"boundary fault changed epoch {e} ({mode}/{kind})")


def test_loader_stall_watchdog_on_wedged_producer():
    """A producer wedged (not dead) past stall_timeout surfaces a
    StallError embedding the stuck thread's stack."""
    loader = make_loader("plain", stall_timeout=0.3)
    plan = F.FaultPlan([F.FaultRule(site="loader.prefetch", kind="delay",
                                    nth=1, count=1, delay_s=1.5)])
    with plan:
        with pytest.raises(StallError) as ei:
            collect(loader)
    assert plan.fired("loader.prefetch") == 1
    assert ei.value.thread_alive is True
    assert "stack of stalled thread" in str(ei.value)


# -------------------------------------------------- degraded mode + re-attach
def test_degraded_fallback_mid_epoch_then_reattach():
    X = np.arange(530, dtype=np.int64)
    local = HostDataLoader(X, window=32, batch=64, seed=7, rank=0, world=1)
    with IndexServer(plain_spec(world=1)) as srv:
        client = ServiceIndexClient(srv.address, rank=0, batch=37,
                                    backoff_base=0.01,
                                    reconnect_timeout=0.3)
        loader = HostDataLoader(X, window=32, batch=64, seed=7, rank=0,
                                world=1, index_client=client,
                                reattach_interval=0.05)
        try:
            # healthy epoch first: service stream == local stream
            assert np.array_equal(loader.epoch_indices(0),
                                  local.epoch_indices(0))
            assert not loader.degraded
            # now every reply resets: the daemon is effectively dead
            # mid-epoch, past the client's reconnect deadline
            plan = F.FaultPlan([F.FaultRule(site="service.recv",
                                            kind="reset", count=0)])
            with plan:
                with warnings.catch_warnings(record=True) as caught:
                    warnings.simplefilter("always")
                    got = loader.epoch_indices(1)
            assert plan.fired("service.recv") > 0
            assert loader.degraded
            assert any("index service unavailable" in str(w.message)
                       for w in caught)
            assert np.array_equal(got, local.epoch_indices(1))
            counters = client.metrics.report()["counters"]
            assert counters.get("degraded_mode", 0) >= 1
            # the daemon is healthy again (plan disarmed): past the
            # re-attach interval the next epoch probes and re-attaches
            time.sleep(0.06)
            back = loader.epoch_indices(2)
            assert not loader.degraded
            assert np.array_equal(back, local.epoch_indices(2))
            counters = client.metrics.report()["counters"]
            assert counters.get("reattached", 0) >= 1
        finally:
            client.close()


def test_degraded_fallback_off_raises_typed_error():
    X = np.arange(530, dtype=np.int64)
    srv = IndexServer(plain_spec(world=1))
    srv.start()
    client = ServiceIndexClient(srv.address, rank=0, batch=37,
                                backoff_base=0.01, reconnect_timeout=0.2)
    loader = HostDataLoader(X, window=32, batch=64, seed=7, rank=0,
                            world=1, index_client=client,
                            degraded_fallback=False)
    try:
        assert loader.epoch_indices(0) is not None
        srv.stop()
        with pytest.raises(ServiceUnavailable):
            loader.epoch_indices(1)
        assert not loader.degraded
    finally:
        client.close()


# ----------------------------------------------------------- graceful drain
def test_drain_replies_structured_error_then_stop_leaks_no_threads():
    srv = IndexServer(plain_spec(world=1))
    srv.start()
    sock, msg, _ = _raw_hello(srv.address, rank=0)
    try:
        assert msg == P.MSG_WELCOME
        srv._draining.set()  # the stop() drain window, held open
        P.send_msg(sock, P.MSG_GET_BATCH,
                   {"rank": 0, "epoch": 0, "seq": 0, "ack": -1})
        msg, header, _ = P.recv_msg(sock)
        assert msg == P.MSG_ERROR and header["code"] == "draining"
        assert header["retry_ms"] > 0
    finally:
        sock.close()
    srv.stop()
    alive = [t.name for t in threading.enumerate()
             if t.name.startswith("psds-service") and t.is_alive()]
    assert not alive, f"stop() leaked serve threads: {alive}"


def test_client_survives_drain_window_across_restart():
    """A stop() with a long drain window answers in-flight requests with
    ``draining`` and the retrying client completes bit-identically once
    the server is back."""
    spec = plain_spec(world=1)
    ref = np.asarray(spec.rank_indices(1, 0))
    srv = IndexServer(spec)
    srv.start()
    client = ServiceIndexClient(srv.address, rank=0, batch=37,
                                backoff_base=0.01, reconnect_timeout=10.0)
    got = []

    def bounce():
        srv.stop(drain_s=0.2)
        srv.start()  # same instance re-binds the same port

    try:
        it = client.epoch_batches(1)
        for _ in range(3):
            got.append(next(it))
        bouncer = threading.Thread(target=bounce)
        bouncer.start()
        time.sleep(0.05)  # land the next requests inside the drain window
        got.extend(it)  # rides draining replies, reconnects, finishes
        bouncer.join()
    finally:
        client.close()
        srv.stop()
    assert np.array_equal(np.concatenate(got), ref)


# ------------------------------------------------- elastic membership faults
# (site, kind, rule kwargs): nth=1 lands on the barrier trigger, nth=2 on
# the commit (which fires only once every drain participant arrived) —
# both before any state mutation, so a retry always finds clean state
_ELASTIC_FAULTS = [
    ("server.reshard", "delay", dict(nth=1, count=2, delay_s=0.01)),
    ("server.reshard", "reset", dict(nth=1, count=1)),
    ("server.reshard", "thread_death", dict(nth=1, count=1)),
    ("server.reshard", "reset", dict(nth=2, count=1)),
    ("client.leave", "delay", dict(nth=1, count=1, delay_s=0.01)),
    ("client.leave", "reset", dict(nth=1, count=1)),
    ("client.leave", "error", dict(nth=1, count=1)),
    ("client.leave", "thread_death", dict(nth=1, count=1)),
]


@pytest.mark.parametrize("mode", sorted(SPECS))
@pytest.mark.parametrize(
    "site,kind,rule_kw", _ELASTIC_FAULTS,
    ids=[f"{s}-{k}-nth{kw.get('nth', 1)}" for s, k, kw in _ELASTIC_FAULTS])
def test_elastic_fault_matrix_exactly_once(mode, site, kind, rule_kw):
    """Faults at the reshard trigger, the barrier commit, or the LEAVE
    call itself: the epoch union stays exactly the uninterrupted stream
    (2 -> 1 has no wrap-pad) — a fault either delays the world change or
    aborts it cleanly as a typed error, never tears it half-applied."""
    spec = SPECS[mode](world=2)
    ref = np.concatenate([np.asarray(spec.rank_indices(0, r))
                          for r in range(2)])
    op = "leave" if site == "client.leave" else "reshard"
    plan = F.FaultPlan([F.FaultRule(site=site, kind=kind, **rule_kw)])
    delivered = {}
    aborted = []
    lock = threading.Lock()
    b_hit = threading.Barrier(2)
    b_go = threading.Barrier(2)
    with plan:
        with IndexServer(spec) as srv:

            def worker(r):
                got = []
                c = ServiceIndexClient(srv.address, rank=r, batch=31,
                                       backoff_base=0.01,
                                       reconnect_timeout=15.0)
                try:
                    it = c.epoch_batches(0)
                    for _ in range(1 + r):
                        got.append(next(it))
                    b_hit.wait(timeout=30.0)
                    if r == 0:
                        try:
                            if op == "leave":
                                c.leave(grace_ms=60_000)
                            else:
                                c.reshard(1)
                        except (F.InjectedFault, F.InjectedThreadDeath,
                                ConnectionError) as exc:
                            with lock:
                                aborted.append(exc)
                    b_go.wait(timeout=30.0)
                    for arr in it:
                        got.append(arr)
                finally:
                    with lock:
                        delivered[r] = got
                    c.close()

            threads = [threading.Thread(target=worker, args=(r,))
                       for r in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60.0)
                assert not t.is_alive(), "elastic chaos worker hung"
            generation = srv._state_dict()["generation"]
    assert plan.fired(site) > 0, "fault never fired; the test is vacuous"
    if aborted:
        # the LEAVE died client-side before reaching the daemon: the
        # world must be untouched and both ranks finish their epoch
        assert site == "client.leave"
        assert generation == 0
    else:
        assert generation == 1, "world change lost under injected fault"
    union = np.concatenate(
        [np.concatenate(v) if v else np.empty(0, np.int64)
         for v in delivered.values()])
    assert np.array_equal(np.sort(union), np.sort(ref)), (
        f"stream not exactly-once under {kind} at {site}")


# ---------------------------------------------------------- snapshot faults
def test_snapshot_disk_full_does_not_stop_serving(tmp_path):
    spec = plain_spec(world=1)
    ref = np.asarray(spec.rank_indices(0, 0))
    plan = F.FaultPlan([F.FaultRule(site="server.snapshot_write",
                                    kind="disk_full", count=0)])
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with plan:
            with IndexServer(spec, snapshot_path=str(tmp_path / "s.json"),
                             snapshot_interval=1) as srv:
                with ServiceIndexClient(srv.address, rank=0,
                                        batch=37) as client:
                    got = client.epoch_indices(0)
    assert plan.fired("server.snapshot_write") >= 1
    assert np.array_equal(got, ref)
    assert srv.metrics.report()["counters"].get("snapshot_errors", 0) >= 1
    # warned exactly once, not once per failed write
    snap_warnings = [w for w in caught
                     if "snapshot write" in str(w.message)]
    assert len(snap_warnings) == 1


# ------------------------------------------- hot-standby replication faults
def _replicated_pair(spec, feed_timeout=0.25):
    standby = IndexServer(spec, role="standby",
                          repl_feed_timeout=feed_timeout)
    standby.start()
    primary = IndexServer(spec, standby=standby.address,
                          repl_feed_timeout=feed_timeout)
    primary.start()
    return primary, standby


def _wait_synced(primary, standby, timeout=10.0):
    t0 = time.monotonic()
    while not (primary._shipper is not None
               and primary._shipper.synced.is_set()
               and standby._applied_lsn >= primary._repl_log.lsn):
        if time.monotonic() - t0 > timeout:
            raise AssertionError("standby never caught up")
        time.sleep(0.01)


def test_repl_append_fault_never_touches_the_serving_path():
    """A WAL append that dies must cost the standby a re-SYNC, never the
    clients a byte: the stream stays bit-identical and the log heals."""
    spec = plain_spec(world=1)
    ref = np.asarray(spec.rank_indices(0, 0))
    plan = F.FaultPlan([F.FaultRule(site="repl.append", kind="error",
                                    nth=2, count=2)])
    with plan:
        primary, standby = _replicated_pair(spec)
        try:
            with ServiceIndexClient(primary.address, rank=0, batch=37,
                                    backoff_base=0.01) as client:
                got = client.epoch_indices(0)
            _wait_synced(primary, standby)
            assert standby._cursors.get(0, {}).get("epoch") == 0
        finally:
            primary.stop()
            standby.stop()
    assert plan.fired("repl.append") > 0, "fault never fired; vacuous"
    assert np.array_equal(got, ref), "stream diverged under repl.append"
    counters = primary.metrics.report()["counters"]
    assert counters.get("repl_append_errors", 0) >= 1
    assert counters.get("repl_resyncs", 0) >= 1


def test_repl_promote_fault_aborts_then_retry_succeeds():
    """The first promotion attempt dies BEFORE any state flips: the
    failing-over client just retries, the second attempt promotes, and
    the stream is still exactly-once."""
    spec = plain_spec(world=1)
    ref = np.asarray(spec.rank_indices(0, 0))
    plan = F.FaultPlan([F.FaultRule(site="repl.promote", kind="error",
                                    nth=1, count=1)])
    with plan:
        primary, standby = _replicated_pair(spec)
        client = ServiceIndexClient(primary.address, rank=0, batch=37,
                                    backoff_base=0.01,
                                    reconnect_timeout=2.0)
        try:
            it = client.epoch_batches(0)
            got = [next(it)]
            _wait_synced(primary, standby)
            primary.kill()
            got.extend(it)
        finally:
            client.close()
            primary.kill()
            standby.stop()
    assert plan.fired("repl.promote") > 0, "fault never fired; vacuous"
    assert standby.role == "primary", "retry after the aborted promotion"
    assert np.array_equal(np.concatenate(got), ref)
    counters = client.metrics.report()["counters"]
    assert counters.get("degraded_mode", 0) == 0


def test_zombie_write_refusal_survives_injected_fault():
    """The fencing refusal is load-bearing: even with a fault injected
    at the refusal site, the zombie's write is still refused with the
    typed ``fenced`` error carrying the new term, and its state never
    mutates."""
    spec = plain_spec(world=1)
    plan = F.FaultPlan([F.FaultRule(site="server.zombie_write",
                                    kind="error", count=0)])
    with plan:
        primary, standby = _replicated_pair(spec, feed_timeout=60.0)
        try:
            _wait_synced(primary, standby)
            epoch_before = primary.epoch
            assert standby._try_promote(force=True)
            sock = socket.create_connection(primary.address, timeout=5.0)
            try:
                P.send_msg(sock, P.MSG_HELLO,
                           {"proto": P.PROTOCOL_VERSION, "rank": 0,
                            "batch": 32, "term": standby.term})
                msg, header, _ = P.recv_msg(sock)
            finally:
                sock.close()
            assert msg == P.MSG_ERROR
            assert header["code"] == "fenced"
            assert header["term"] >= standby.term
            assert header["serving"] is False
            assert primary.epoch == epoch_before
        finally:
            primary.stop()
            standby.stop()
    assert plan.fired("server.zombie_write") > 0, "fault never fired"


# --------------------------------------------------- durability WAL faults
def _wal_records(wal_dir):
    w = WriteAheadLog(wal_dir, fsync="off")
    try:
        return w.read_records()
    finally:
        w.close(sync=False)


def test_wal_torn_append_degrades_never_the_stream(tmp_path):
    """A torn frame mid-append leaves a REAL torn tail on disk and
    degrades the WAL — the client's stream stays bit-identical, and the
    next restart cuts the tear and serves again."""
    spec = plain_spec(world=1)
    ref = np.asarray(spec.rank_indices(0, 0))
    wal_dir = str(tmp_path / "wal")
    plan = F.FaultPlan([F.FaultRule(site="wal.append", kind="torn_frame",
                                    nth=3, count=1)])
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with plan:
            with IndexServer(spec, wal_dir=wal_dir) as srv:
                with ServiceIndexClient(srv.address, rank=0,
                                        batch=37) as client:
                    got = client.epoch_indices(0)
    assert plan.fired("wal.append") == 1, "fault never fired; vacuous"
    assert np.array_equal(got, ref), "stream diverged under wal.append"
    assert srv.metrics.report()["counters"].get("wal_append_errors", 0) >= 1
    assert any("torn frame" in str(w.message) for w in caught)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with IndexServer(plain_spec(world=1), wal_dir=wal_dir) as srv2:
            with ServiceIndexClient(srv2.address, rank=0,
                                    batch=37) as client:
                assert np.array_equal(client.epoch_indices(0), ref)
    assert srv2.metrics.report()["counters"].get("wal_torn_tails", 0) >= 1
    assert any("torn tail" in str(w.message) for w in caught)


def test_wal_fsync_fault_does_not_stop_serving(tmp_path):
    """Every fsync failing costs durability (counted), never a byte of
    the stream — and the records still reach the page cache, so a clean
    shutdown leaves a fully replayable log."""
    spec = plain_spec(world=1)
    ref = np.asarray(spec.rank_indices(0, 0))
    wal_dir = str(tmp_path / "wal")
    plan = F.FaultPlan([F.FaultRule(site="wal.fsync", kind="error",
                                    count=0)])
    with warnings.catch_warnings(), plan:
        warnings.simplefilter("ignore")
        with IndexServer(spec, wal_dir=wal_dir,
                         fsync="per_record") as srv:
            with ServiceIndexClient(srv.address, rank=0,
                                    batch=37) as client:
                got = client.epoch_indices(0)
    assert plan.fired("wal.fsync") >= 1, "fault never fired; vacuous"
    assert np.array_equal(got, ref), "stream diverged under wal.fsync"
    assert srv.metrics.report()["counters"].get("wal_fsync_errors", 0) >= 1
    recs = _wal_records(wal_dir)
    assert recs and [r["lsn"] for r in recs] == \
        list(range(1, len(recs) + 1))


def test_wal_rotate_disk_full_keeps_appending(tmp_path):
    """A failed segment rollover keeps appending to the full segment
    (bounded growth beats lost records); every record stays readable
    and later rollovers succeed."""
    from partiallyshuffledistributedsampler_tpu.service.metrics import (
        ServiceMetrics,
    )
    m = ServiceMetrics()
    w = WriteAheadLog(str(tmp_path / "wal"), fsync="off",
                      segment_bytes=200, metrics=m)
    plan = F.FaultPlan([F.FaultRule(site="wal.rotate", kind="disk_full",
                                    nth=1, count=1)])
    with plan:
        for i in range(1, 31):
            assert w.append({"lsn": i, "op": "epoch", "epoch": i})
    assert plan.fired("wal.rotate") == 1, "fault never fired; vacuous"
    assert [r["lsn"] for r in w.read_records()] == list(range(1, 31))
    counters = m.report()["counters"]
    assert counters.get("wal_rotate_errors", 0) == 1
    assert counters.get("wal_rotations", 0) >= 1, "later rollovers healed"
    w.close()


def test_wal_gc_abort_between_seal_and_truncate(tmp_path):
    """A crash between the checkpoint seal and the segment truncation
    (injected at the GC's wal.rotate site) only delays reclamation:
    every record is still readable, and the next seal truncates."""
    w = WriteAheadLog(str(tmp_path / "wal"), fsync="off",
                      segment_bytes=200)
    for i in range(1, 61):
        w.append({"lsn": i, "op": "epoch", "epoch": i})
    w.register_owner("front")
    w.checkpoint("front", 30)
    nseg = len(w.segment_paths())
    plan = F.FaultPlan([F.FaultRule(site="wal.rotate", kind="error",
                                    nth=1, count=1)])
    with plan:  # armed ONLY around the seal: rollovers must not consume it
        assert w.checkpoint("front", 50) == 0
    assert plan.fired("wal.rotate") == 1, "fault never fired; vacuous"
    assert len(w.segment_paths()) == nseg, "aborted GC must not truncate"
    assert [r["lsn"] for r in w.read_records()] == list(range(1, 61))
    assert w.checkpoint("front", 55) > 0, "the next seal retries the GC"
    assert [r["lsn"] for r in w.read_records(after_lsn=50)] == \
        list(range(51, 61))
    w.close()


def test_wal_append_disk_full_recovery_stays_dense(tmp_path):
    """Two dropped appends (injected ENOSPC) leave holes that the next
    successful append noop-fills: the stream is untouched, the on-disk
    sequence stays dense, and a restarted daemon recovers and serves
    bit-identically."""
    spec = plain_spec(world=1)
    ref = np.asarray(spec.rank_indices(0, 0))
    wal_dir = str(tmp_path / "wal")
    plan = F.FaultPlan([F.FaultRule(site="wal.append", kind="disk_full",
                                    nth=2, count=2)])
    with plan:
        srv = IndexServer(spec, wal_dir=wal_dir)
        srv.start()
        with ServiceIndexClient(srv.address, rank=0, batch=37) as client:
            got = client.epoch_indices(0)
        srv.kill()
    assert plan.fired("wal.append") == 2, "fault never fired; vacuous"
    assert np.array_equal(got, ref), "stream diverged under wal.append"
    assert srv.metrics.report()["counters"].get("wal_append_errors", 0) == 2
    recs = _wal_records(wal_dir)
    assert [r["lsn"] for r in recs] == list(range(1, len(recs) + 1))
    assert [r["op"] for r in recs].count("noop") == 2
    srv2 = IndexServer(plain_spec(world=1), wal_dir=wal_dir)
    srv2.start()
    try:
        with ServiceIndexClient(srv2.address, rank=0, batch=37) as client:
            assert np.array_equal(client.epoch_indices(0), ref)
    finally:
        srv2.stop()
    assert srv2.metrics.report()["counters"].get("wal_recoveries", 0) == 1


# -------------------------------------------------- sampling fault matrix
def _weighted_spec(weights):
    from partiallyshuffledistributedsampler_tpu.sampling import SamplingSpec
    return SamplingSpec.weighted((40, 30, 26), weights, epoch_samples=96,
                                 seed=7, window=8)


def test_sampling_alias_build_fault_serves_uniform_loudly():
    """An injected alias-table build failure degrades to the UNIFORM
    table — loudly (RuntimeWarning), deterministically (the served
    stream equals the uniform-weights stream bit-for-bit), and on every
    surface (the fallback is computed inside the spec, so served
    batches and local regen degrade identically)."""
    spec = _weighted_spec((3, 1, 2))
    ref = _weighted_spec((1, 1, 1)).rank_indices(1, 0)
    plan = F.FaultPlan([F.FaultRule(site="sampling.alias_build",
                                    kind="error", count=0)])
    # same-thread check first: the fallback warns where it degrades
    with plan:
        with pytest.warns(RuntimeWarning, match="UNIFORM"):
            direct = spec.rank_indices(1, 0)
    assert np.array_equal(direct, ref)
    # then the served path: the server-side fallback serves the same
    # degraded-but-deterministic stream
    plan2 = F.FaultPlan([F.FaultRule(site="sampling.alias_build",
                                     kind="error", count=0)])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with plan2:
            with IndexServer(_weighted_spec((3, 1, 2))) as srv:
                with ServiceIndexClient(srv.address, rank=0, batch=37,
                                        backoff_base=0.01,
                                        reconnect_timeout=10.0) as client:
                    got = client.epoch_indices(1)
    assert plan.fired("sampling.alias_build") > 0, "vacuous"
    assert plan2.fired("sampling.alias_build") > 0, "vacuous"
    assert np.array_equal(got, ref), "served fallback diverged from uniform"


def test_sampling_dedup_check_fault_never_double_serves():
    """An injected seen-set membership failure is fail-safe: the check
    reports 'seen', the draw probes on, and the cross-epoch no-repeat
    law survives — a dedup fault may skip candidates, never re-serve
    one."""
    from partiallyshuffledistributedsampler_tpu.sampling import SamplingSpec
    spec = SamplingSpec.deduped((40, 30, 26), epoch_samples=48, seed=7,
                                window=8)
    plan = F.FaultPlan([F.FaultRule(site="sampling.dedup_check",
                                    kind="error", nth=5, count=3)])
    with plan:
        with IndexServer(spec) as srv:
            with ServiceIndexClient(srv.address, rank=0, batch=16,
                                    backoff_base=0.01,
                                    reconnect_timeout=10.0) as client:
                e0 = client.epoch_indices(0)
                e1 = client.epoch_indices(1)
    assert plan.fired("sampling.dedup_check") >= 1, "vacuous"
    assert len(e0) == 48 and len(e1) == 48, "epoch length moved"
    union = np.concatenate([e0, e1]).tolist()
    assert len(set(union)) == len(union), "dedup fault double-served an id"


# ------------------------------------------------- federation fault matrix
def test_cell_ship_torn_mid_record_never_double_applies(tmp_path):
    """A cross-cell shipping frame torn mid-record (``cell.ship``)
    forces the shipper through its reconnect + re-SYNC path; the
    receiving cell's overlap check must make the replay idempotent —
    the remote standby's folded state equals the home primary's
    exactly, nothing applied twice."""
    from partiallyshuffledistributedsampler_tpu.federation import WalShipper

    spec = plain_spec(world=1)
    ref = np.asarray(spec.rank_indices(1, 0))
    primary = IndexServer(spec, wal_dir=str(tmp_path / "east"))
    remote = IndexServer(spec, role="standby", repl_feed_timeout=60.0,
                         wal_dir=str(tmp_path / "west"))
    plan = F.FaultPlan([F.FaultRule(site="cell.ship", kind="torn_frame",
                                    nth=2, count=1)])
    shipper = None
    try:
        remote.start()
        primary.start()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with plan:
                shipper = WalShipper(
                    primary._repl_log, remote.address,
                    cell_id="east", target_cell="west",
                    state_fn=primary._repl_sync_state,
                    term_fn=lambda: primary.term,
                    on_fenced=lambda term: None,
                    metrics=primary.metrics)
                shipper.start()
                assert shipper.synced.wait(10.0)
                with ServiceIndexClient(primary.address, rank=0, batch=37,
                                        backoff_base=0.01,
                                        reconnect_timeout=10.0) as client:
                    got = client.epoch_indices(1)
                deadline = time.monotonic() + 10.0
                while shipper.shipped_lsn < primary._repl_log.lsn:
                    assert time.monotonic() < deadline, (
                        "shipper never drained after the torn frame")
                    time.sleep(0.01)
    finally:
        if shipper is not None:
            shipper.stop()
        primary.stop()
        remote.stop()
    assert plan.fired("cell.ship") > 0, "fault never fired; vacuous"
    assert np.array_equal(got, ref)
    # never double-applied: the remote fold IS the primary's state
    assert remote._cursors == primary._cursors
    assert remote.epoch == primary.epoch
    resyncs = primary.metrics.report()["counters"].get(
        "cell_ship_resyncs", 0)
    assert resyncs >= 1, "the torn frame never forced a re-SYNC"


def test_cell_fence_fault_leaves_exactly_one_writable_cell(tmp_path):
    """An injected ``cell.fence`` fault skips one server during the
    whole-cell fence at promotion.  The skipped server must self-fence
    at its first newer-term request (``_term_refusal``), so the end
    state is reached either way: exactly one writable cell."""
    from partiallyshuffledistributedsampler_tpu.federation import Federation
    from partiallyshuffledistributedsampler_tpu.service import protocol as P

    spec = plain_spec(world=2)
    plan = F.FaultPlan([F.FaultRule(site="cell.fence", kind="error",
                                    nth=1, count=1)])
    with Federation(spec, root=str(tmp_path), n_shards=2) as fed:
        fed.wait_synced()
        assert fed.wait_shipped()
        with plan:
            fed.promote("west")  # east alive: the fence IS the guard
        assert plan.fired("cell.fence") == 1, "fault never fired; vacuous"
        m = fed.metrics.report()["counters"]
        assert m.get("cell_fence_faults", 0) == 1
        assert m.get("cell_fenced", 0) == len(fed.cells["east"].servers()) - 1
        term = max(s.term for s in fed.cells["west"].mirrors)
        fenced = []
        for srv in fed.cells["east"].servers():
            # a post-promotion client carries the new term; the skipped
            # zombie fences itself on the spot, the rest were fenced
            sock = socket.create_connection(srv.address, timeout=5.0)
            try:
                P.send_msg(sock, P.MSG_HELLO,
                           {"proto": P.PROTOCOL_VERSION, "rank": 0,
                            "batch": 8, "term": term})
                msg, hdr, _ = P.recv_msg(sock)
            finally:
                sock.close()
            fenced.append((msg, hdr.get("code")))
        assert all(m_ == P.MSG_ERROR and c == "fenced"
                   for m_, c in fenced), fenced
        # exactly one writable cell remains: west serves
        with ServiceIndexClient(fed.cells["west"].address, rank=0,
                                batch=37, backoff_base=0.01,
                                reconnect_timeout=10.0) as client:
            got = client.epoch_indices(0)
    assert np.array_equal(got, np.asarray(spec.rank_indices(0, 0)))


def test_cell_migrate_fault_aborts_cleanly_and_retry_succeeds(tmp_path):
    """An injected ``cell.migrate`` fault during the cutover prepare
    phase aborts CLEANLY: the home cell unfreezes, nothing flipped,
    nothing fenced — and the retried migration succeeds with the
    established client's stream staying exactly-once end to end."""
    from partiallyshuffledistributedsampler_tpu.federation import (
        Federation,
        MigrationAborted,
    )
    from partiallyshuffledistributedsampler_tpu.tenancy import tenant_id_for

    spec = plain_spec(world=1)
    tenant = tenant_id_for(spec.fingerprint(include_world=False))
    ref = np.asarray(spec.rank_indices(0, 0))
    plan = F.FaultPlan([F.FaultRule(site="cell.migrate", kind="error",
                                    count=1)])
    with Federation(spec, root=str(tmp_path)) as fed:
        fed.wait_synced()
        with ServiceIndexClient(fed.address, rank=0, batch=23,
                                backoff_base=0.01,
                                reconnect_timeout=5.0) as client:
            it = client.epoch_batches(0)
            got = [next(it)]
            with plan:
                with pytest.raises(MigrationAborted):
                    fed.migrate_tenant(tenant, "west")
            assert plan.fired("cell.migrate") == 1, "vacuous"
            d = fed.directory()
            assert d.home(tenant) == "east", "abort must not flip"
            assert d.version == 1, "abort must not bump the directory"
            m = fed.metrics.report()["counters"]
            assert m.get("federation_migrate_aborts", 0) == 1
            assert m.get("cell_fenced", 0) == 0, "abort must not fence"
            got.append(next(it))  # unfrozen: the home cell still serves
            nd = fed.migrate_tenant(tenant, "west")  # the retry succeeds
            assert nd.home(tenant) == "west"
            for arr in it:
                got.append(arr)
    stream = np.concatenate(got)
    assert np.array_equal(stream, ref), (
        "abort + retry duplicated or skipped indices")
