"""End-to-end torch-shim gate on the REAL device — VERDICT round 2, weak #5.

The pytest process is pinned to the CPU platform (conftest.py), so the
shim's xla backend pipeline — `set_epoch` async dispatch, the
`copy_to_host_async` staging, the pending-buffer handoff and chunked
streaming in `__iter__` (torch_shim.py) — normally never touches the
machine's actual device in the suite; a device-specific transfer bug would
ship green.  Same subprocess pattern as test_pallas_compiled.py: drop the
platform override, construct the sampler with ``backend='xla'`` on the real
TPU, and drive the full user flow (set_epoch -> iterate -> DataLoader ->
checkpoint -> resume) against the cpu backend's answers.
"""

import os
import subprocess
import sys

import pytest

_SCRIPT = r"""
import sys
import numpy as np
import jax

if jax.default_backend() != "tpu":
    print("NO_TPU", jax.default_backend())
    sys.exit(0)

import torch
from torch.utils.data import DataLoader, TensorDataset

from partiallyshuffledistributedsampler_tpu import (
    PartiallyShuffleDistributedSampler,
)

N, WINDOW, WORLD, RANK, SEED = 200_003, 512, 2, 1, 5
ds = TensorDataset(torch.arange(N))


def make(backend, seed=SEED):
    return PartiallyShuffleDistributedSampler(
        ds, num_replicas=WORLD, rank=RANK, window=WINDOW, seed=seed,
        backend=backend,
    )


ref = make("cpu")
dev = make("xla")

# 1. plain iteration parity across epochs (exercises the async dispatch +
#    chunked device->host streaming path end to end)
for epoch in (0, 3):
    ref.set_epoch(epoch)
    dev.set_epoch(epoch)
    if list(dev) != list(ref):
        print("MISMATCH iterate epoch", epoch)
        sys.exit(1)

# 2. through a real DataLoader
ref.set_epoch(1)
dev.set_epoch(1)
got = torch.cat([b[0] for b in DataLoader(ds, batch_size=1024, sampler=dev)])
exp = torch.as_tensor(list(ref), dtype=got.dtype)
if not torch.equal(got, exp):
    print("MISMATCH dataloader")
    sys.exit(1)

# 3. checkpoint mid-epoch on the device backend, resume into a FRESH
#    sampler (different constructor seed — state must fully override it)
dev.set_epoch(2)
it = iter(dev)
head = [next(it) for _ in range(1234)]
sd = dev.state_dict()
res = make("xla", seed=0)
res.load_state_dict(sd)
tail = list(res)
ref.set_epoch(2)
if head + tail != list(ref):
    print("MISMATCH resume: head", len(head), "tail", len(tail))
    sys.exit(1)

# 4. elastic reshard on the device backend: the jitted remainder-epoch
#    executable (elastic_indices_jax) runs on the actual device here, and
#    must match the cpu backend's remainder bit-for-bit for every new
#    rank (the exactly-once LAWS are pinned by the CPU suite; this gates
#    the device executable against that reference).
dev2 = make("xla")
dev2.set_epoch(4)
it2 = iter(dev2)
for _ in range(777):
    next(it2)
sd2 = dev2.state_dict()
for r in range(3):
    es_dev = PartiallyShuffleDistributedSampler.reshard_from_state_dict(
        sd2, 3, r, dataset=ds, backend="xla"
    )
    es_cpu = PartiallyShuffleDistributedSampler.reshard_from_state_dict(
        sd2, 3, r, dataset=ds, backend="cpu"
    )
    if list(es_dev) != list(es_cpu):
        print("MISMATCH elastic device-vs-cpu, new rank", r)
        sys.exit(1)

print("OK")
"""


def test_shim_xla_backend_end_to_end_on_real_device():
    env = os.environ.copy()
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Bounded backend-discovery probe first: a chipless libtpu install hangs
    # for minutes retrying metadata fetches during jax init, which would eat
    # most of the 600 s gate budget before NO_TPU could ever print.
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, cwd=repo, capture_output=True, text=True, timeout=30,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("jax backend discovery hung (>30s) without the CPU pin "
                    "(chipless libtpu?); shim e2e gate needs a real TPU")
    res = subprocess.run(
        [sys.executable, "-c", _SCRIPT], env=env, cwd=repo,
        capture_output=True, text=True, timeout=600,
    )
    out = res.stdout.strip().splitlines()
    last = out[-1] if out else ""
    if last.startswith("NO_TPU"):
        pytest.skip(f"no TPU on this machine ({last}); shim e2e covered "
                    "CPU-platform-only elsewhere")
    assert res.returncode == 0 and last == "OK", (
        f"device shim e2e failed:\nstdout: {res.stdout[-2000:]}\n"
        f"stderr: {res.stderr[-2000:]}"
    )
