"""Pallas kernel parity vs the CPU reference (interpret mode on the CPU test
platform; the compiled path is exercised on the real device by bench.py)."""

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops import cpu
from partiallyshuffledistributedsampler_tpu.ops.pallas_kernel import (
    epoch_indices_pallas,
)

CONFIGS = [
    dict(n=5000, window=512, world=2),
    dict(n=1024, window=64, world=8),            # exact tile multiple
    dict(n=12_345, window=512, world=8),         # remainders + padding lanes
    dict(n=100, window=7, world=3),              # tiny: single padded tile
    dict(n=4096, window=4096, world=4),          # W == n full-shuffle window
    dict(n=2000, window=128, world=4, partition="blocked"),
    dict(n=2000, window=128, world=4, order_windows=False),
    dict(n=999, window=50, world=2, shuffle=False),
    dict(n=640, window=64, world=8, drop_last=True),
]


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: f"n{c['n']}w{c['window']}x{c['world']}")
def test_pallas_bit_identical(cfg):
    cfg = dict(cfg)
    n, w, world = cfg.pop("n"), cfg.pop("window"), cfg.pop("world")
    for rank in (0, world - 1):
        ref = cpu.epoch_indices_np(n, w, 42, 3, rank, world, **cfg)
        got = np.asarray(
            epoch_indices_pallas(n, w, 42, 3, rank, world, interpret=True, **cfg)
        )
        assert got.shape == ref.shape and got.dtype == ref.dtype
        np.testing.assert_array_equal(got, ref)


def test_pallas_big_seed_and_epoch():
    ref = cpu.epoch_indices_np(3000, 100, (1 << 40) + 9, 77, 1, 2)
    got = np.asarray(
        epoch_indices_pallas(3000, 100, (1 << 40) + 9, 77, 1, 2, interpret=True)
    )
    np.testing.assert_array_equal(got, ref)


def test_pallas_rejects_big_n():
    with pytest.raises(ValueError, match="int32"):
        epoch_indices_pallas(2**31, 8192, 0, 0, 0, 256, interpret=True)


def test_xla_entrypoint_dispatches_pallas():
    # use_pallas=True on the public entrypoint must agree with the reference
    # (compiled Mosaic on TPU, interpreter elsewhere is not automatic — this
    # exercises the wiring, on CPU via interpret fallback in the kernel).
    from partiallyshuffledistributedsampler_tpu.ops.xla import epoch_indices_jax

    ref = cpu.epoch_indices_np(2048, 256, 1, 2, 0, 4)
    got = np.asarray(epoch_indices_jax(2048, 256, 1, 2, 0, 4, use_pallas=True))
    np.testing.assert_array_equal(got, ref)
