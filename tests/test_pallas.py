"""Pallas kernel parity vs the CPU reference (interpret mode on the CPU test
platform; the compiled path is exercised on the real device by bench.py)."""

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops import cpu
from partiallyshuffledistributedsampler_tpu.ops.pallas_kernel import (
    compact_kex_applicable,
    epoch_indices_pallas,
)

CONFIGS = [
    dict(n=5000, window=512, world=2),
    dict(n=1024, window=64, world=8),            # exact tile multiple
    dict(n=12_345, window=512, world=8),         # remainders + padding lanes
    dict(n=100, window=7, world=3),              # tiny: single padded tile
    dict(n=4096, window=4096, world=4),          # W == n full-shuffle window
    dict(n=2000, window=128, world=4, partition="blocked"),
    dict(n=2000, window=128, world=4, order_windows=False),
    dict(n=999, window=50, world=2, shuffle=False),
    dict(n=640, window=64, world=8, drop_last=True),
]


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: f"n{c['n']}w{c['window']}x{c['world']}")
def test_pallas_bit_identical(cfg):
    cfg = dict(cfg)
    n, w, world = cfg.pop("n"), cfg.pop("window"), cfg.pop("world")
    for rank in (0, world - 1):
        ref = cpu.epoch_indices_np(n, w, 42, 3, rank, world, **cfg)
        got = np.asarray(
            epoch_indices_pallas(n, w, 42, 3, rank, world, interpret=True, **cfg)
        )
        assert got.shape == ref.shape and got.dtype == ref.dtype
        np.testing.assert_array_equal(got, ref)


def test_pallas_big_seed_and_epoch():
    ref = cpu.epoch_indices_np(3000, 100, (1 << 40) + 9, 77, 1, 2)
    got = np.asarray(
        epoch_indices_pallas(3000, 100, (1 << 40) + 9, 77, 1, 2, interpret=True)
    )
    np.testing.assert_array_equal(got, ref)


def test_pallas_rejects_big_n():
    with pytest.raises(ValueError, match="int32"):
        epoch_indices_pallas(2**31, 8192, 0, 0, 0, 256, interpret=True)


def test_xla_entrypoint_dispatches_pallas():
    # use_pallas=True on the public entrypoint must agree with the reference
    # (compiled Mosaic on TPU, interpreter elsewhere is not automatic — this
    # exercises the wiring, on CPU via interpret fallback in the kernel).
    from partiallyshuffledistributedsampler_tpu.ops.xla import epoch_indices_jax

    ref = cpu.epoch_indices_np(2048, 256, 1, 2, 0, 4)
    got = np.asarray(epoch_indices_jax(2048, 256, 1, 2, 0, 4, use_pallas=True))
    np.testing.assert_array_equal(got, ref)


# ------------------------------------------- amortized compact-kex kernel
def test_compact_kex_applicability_gate():
    from partiallyshuffledistributedsampler_tpu.ops.pallas_kernel import (
        build_amortized_call,
        compact_kex_applicable,
    )

    assert compact_kex_applicable(8192, 256)   # m=32  (select path)
    assert compact_kex_applicable(8192, 64)    # m=128 (broadcast)
    assert compact_kex_applicable(8192, 8)     # m=1024 (broadcast)
    assert not compact_kex_applicable(512, 256)   # m=2: g too long
    assert not compact_kex_applicable(768, 4)     # m=192: 128 ∤ m
    assert not compact_kex_applicable(64, 128)    # world > window: m=0
    with pytest.raises(ValueError, match="expandable"):
        build_amortized_call(10**9, 512, 256, 10**9 // 256, interpret=True)


def test_amortized_call_asserts_num_samples_contract():
    from partiallyshuffledistributedsampler_tpu.ops.pallas_kernel import (
        build_amortized_call,
    )

    with pytest.raises(ValueError, match="body lanes"):
        build_amortized_call(4096, 256, 8, 10, interpret=True)


def test_explicit_pallas_pin_honored_when_compact_inapplicable():
    # m=2 can't be expanded in-kernel; an explicit use_pallas=True must
    # still run a Pallas kernel (the general one), bit-identically — never
    # a silent demotion to the XLA evaluator — and must WARN that it got
    # the ~5x general kernel (round-3 verdict: the downgrade was silent)
    from partiallyshuffledistributedsampler_tpu.ops.xla import (
        epoch_indices_jax,
    )

    ref = cpu.epoch_indices_np(2048, 512, 3, 1, 7, 256)
    with pytest.warns(RuntimeWarning, match="GENERAL fused kernel"):
        got = np.asarray(epoch_indices_jax(2048, 512, 3, 1, 7, 256,
                                           use_pallas=True))
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize(
    "n,window,world",
    [
        (4096, 16, 4),    # m=4 < 8: select-chain expansion too costly
        (4096, 192, 8),   # m=24: neither 128 | m nor m | 128
    ],
)
def test_coverage_hole_shape_classes(n, window, world, monkeypatch):
    """Per-shape-class contract for the amortized kernel's coverage holes:
    explicit pin -> general kernel + RuntimeWarning; 'auto' on a TPU
    backend -> the XLA amortized evaluator, silently (it is the measured
    next-best there).  Values bit-identical in every case."""
    import warnings

    import jax

    from partiallyshuffledistributedsampler_tpu.ops import xla as x

    assert not compact_kex_applicable(window, world)
    ref = cpu.epoch_indices_np(n, window, 5, 2, 1, world)

    with pytest.warns(RuntimeWarning, match="GENERAL fused kernel"):
        got_pin = np.asarray(
            x.epoch_indices_jax(n, window, 5, 2, 1, world, use_pallas=True)
        )
    np.testing.assert_array_equal(got_pin, ref)

    # force the 'auto' TPU-backend branch without a TPU: the hole routes
    # to use_pallas=False before any kernel build, so no Mosaic compile
    monkeypatch.setattr(x.jax, "default_backend", lambda: "tpu")
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning would fail the test
        got_auto = np.asarray(
            x.epoch_indices_jax(n, window, 5, 2, 1, world, use_pallas="auto")
        )
    np.testing.assert_array_equal(got_auto, ref)


@pytest.mark.parametrize(
    "n,window,world",
    [
        (4096, 256, 8),      # m=32: in-row select expansion
        (8200, 128, 8),      # m=16: select expansion + tail lanes
        (4096, 256, 2),      # m=128: row-broadcast expansion, q=1
        (4100, 512, 2),      # m=256: row-broadcast, q=2, with tail
        (70_000, 32768, 2),  # m=16384: tail starts exactly on a tile edge
        (50_000, 16384, 2),  # m=8192: body=1.5 tiles — a tile mixes body
                             #   and tail lanes (mid-tile straddle)
        (900, 1024, 2),      # window > n: amortization is inapplicable
                             #   (nw=0) so this pins the general-kernel
                             #   routing for the degenerate config
    ],
)
def test_amortized_compact_expansion_bit_identical(n, window, world):
    # the amortized kernel with IN-KERNEL window-id expansion (round 3's
    # compact-kex design) against the numpy reference, both ranks' ends
    from partiallyshuffledistributedsampler_tpu.ops.xla import (
        epoch_indices_jax,
    )

    for rank in (0, world - 1):
        for epoch in (0, 9):
            ref = cpu.epoch_indices_np(n, window, 5, epoch, rank, world)
            got = np.asarray(
                epoch_indices_jax(n, window, 5, epoch, rank, world,
                                  use_pallas=True)
            )
            np.testing.assert_array_equal(got, ref)
