"""StatefulDataLoader: exact mid-epoch checkpoint with num_workers>0.

Closes the torch_shim ``.. warning::`` gap: a multi-worker DataLoader
prefetches indices ahead of delivered batches, so a bare sampler
``state_dict()`` over-counts.  The wrapper counts delivered batches in the
main process; these tests assert the resulting exactness law — resuming from
a checkpoint taken after batch k yields exactly the batches k+1.. that the
uninterrupted run yields — across worker counts, drop_last, tail shapes,
batch-sampler construction, sample mode, and set_epoch boundaries.
"""

import numpy as np
import pytest
import torch
from torch.utils.data import BatchSampler, TensorDataset

from partiallyshuffledistributedsampler_tpu import (
    PartiallyShuffleDistributedSampler,
    StatefulDataLoader,
)

N = 333  # not divisible by batch or world: exercises pad + tail batches


def make_sampler(**kw):
    kw.setdefault("window", 32)
    kw.setdefault("backend", "cpu")
    return PartiallyShuffleDistributedSampler(
        N, num_replicas=2, rank=0, **kw
    )


def make_loader(sampler, **kw):
    ds = TensorDataset(torch.arange(N))
    kw.setdefault("batch_size", 16)
    return StatefulDataLoader(ds, sampler=sampler, **kw)


def batches_as_lists(loader):
    return [b[0].tolist() for b in loader]


def full_epoch(epoch, **loader_kw):
    s = make_sampler()
    s.set_epoch(epoch)
    return batches_as_lists(make_loader(s, **loader_kw))


@pytest.mark.parametrize("num_workers", [0, 2])
@pytest.mark.parametrize("stop_after", [0, 1, 3, 7])
def test_resume_matches_uninterrupted(num_workers, stop_after):
    ref = full_epoch(4, num_workers=num_workers)
    # interrupted run: checkpoint inside the loop body after `stop_after`
    # batches, while workers have prefetched well past that point
    s = make_sampler()
    s.set_epoch(4)
    loader = make_loader(s, num_workers=num_workers)
    state = loader.state_dict()  # pre-iteration checkpoint must also work
    seen = []
    if stop_after:
        for i, b in enumerate(loader):
            seen.append(b[0].tolist())
            state = loader.state_dict()
            if i + 1 == stop_after:
                break
    assert seen == ref[:stop_after]
    # fresh process stand-in: brand-new sampler and loader
    s2 = make_sampler()
    loader2 = make_loader(s2, num_workers=num_workers)
    loader2.load_state_dict(state)
    rest = batches_as_lists(loader2)
    assert seen + rest == ref, (
        f"resume after batch {stop_after} with num_workers={num_workers} "
        "diverged from the uninterrupted epoch"
    )


def test_exact_offset_despite_prefetch():
    """The recorded offset is delivered*batch, NOT inflated by the worker
    prefetch depth — the precise failure mode of a bare sampler state_dict."""
    s = make_sampler()
    s.set_epoch(1)
    loader = make_loader(s, num_workers=2, prefetch_factor=4)
    it = iter(loader)
    for _ in range(3):
        next(it)
    state = loader.state_dict()
    assert state["batches_delivered"] == 3
    assert state["sampler"]["offset"] == 3 * 16
    # the sampler's own auto-count HAS raced ahead (that's the bug the
    # wrapper fixes) — with 2 workers x prefetch 4 the whole 167-sample
    # shard is typically already yielded
    assert s.state_dict()["offset"] >= 3 * 16
    del it


def test_drop_last_tail_and_final_batch():
    ref = full_epoch(2, drop_last=True)
    assert all(len(b) == 16 for b in ref)
    s = make_sampler()
    s.set_epoch(2)
    loader = make_loader(s, drop_last=True, num_workers=2)
    state = None
    for i, b in enumerate(loader):
        if i + 1 == len(ref):  # checkpoint after the FINAL delivered batch
            state = loader.state_dict()
    s2 = make_sampler()
    loader2 = make_loader(s2, drop_last=True, num_workers=2)
    loader2.load_state_dict(state)
    assert batches_as_lists(loader2) == []  # nothing left to serve


def test_end_of_epoch_then_next_epoch():
    s = make_sampler()
    s.set_epoch(0)
    loader = make_loader(s)
    _ = batches_as_lists(loader)
    state = loader.state_dict()
    # resume at end-of-epoch: empty remainder, then set_epoch proceeds
    s2 = make_sampler()
    loader2 = make_loader(s2)
    loader2.load_state_dict(state)
    assert batches_as_lists(loader2) == []
    s2.set_epoch(1)
    assert batches_as_lists(loader2) == full_epoch(1)


def test_batch_sampler_construction():
    s = make_sampler()
    s.set_epoch(3)
    ds = TensorDataset(torch.arange(N))
    loader = StatefulDataLoader(
        ds, batch_sampler=BatchSampler(s, batch_size=16, drop_last=False),
        num_workers=2,
    )
    ref = full_epoch(3, num_workers=0)
    seen = []
    state = None
    for i, b in enumerate(loader):
        seen.append(b[0].tolist())
        if i + 1 == 5:
            state = loader.state_dict()
            break
    s2 = make_sampler()
    loader2 = StatefulDataLoader(
        TensorDataset(torch.arange(N)),
        batch_sampler=BatchSampler(s2, batch_size=16, drop_last=False),
    )
    loader2.load_state_dict(state)
    assert seen + batches_as_lists(loader2) == ref


def test_sample_mode_batch_size_none():
    s = make_sampler()
    s.set_epoch(5)
    ds = TensorDataset(torch.arange(N))
    loader = StatefulDataLoader(ds, batch_size=None, sampler=s)
    ref = [int(x[0]) for x in loader]
    s.set_epoch(5)  # reset for the interrupted pass (same sampler object)
    state = None
    seen = []
    for i, x in enumerate(loader):
        seen.append(int(x[0]))
        if i + 1 == 40:
            state = loader.state_dict()
            break
    assert state["sampler"]["offset"] == 40
    s2 = make_sampler()
    loader2 = StatefulDataLoader(TensorDataset(torch.arange(N)),
                                 batch_size=None, sampler=s2)
    loader2.load_state_dict(state)
    assert seen + [int(x[0]) for x in loader2] == ref


def test_cross_rank_partition_still_holds_through_loader():
    """The wrapper is pure plumbing: the two ranks' delivered batches still
    tile the padded index space exactly (SURVEY §4 invariant 1)."""
    ds = TensorDataset(torch.arange(N))
    got = []
    for r in range(2):
        s = PartiallyShuffleDistributedSampler(
            N, num_replicas=2, rank=r, window=32, backend="cpu")
        s.set_epoch(1)
        for b in StatefulDataLoader(ds, batch_size=16, sampler=s):
            got.extend(b[0].tolist())
    assert sorted(set(got)) == list(range(N))
    assert len(got) == 2 * -(-N // 2)


def test_rejects_plain_sampler():
    ds = TensorDataset(torch.arange(N))
    with pytest.raises(TypeError, match="checkpointable"):
        StatefulDataLoader(ds, batch_size=4)  # default RandomSampler


def test_custom_batch_sampler_without_batch_size_needs_override():
    class Weird:
        def __init__(self, sampler):
            self.sampler = sampler

        def __iter__(self):
            it = iter(self.sampler)
            while True:
                out = []
                try:
                    for _ in range(8):
                        out.append(next(it))
                except StopIteration:
                    if out:
                        yield out
                    return
                yield out

        def __len__(self):
            return -(-len(self.sampler) // 8)

    s = make_sampler()
    ds = TensorDataset(torch.arange(N))
    # rejected at CONSTRUCTION, not hours later at the first checkpoint
    with pytest.raises(TypeError, match="samples_per_batch"):
        StatefulDataLoader(ds, batch_sampler=Weird(s))
    loader2 = StatefulDataLoader(ds, batch_sampler=Weird(make_sampler()),
                                 samples_per_batch=8)
    it = iter(loader2)
    next(it), next(it)
    assert loader2.state_dict()["sampler"]["offset"] == 16


def test_works_over_mixture_sampler():
    """The mixture sampler exposes the same checkpoint surface, so the
    exact-resume law must hold through StatefulDataLoader for it too."""
    from partiallyshuffledistributedsampler_tpu.sampler import (
        PartialShuffleMixtureSampler,
    )

    sizes, weights = [200, 80, 53], [3, 2, 1]
    total = sum(sizes)
    ds = TensorDataset(torch.arange(total))

    def make_mix():
        s = PartialShuffleMixtureSampler(
            sizes, weights, num_replicas=2, rank=0, windows=16, block=12)
        s.set_epoch(1)
        return s

    ref = [b[0].tolist() for b in
           StatefulDataLoader(ds, batch_size=16, sampler=make_mix(),
                              num_workers=2)]
    loader = StatefulDataLoader(ds, batch_size=16, sampler=make_mix(),
                                num_workers=2)
    seen, state = [], None
    for i, b in enumerate(loader):
        seen.append(b[0].tolist())
        state = loader.state_dict()
        if i == 2:
            break
    loader2 = StatefulDataLoader(ds, batch_size=16, sampler=make_mix(),
                                 num_workers=2)
    loader2.load_state_dict(state)
    assert seen + [b[0].tolist() for b in loader2] == ref


def test_works_over_shard_sampler():
    from partiallyshuffledistributedsampler_tpu.sampler import (
        PartialShuffleShardSampler,
    )

    num_shards = 96
    ds = TensorDataset(torch.arange(num_shards))

    def make_shard():
        s = PartialShuffleShardSampler(
            num_shards, num_replicas=2, rank=0, window=8, backend="cpu")
        s.set_epoch(2)
        return s

    ref = [b[0].tolist() for b in
           StatefulDataLoader(ds, batch_size=8, sampler=make_shard())]
    loader = StatefulDataLoader(ds, batch_size=8, sampler=make_shard())
    seen, state = [], None
    for i, b in enumerate(loader):
        seen.append(b[0].tolist())
        state = loader.state_dict()
        if i == 1:
            break
    loader2 = StatefulDataLoader(ds, batch_size=8, sampler=make_shard())
    loader2.load_state_dict(state)
    assert seen + [b[0].tolist() for b in loader2] == ref


def test_load_accepts_bare_sampler_state():
    s = make_sampler()
    s.set_epoch(7)
    bare = s.state_dict(consumed=32)
    s2 = make_sampler()
    loader = make_loader(s2)
    loader.load_state_dict(bare)
    got = [i for b in batches_as_lists(loader) for i in b]
    s3 = make_sampler()
    s3.set_epoch(7)
    assert got == list(s3)[32:]


def test_set_epoch_after_abandoned_iter_resets_state():
    """Checkpoint between set_epoch(new) and the next iteration must record
    offset 0 for the new epoch — not the abandoned iterator's stale batch
    count converted into the new epoch's stream (silent sample skip)."""
    s = make_sampler()
    s.set_epoch(0)
    loader = make_loader(s)
    it = iter(loader)
    for _ in range(3):
        next(it)
    s.set_epoch(1)
    state = loader.state_dict()
    assert state["sampler"]["epoch"] == 1
    assert state["sampler"]["offset"] == 0
    s2 = make_sampler()
    loader2 = make_loader(s2)
    loader2.load_state_dict(state)
    assert batches_as_lists(loader2) == full_epoch(1)
    # worse variant: a fully exhausted epoch then set_epoch — offset must
    # not carry the full shard length into the new epoch
    s3 = make_sampler()
    s3.set_epoch(0)
    loader3 = make_loader(s3)
    _ = batches_as_lists(loader3)
    s3.set_epoch(1)
    assert loader3.state_dict()["sampler"]["offset"] == 0


def test_stale_iterator_cannot_count_or_crash():
    """A drained pre-existing iterator after a newer __iter__ must not
    inflate the count; after load_state_dict it must not crash on the
    cleared counter."""
    s = make_sampler()
    s.set_epoch(0)
    loader = make_loader(s)
    old = iter(loader)
    next(old), next(old)
    new = iter(loader)
    next(new)
    next(old)  # stale delivery: must not count toward the live iterator
    assert loader.state_dict()["batches_delivered"] == 1
    assert loader.state_dict()["sampler"]["offset"] == 16
    # load_state_dict clears the counter; a further stale next() must not
    # raise TypeError(None += 1)
    loader.load_state_dict(loader.state_dict())
    next(old)
    assert loader.state_dict()["batches_delivered"] == 0


def test_direct_sampler_load_detected_same_epoch():
    """A same-epoch sampler.load_state_dict under a live count advances the
    sampler's generation; the loader must fall back to the sampler's own
    (exact) state instead of converting its now-stale batch count."""
    s = make_sampler()
    s.set_epoch(0)
    ckpt_at_32 = s.state_dict(consumed=32)
    loader = make_loader(s)
    it = iter(loader)
    next(it), next(it)
    s.load_state_dict(ckpt_at_32)  # bypasses the loader deliberately
    assert loader.state_dict()["sampler"]["offset"] == 32


def test_rejects_sampler_without_offset_attr():
    class NoOffset:
        def __init__(self, n):
            self.n = n

        def __iter__(self):
            return iter(range(self.n))

        def __len__(self):
            return self.n

        def state_dict(self, consumed=None):
            return {}

        def load_state_dict(self, state):
            pass

    ds = TensorDataset(torch.arange(N))
    with pytest.raises(TypeError, match="_offset"):
        StatefulDataLoader(ds, batch_size=4, sampler=NoOffset(N))


def test_config_mismatch_still_raises_through_loader():
    s = make_sampler()
    state = make_loader(s).state_dict()
    s2 = PartiallyShuffleDistributedSampler(
        N, num_replicas=2, rank=0, window=64, backend="cpu")
    loader2 = make_loader(s2)
    with pytest.raises(ValueError, match="window"):
        loader2.load_state_dict(state)
