"""Telemetry: span tracer, trace-ID propagation, flight recorder, exporters.

Laws under test (docs/OBSERVABILITY.md):

* zero-cost-when-off — a disabled tracer hands out one shared no-op
  span, records nothing, and adds NO bytes to the protocol (no ``trace``
  header field);
* one trace ID per logical request — the ``client.rpc`` span covers
  every retry of one operation, so a GET_BATCH refused with ``reshard``
  and retried produces two server dispatch spans under ONE trace;
* failure timelines — a fault injected inside server dispatch dumps the
  flight ring with the faulted (still-open) span in it; a degraded
  fallback's regen span links to the exact RPC span that failed;
* bounded state — ``RegenTimer.samples_ms`` caps at its ring size with
  exact running totals, and ``ServiceMetrics`` prunes per-client entries
  at eviction and reshard commit.
"""

import glob
import json
import os
import socket
import threading
import time
import warnings

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu import faults as F
from partiallyshuffledistributedsampler_tpu import telemetry as T
from partiallyshuffledistributedsampler_tpu.sampler.host_loader import (
    HostDataLoader,
)
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    PartialShuffleSpec,
    ServiceIndexClient,
    ServiceMetrics,
)
from partiallyshuffledistributedsampler_tpu.service import protocol as P
from partiallyshuffledistributedsampler_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    RegenTimer,
)
from partiallyshuffledistributedsampler_tpu.utils.watchdog import StallError

pytestmark = pytest.mark.telemetry


def plain_spec(world=1, n=512, window=64):
    return PartialShuffleSpec.plain(n, window=window, world=world, seed=7)


@pytest.fixture
def traced(tmp_path):
    """Global tracer ON with a flight dir; reset to off-by-default after."""
    T.reset()
    T.configure(enabled=True, dump_dir=str(tmp_path))
    yield tmp_path
    T.reset()


def spans(name=None):
    out = [e for e in T.snapshot() if e.get("kind") == "span"]
    return out if name is None else [e for e in out if e["name"] == name]


# ------------------------------------------------------------------ tracer
def test_span_nesting_attrs_events(traced):
    with T.span("outer", a=1) as so:
        so.set("b", "two")
        with T.span("inner") as si:
            assert si.trace_id == so.trace_id
            assert si.parent_id == so.span_id
            si.event("tick", x=3)
        # remote context parents the same way a frame header does
        with T.span("remote_child", trace=so.ids) as sr:
            assert sr.trace_id == so.trace_id
            assert sr.parent_id == so.span_id
    inner, outer = spans("inner")[0], spans("outer")[0]
    assert outer["attrs"] == {"a": 1, "b": "two"}
    assert outer["status"] == "ok" and outer["ms"] >= 0
    assert inner["events"][0]["name"] == "tick"
    assert inner["events"][0]["attrs"] == {"x": 3}


def test_exception_marks_span_and_tags_innermost(traced):
    with pytest.raises(ValueError):
        with T.span("outer"):
            with T.span("inner"):
                raise ValueError("boom")
    try:
        with T.span("a") as sa:
            raise ValueError("tagged")
    except ValueError as exc:
        assert exc._psds_span == sa.ids
    inner = spans("inner")[0]
    assert inner["status"] == "error" and "boom" in inner["error"]


def test_disabled_tracer_is_shared_noop():
    T.reset()
    assert not T.enabled()
    s1, s2 = T.span("x", a=1), T.span("y")
    assert s1 is s2  # the one shared null span: no allocation when off
    assert s1.ids is None
    with s1 as s:
        assert s.set("k", "v") is s
        assert T.current() is None
    assert T.snapshot() == []
    assert T.dump() is None  # no destination, no tracing: nothing written


# ------------------------------------------------- protocol: trace on wire
def test_disabled_tracer_adds_no_protocol_field():
    """Off by default ⇒ request headers carry no ``trace`` key (zero
    extra wire bytes); enabled ⇒ the key appears.  Old servers ignore
    unknown header fields, so this is the whole interop surface."""
    T.reset()
    with IndexServer(plain_spec()) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=64)
        try:
            hdr = {}
            c._rpc(P.MSG_METRICS, hdr)
            assert "trace" not in hdr
            T.configure(enabled=True)
            hdr = {}
            c._rpc(P.MSG_METRICS, hdr)
            assert isinstance(hdr.get("trace"), list) and len(hdr["trace"]) == 2
        finally:
            c.close()
            T.reset()


def test_old_client_without_trace_field_still_served():
    """A pre-telemetry peer (never sends ``trace``) interoperates with a
    tracing-enabled server — raw-socket HELLO + GET_BATCH."""
    spec = plain_spec()
    T.reset()
    T.configure(enabled=True)
    try:
        with IndexServer(spec) as srv:
            s = socket.create_connection(srv.address, timeout=5.0)
            try:
                P.send_msg(s, P.MSG_HELLO,
                           {"rank": 0, "batch": 64,
                            "proto": P.PROTOCOL_VERSION})
                msg, h, _ = P.recv_msg(s)
                assert msg == P.MSG_WELCOME
                P.send_msg(s, P.MSG_GET_BATCH,
                           {"rank": 0, "epoch": 0, "seq": 0, "gen": 0})
                msg, h, payload = P.recv_msg(s)
                assert msg == P.MSG_BATCH
                got = P.decode_indices(h, payload)
                ref = np.asarray(spec.rank_indices(0, 0))[:64]
                assert np.array_equal(got, ref)
            finally:
                s.close()
        # the server still traced the untraced peer's dispatch (new root)
        assert spans("server.GET_BATCH")
    finally:
        T.reset()


def test_trace_id_threads_client_to_server(traced):
    spec = plain_spec()
    with IndexServer(spec) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=64)
        try:
            got = np.concatenate(list(c.epoch_batches(0)))
        finally:
            c.close()
    assert np.array_equal(got, np.asarray(spec.rank_indices(0, 0)))
    rpc, srv_spans = spans("client.rpc"), spans("server.GET_BATCH")
    assert rpc and srv_spans
    by_span = {e["span"]: e for e in rpc}
    for s in srv_spans:
        parent = by_span.get(s["parent"])
        assert parent is not None, "server span not parented under an rpc"
        assert parent["trace"] == s["trace"]


def test_reshard_refusal_then_retry_keeps_one_trace(traced):
    """A GET_BATCH refused with ``reshard`` and retried is ONE logical
    request: both server dispatch spans carry the same trace id, and the
    refused one is annotated with the error code."""
    spec = plain_spec(n=512, window=64)
    with IndexServer(spec) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=64,
                               backoff_base=0.01, reconnect_timeout=5.0)
        try:
            it = c.epoch_batches(0)
            first = next(it)  # connected and streaming before the stub
            with srv._lock:
                srv._reshard = {"phase": "freeze"}

            def release():
                # wait until the freeze refused at least one request
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if c.metrics.registry.get("reshard_waits") >= 1:
                        break
                    time.sleep(0.005)
                with srv._lock:
                    srv._reshard = None

            rel = threading.Thread(target=release)
            rel.start()
            rest = list(it)
            rel.join()
        finally:
            c.close()
    assert c.metrics.registry.get("reshard_waits") >= 1
    refused = [s for s in spans("server.GET_BATCH")
               if s["attrs"].get("error_code") == "reshard"]
    assert refused, "no dispatch span recorded the reshard refusal"
    served = [s for s in spans("server.GET_BATCH")
              if s["trace"] == refused[0]["trace"]
              and "error_code" not in s["attrs"]]
    assert served, "the retried attempt did not keep the refused trace id"
    # and the stream itself was unharmed
    got = np.concatenate([first] + rest)
    assert np.array_equal(got, np.asarray(spec.rank_indices(0, 0)))


def test_degraded_fallback_regen_links_failed_rpc(traced):
    """The degraded-mode regen span carries ``failed_rpc`` = the ids of
    the exact client.rpc span whose failure forced the fallback."""
    X = np.arange(530, dtype=np.int64)
    # nothing listens here: reserve a port and close it
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    addr = probe.getsockname()
    probe.close()
    c = ServiceIndexClient(addr, rank=0, batch=64, backoff_base=0.01,
                           reconnect_timeout=0.2)
    loader = HostDataLoader(X, window=32, batch=64, seed=7, rank=0, world=1,
                            index_client=c)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        got = loader.epoch_indices(0)
    assert loader.degraded
    assert np.array_equal(
        got, HostDataLoader(X, window=32, batch=64, seed=7).epoch_indices(0))
    regen = spans("loader.degraded_regen")
    assert regen, "degraded regen span missing"
    link = regen[0]["attrs"].get("failed_rpc")
    assert link is not None, "degraded regen span carries no rpc link"
    failed = [s for s in spans("client.rpc")
              if [s["trace"], s["span"]] == link]
    assert failed and failed[0]["status"] == "error"
    # both live in the same trace, under the serve_epoch span
    serve = spans("loader.serve_epoch")
    assert serve and serve[0]["trace"] == regen[0]["trace"]


def test_dispatch_fault_dumps_flight_with_faulted_span(traced):
    """ISSUE acceptance: an injected server.dispatch fault produces a
    JSONL flight dump whose spans reconstruct client rpc → server
    dispatch → fault → retry."""
    spec = plain_spec()
    with IndexServer(spec) as srv:
        plan = F.FaultPlan([F.FaultRule(site="server.dispatch",
                                        kind="error", nth=3)])
        c = ServiceIndexClient(srv.address, rank=0, batch=64,
                               backoff_base=0.01, reconnect_timeout=5.0)
        try:
            with plan:
                got = np.concatenate(list(c.epoch_batches(0)))
        finally:
            c.close()
    assert plan.fired("server.dispatch") == 1
    # the retry rode through: delivered stream still exact
    assert np.array_equal(got, np.asarray(spec.rank_indices(0, 0)))
    dumps = glob.glob(os.path.join(str(traced), "flight-*.jsonl"))
    assert len(dumps) == 1, f"expected one flight dump, got {dumps}"
    with open(dumps[0]) as f:
        lines = [json.loads(ln) for ln in f]
    assert lines[0]["kind"] == "flight_dump"
    assert lines[0]["reason"] == "fault.server.dispatch"
    entries = lines[1:]
    open_srv = [e for e in entries
                if e.get("open") and e["name"] == "server.GET_BATCH"]
    assert open_srv, "faulted dispatch span missing from the dump"
    faulted = open_srv[0]
    # the fault event is stamped with the faulted dispatch span's ids
    ev = [e for e in entries if e.get("kind") == "event"
          and e["name"] == "fault_injected"]
    assert ev and ev[0]["span"] == faulted["span"]
    assert ev[0]["attrs"] == {"site": "server.dispatch", "kind": "error"}
    # the client rpc span the dispatch was serving is open in the dump too
    open_rpc = [e for e in entries
                if e.get("open") and e["name"] == "client.rpc"]
    assert open_rpc and open_rpc[0]["trace"] == faulted["trace"]
    assert faulted["parent"] == open_rpc[0]["span"]
    # ...and the RETRY of that same trace later succeeded: a finished
    # server dispatch span with the same trace id, no error
    retried = [e for e in spans("server.GET_BATCH")
               if e["trace"] == faulted["trace"] and not e.get("open")
               and e["status"] == "ok"]
    assert retried, "no successful retry recorded under the faulted trace"


def test_trace_dump_rpc_and_api(traced):
    spec = plain_spec()
    with IndexServer(spec) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=64)
        try:
            list(c.epoch_batches(0))
            rep = c.trace_dump(limit=64)
        finally:
            c.close()
    assert rep["enabled"] is True
    names = {e.get("name") for e in rep["entries"]}
    assert "server.GET_BATCH" in names
    assert len(rep["entries"]) <= 64
    # the local dump() API writes the same entries as JSONL
    path = os.path.join(str(traced), "manual.jsonl")
    assert T.dump(path, reason="test") == path
    with open(path) as f:
        meta = json.loads(f.readline())
    assert meta["kind"] == "flight_dump" and meta["reason"] == "test"


def test_stall_error_triggers_flight_dump(traced):
    err = StallError("no progress", thread=None)
    assert isinstance(err, RuntimeError)
    dumps = glob.glob(os.path.join(str(traced), "flight-*stall*.jsonl"))
    assert len(dumps) == 1


def test_reshard_abort_triggers_flight_dump(traced, monkeypatch):
    """A failure between the barrier freeze and the drain flip must
    unfreeze the server AND dump the flight ring (reason
    ``reshard_abort``).  The ``reshard_drain`` event sits inside that
    window, so making it raise exercises the abort path exactly."""
    def boom(*_a, **_k):
        raise RuntimeError("drain-flip failure")

    with IndexServer(plain_spec(world=2)) as srv:
        monkeypatch.setattr(T, "event", boom)
        with pytest.raises(RuntimeError, match="drain-flip"):
            srv._trigger_reshard(1)
        monkeypatch.undo()
        assert srv._reshard is None, "abort left the barrier frozen"
        assert srv.spec.world == 2  # membership unchanged
    dumps = glob.glob(os.path.join(str(traced),
                                   "flight-*reshard_abort*.jsonl"))
    assert len(dumps) == 1


# --------------------------------------------------------------- histogram
def test_histogram_percentiles_and_report():
    h = Histogram()
    for v in [1.0] * 50 + [10.0] * 45 + [1000.0] * 5:
        h.observe(v)
    rep = h.report()
    assert rep["count"] == 100
    assert rep["mean_ms"] == pytest.approx((50 + 450 + 5000) / 100, rel=1e-6)
    assert rep["max_ms"] == 1000.0
    # p50 lands in the bucket containing 1.0; p99 in the 1000.0 bucket
    assert 0.5 <= rep["p50_ms"] <= 2.0
    assert 512.0 <= rep["p99_ms"] <= 1024.0
    assert h.percentile(0.0) >= 1.0  # clamped to observed min
    assert Histogram().report()["count"] == 0
    with pytest.raises(ValueError):
        Histogram(bounds=[2.0, 1.0])


def test_registry_histograms_in_report_and_prometheus():
    reg = MetricsRegistry()
    reg.inc("batches_served", 3)
    with reg.timer("epoch_regen_ms").measure():
        pass
    reg.histogram("rpc_ms").observe(1.5)
    rep = reg.report()
    assert rep["histograms"]["rpc_ms"]["count"] == 1
    text = T.render_prometheus(reg)
    assert "psds_batches_served 3" in text
    assert "# TYPE psds_rpc_ms histogram" in text
    assert 'psds_rpc_ms_bucket{le="+Inf"} 1' in text
    assert "psds_rpc_ms_count 1" in text
    assert "psds_epoch_regen_ms_ms_count 1" in text
    # ServiceMetrics passes through via its .registry attribute
    assert "psds_batches_served" in T.render_prometheus(
        ServiceMetrics(registry=reg))


def test_server_adopts_latency_histograms():
    T.reset()  # histograms are metrics: they populate with tracing OFF
    spec = plain_spec()
    with IndexServer(spec) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=64)
        try:
            list(c.epoch_batches(0))
        finally:
            c.close()
        hs = srv.metrics.report()["histograms"]
    assert hs["batch_service_ms"]["count"] >= 1
    assert hs["epoch_regen_ms"]["count"] >= 1
    assert c.metrics.report()["histograms"]["rpc_ms"]["count"] >= 1


def test_jsonl_sink_receives_recorded_entries(tmp_path):
    path = os.path.join(str(tmp_path), "live.jsonl")
    T.reset()
    try:
        sink = T.JsonlSink(path, interval_s=0.0, batch=1)
        T.configure(enabled=True, sink=sink)
        with T.span("op", a=1):
            pass
        T.event("standalone")
        sink.flush()
        with open(path) as f:
            lines = [json.loads(ln) for ln in f]
        assert {e.get("name") for e in lines} == {"op", "standalone"}
        assert sink.written == 2
    finally:
        T.reset()
    assert os.path.exists(path)


# ---------------------------------------------------------- bounded state
def test_regen_timer_ring_caps_with_exact_totals():
    t = RegenTimer(max_samples=8)
    for i in range(100):
        t.samples_ms.append(float(i))
    assert len(t.samples_ms) == 8          # bounded tail
    assert list(t.samples_ms) == [float(i) for i in range(92, 100)]
    assert t.count == 100                   # exact across the cap
    assert t.mean_ms == pytest.approx(sum(range(100)) / 100)
    assert t.last_ms == 99.0
    assert t.report()["epochs_timed"] == 100
    # external clear() (stall_native's warmup reset) resets totals too
    t.samples_ms.clear()
    assert not t.samples_ms and t.count == 0 and t.mean_ms == 0.0
    with t.measure():
        pass
    assert t.count == 1 and len(t.samples_ms) == 1


def test_service_metrics_pruned_at_lease_eviction():
    now = [0.0]
    with IndexServer(plain_spec(world=2), heartbeat_timeout=10.0,
                     clock=lambda: now[0]) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=64)
        try:
            next(c.epoch_batches(0))
            assert "0" in srv.metrics.report()["clients"]
            served = srv.metrics.report()["clients"]["0"]["batches_served"]
            # the lease must still be OWNED when the sweep runs — a
            # closed connection releases it and nothing gets evicted
            now[0] = 11.0
            srv._sweep_leases()
            rep = srv.metrics.report()
        finally:
            c.close()
    assert "0" not in rep["clients"], "evicted rank still in the report"
    assert rep["departed"]["clients"] == 1
    assert rep["departed"]["batches_served"] == served
    assert rep["departed"]["evictions"] == 1  # archived AFTER the count
    # totals were never touched
    assert rep["counters"]["batches_served"] == served


def test_service_metrics_pruned_at_reshard_commit():
    with IndexServer(plain_spec(world=2)) as srv:
        c1 = ServiceIndexClient(srv.address, rank=1, batch=64)
        try:
            rep = c1.leave(None)  # idle world: barrier commits immediately
        finally:
            c1.close()
        out = srv.metrics.report()
    assert srv.spec.world == 1 and srv.generation == 1
    assert "1" not in out["clients"], "departed rank still in the report"
    assert out["departed"]["leaves"] == 1
    assert out["counters"]["leaves"] == 1
    assert out["histograms"]["barrier_freeze_ms"]["count"] == 1
    assert out["histograms"]["barrier_drain_ms"]["count"] == 1
