"""Non-uniform sampling modes (docs/SAMPLING.md).

The contract under test: the ``weighted`` / ``prioritized`` / ``dedup``
sampling modes are ordinary specs — bit-identical across the CPU twin
and the jitted device kernel, across served batches, capability local
regen and degraded local regen, and across a mid-epoch reshard plus a
primary-kill failover — while obeying their own laws: empirical draw
frequencies track the weights, additive ``weights_delta`` re-weights
fold at epoch boundaries with zero protocol bytes when static, and the
dedup seen-set never re-serves across epochs nor loses samples across
recovery.

These run inside tier-1 and are the first leg of the
``make sampling-smoke`` gate (``-m sampling``).
"""

from __future__ import annotations

import threading
import warnings
from collections import Counter

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu import telemetry
from partiallyshuffledistributedsampler_tpu.sampling import (
    BloomSeen,
    SamplingSpec,
    build_alias_table,
)
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    ServiceError,
    ServiceIndexClient,
)
from partiallyshuffledistributedsampler_tpu.service.spec import (
    PartialShuffleSpec,
)

from test_failover import replicated_pair, wait_for, wait_synced

pytestmark = pytest.mark.sampling

SECRET = b"psds-test-deployment-secret"

SIZES = (40, 30, 26)   #: three sources over a 96-id space
T = 96                 #: epoch draw budget (divisible by worlds 2, 3, 4)
#: dedup epochs draw HALF the id space, so epochs 0+1 tile it exactly
#: once (the strongest no-repeat law) and epoch 2 must saturate
T_DEDUP = 48


def build_spec(mode, world=1, **kw):
    kw.setdefault("seed", 7)
    kw.setdefault("window", 8)
    if mode == "weighted":
        return SamplingSpec.weighted(SIZES, (3, 1, 2), epoch_samples=T,
                                     world=world, **kw)
    if mode == "prioritized":
        return SamplingSpec.prioritized(SIZES, (1, 1, 1), epoch_samples=T,
                                        world=world, **kw)
    return SamplingSpec.deduped(SIZES, epoch_samples=T_DEDUP, world=world,
                                **kw)


def source_of(x):
    if x < SIZES[0]:
        return 0
    return 1 if x < SIZES[0] + SIZES[1] else 2


# ------------------------------------------------------------ alias laws
def test_alias_table_exact_structure():
    t = build_alias_table((3, 1, 0, 2), "per_source", (100, 50, 25, 7))
    assert t.total == 6
    assert sum(t.probs) + sum(t.total - p for p in t.probs) == 4 * t.total
    # columns sum exactly: every source's mass is fully represented
    mass = [0] * 4
    for s in range(4):
        mass[s] += t.probs[s]
        mass[t.alias[s]] += t.total - t.probs[s]
    assert mass == [m * 4 for m in (3, 1, 0, 2)]


def test_alias_degenerate_uniform_and_one_hot_exact():
    # uniform weights: every column is a full column of itself
    t = build_alias_table((5, 5, 5), "per_source", SIZES)
    assert all(p == t.total for p in t.probs)
    assert tuple(t.alias) == (0, 1, 2)
    # one-hot: every draw must land inside the hot source, exactly
    spec = SamplingSpec.weighted(SIZES, (0, 1, 0), epoch_samples=T, seed=3)
    got = spec.rank_indices(0, 0)
    lo, hi = SIZES[0], SIZES[0] + SIZES[1]
    assert len(got) == T
    assert all(lo <= int(x) < hi for x in got)


def test_alias_scaling_invariance():
    a = build_alias_table((3, 1, 2), "per_source", SIZES)
    b = build_alias_table((21, 7, 14), "per_source", SIZES)
    # GCD canonicalization: proportional weights build the SAME table
    assert a == b and a.total == 6
    # and the streams are identical: only the RATIOS are the identity
    s1 = SamplingSpec.weighted(SIZES, (3, 1, 2), epoch_samples=T, seed=7)
    s2 = SamplingSpec.weighted(SIZES, (21, 7, 14), epoch_samples=T, seed=7)
    assert np.array_equal(s1.rank_indices(0, 0), s2.rank_indices(0, 0))


def test_statistical_law_frequencies_track_weights():
    """Empirical per-source frequencies of a seeded run stay within a
    fixed tolerance of the target ratios — per_source AND per_sample."""
    big = SamplingSpec.weighted(SIZES, (5, 0, 3), epoch_samples=40_000,
                                seed=11)
    got = big.rank_indices(0, 0)
    counts = Counter(source_of(int(x)) for x in got)
    assert counts[1] == 0
    for s, target in ((0, 5 / 8), (2, 3 / 8)):
        f = counts[s] / 40_000
        assert abs(f - target) < 0.02, (s, f, target)
    # per_sample: mass is weight * size -> (40*2, 30*0, 26*5)
    ps = SamplingSpec.weighted(SIZES, (2, 0, 5), epoch_samples=40_000,
                               weight_kind="per_sample", seed=11)
    got = ps.rank_indices(0, 0)
    counts = Counter(source_of(int(x)) for x in got)
    tot = 40 * 2 + 26 * 5
    assert counts[1] == 0
    for s, target in ((0, 80 / tot), (2, 130 / tot)):
        f = counts[s] / 40_000
        assert abs(f - target) < 0.02, (s, f, target)


# ----------------------------------------------------- CPU/device identity
@pytest.mark.parametrize("mode", ["weighted", "prioritized", "dedup"])
def test_cpu_vs_device_bit_identity(mode):
    """The jitted device kernel and the CPU twin agree bit-for-bit —
    epoch streams AND elastic cascade layers (for dedup the fold itself
    is host-normative, so backend choice must be a no-op)."""
    cpu = build_spec(mode, world=2)
    dev = PartialShuffleSpec.from_wire(cpu.to_wire(), backend="xla")
    if mode == "prioritized":
        cpu = cpu.with_stream_weights({1: (4, 1, 2)})
        dev = dev.with_stream_weights({1: (4, 1, 2)})
    # consumed must fit the per-rank share (T/2 per rank at world 2)
    layers = [(2, 18)] if mode == "dedup" else [(2, 36)]
    for epoch in (0, 1):
        for r in range(2):
            a = np.asarray(cpu.rank_indices(epoch, r))
            b = np.asarray(dev.rank_indices(epoch, r))
            assert np.array_equal(a, b), (mode, epoch, r)
            if mode != "dedup" or epoch == 0:
                c = np.asarray(cpu.rank_indices(epoch, r, layers=layers))
                d = np.asarray(dev.rank_indices(epoch, r, layers=layers))
                assert np.array_equal(c, d), (mode, "elastic", epoch, r)


@pytest.mark.parametrize("mode", ["weighted", "prioritized", "dedup"])
def test_wire_roundtrip_and_world_stripped_fingerprint(mode):
    spec = build_spec(mode, world=2)
    rt = PartialShuffleSpec.from_wire(spec.to_wire())
    assert isinstance(rt, SamplingSpec)
    assert rt.fingerprint() == spec.fingerprint()
    assert np.array_equal(rt.rank_indices(0, 0), spec.rank_indices(0, 0))
    w3 = spec.with_world(3)
    assert w3.fingerprint() != spec.fingerprint()
    assert (w3.fingerprint(include_world=False)
            == spec.fingerprint(include_world=False))
    if mode == "prioritized":
        # adopted weights stay OUT of the wire: same stream identity
        re = spec.with_stream_weights({2: (9, 1, 1)})
        assert re.fingerprint() == spec.fingerprint()
        assert not np.array_equal(re.rank_indices(2, 0),
                                  spec.rank_indices(2, 0))
        assert np.array_equal(re.rank_indices(1, 0),
                              spec.rank_indices(1, 0))


def test_union_of_ranks_is_the_global_stream():
    for mode in ("weighted", "dedup"):
        g = build_spec(mode, world=1)
        w4 = g.with_world(4)
        u = np.concatenate([w4.rank_indices(1, r) for r in range(4)])
        assert sorted(u.tolist()) == sorted(g.rank_indices(1, 0).tolist())


# -------------------------------------------------------------- dedup laws
def test_dedup_never_repeats_across_epochs():
    """Epochs 0+1 (2 x 48 draws over 96 ids) tile the id space exactly
    once — the seen-set turns sampling-with-replacement into full
    coverage; epoch 2 must then saturate, loudly, at full length."""
    spec = build_spec("dedup")
    served = []
    for e in range(2):
        got = spec.rank_indices(e, 0)
        assert len(got) == T_DEDUP
        served.extend(int(x) for x in got)
    assert sorted(served) == list(range(sum(SIZES)))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        e2 = spec.rank_indices(2, 0)
    assert len(e2) == T_DEDUP
    assert any("saturated" in str(x.message) for x in w)


def test_dedup_saturation_is_loud_and_keeps_epoch_length():
    tiny = SamplingSpec.deduped((4, 4), epoch_samples=6, seed=3, window=4)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        a = tiny.rank_indices(0, 0)
        b = tiny.rank_indices(1, 0)
    assert len(a) == 6 and len(b) == 6
    assert len(set(a.tolist() + b.tolist())) == 8, "ids lost pre-saturation"
    assert any("saturated" in str(x.message) for x in w)


def test_dedup_boundary_snapshot_equals_refold():
    spec = build_spec("dedup")
    spec.rank_indices(1, 0)  # folds epochs 0..1, caching boundaries
    bw = spec.dedup_boundary_wire(1)
    assert bw is not None and bw["epoch"] == 1
    fresh = build_spec("dedup").with_dedup_boundary(bw["epoch"], bw["seen"])
    assert np.array_equal(fresh.rank_indices(1, 0), spec.rank_indices(1, 0))


def test_bloom_no_false_negatives_and_filtering():
    bs = BloomSeen(1 << 12, 4, seed=42)
    for x in range(500):
        bs.add(x * 3)
    assert all(bs.contains(x * 3) for x in range(500))
    spec = SamplingSpec.deduped(
        SIZES, epoch_samples=T_DEDUP, seed=7, window=8,
        dedup={"kind": "bloom", "bits": 4096, "hashes": 3})
    served = [int(x) for e in range(2) for x in spec.rank_indices(e, 0)]
    assert len(set(served)) == len(served), "bloom mode re-served an id"
    bw = spec.dedup_boundary_wire(1)
    fresh = SamplingSpec.deduped(
        SIZES, epoch_samples=T_DEDUP, seed=7, window=8,
        dedup={"kind": "bloom", "bits": 4096, "hashes": 3})
    fresh = fresh.with_dedup_boundary(bw["epoch"], bw["seen"])
    assert np.array_equal(fresh.rank_indices(1, 0), spec.rank_indices(1, 0))


# ------------------------------------------------------- three serve paths
@pytest.mark.parametrize("mode", ["weighted", "prioritized", "dedup"])
def test_three_serve_paths_bit_identical(mode):
    """Served batches, capability local regen, and degraded local regen
    produce the identical stream for every mode."""
    spec = build_spec(mode, world=2)
    # one FRESH server per arm: delivery is exactly-once per rank, so
    # re-serving the same epoch to the same rank on one server would
    # (correctly) come back empty on the second arm
    for arm in ("served", "capability", "degraded"):
        with IndexServer(build_spec(mode, world=2),
                         capability_secret=SECRET) as srv:
            for r in range(2):
                local = np.asarray(spec.rank_indices(0, r))
                c = ServiceIndexClient(srv.address, rank=r, batch=16,
                                       spec=build_spec(mode, world=2),
                                       capability_secret=SECRET,
                                       backoff_base=0.01,
                                       reconnect_timeout=10.0)
                try:
                    if arm == "served":
                        arr = np.concatenate(list(c.epoch_batches(0)))
                    elif arm == "capability":
                        arr = np.asarray(c.capability_epoch_indices(
                            0, spec=build_spec(mode, world=2)))
                    else:
                        arr = np.asarray(c.local_epoch_indices(
                            build_spec(mode, world=2), 0))
                finally:
                    c.close()
                assert np.array_equal(arr, local), (mode, r, arm)


def test_prioritized_weights_delta_folds_at_epoch_boundary():
    """SET_EPOCH's additive ``weights_delta`` re-weights the alias table
    with the streaming fold law; the signed capability carries the
    effective weights so the regen arm tracks; a static spec keeps the
    grant byte-identical (``weights_for`` stays None)."""
    spec = build_spec("prioritized", world=1)
    with IndexServer(spec, capability_secret=SECRET) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=16,
                               spec=build_spec("prioritized", world=1),
                               capability_secret=SECRET,
                               backoff_base=0.01, reconnect_timeout=10.0)
        try:
            base_e0 = np.concatenate(list(c.epoch_batches(0)))
            assert srv.spec.weights_for(0) is None, "static spec adopted"
            c.set_epoch(1, weights_delta=[4, 0, 0])
            assert srv.spec.weights_for(1) == (5, 1, 1)
            assert srv.spec.weights_for(0) is None
            served = np.concatenate(list(c.epoch_batches(1)))
        finally:
            c.close()
        assert srv.metrics.report()["counters"]["sampling_reweights"] >= 1
    # the capability arm on its own server (delivery is exactly-once
    # per rank): the grant's effective weights drive local regen
    with IndexServer(build_spec("prioritized", world=1),
                     capability_secret=SECRET) as srv:
        c2 = ServiceIndexClient(srv.address, rank=None, batch=16,
                                attach=True, backoff_base=0.01,
                                reconnect_timeout=10.0)
        try:
            c2.set_epoch(1, weights_delta=[4, 0, 0])
        finally:
            c2.close()
        c3 = ServiceIndexClient(srv.address, rank=0, batch=16,
                                spec=build_spec("prioritized", world=1),
                                capability_secret=SECRET,
                                backoff_base=0.01, reconnect_timeout=10.0)
        try:
            cap = np.asarray(c3.capability_epoch_indices(
                1, spec=build_spec("prioritized", world=1)))
        finally:
            c3.close()
    assert len(base_e0) == T
    ref = build_spec("prioritized", world=1).with_stream_weights(
        {1: (5, 1, 1)})
    assert np.array_equal(served, ref.rank_indices(1, 0))
    assert np.array_equal(cap, served), "capability arm diverged"
    assert not np.array_equal(  # the re-weight genuinely moved epoch 1
        served, build_spec("prioritized", world=1).rank_indices(1, 0))


def test_weights_delta_refused_for_non_prioritized():
    with IndexServer(build_spec("weighted", world=1)) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=16,
                               backoff_base=0.01, reconnect_timeout=10.0)
        try:
            with pytest.raises(ServiceError):
                c.set_epoch(1, weights_delta=[1, 0, 0])
        finally:
            c.close()
        assert srv.epoch == 0, "refused delta must not move the epoch"
    with IndexServer(build_spec("prioritized", world=1)) as srv:
        c = ServiceIndexClient(srv.address, rank=0, batch=16,
                               backoff_base=0.01, reconnect_timeout=10.0)
        try:
            with pytest.raises(ServiceError):  # wrong arity refused too
                c.set_epoch(1, weights_delta=[1])
        finally:
            c.close()
        assert srv.epoch == 0 and srv.spec.weights_for(1) is None


def test_prioritized_reweight_survives_failover():
    """The sampling WAL record replicates an adopted re-weight: the
    promoted standby serves the re-weighted epoch bit-identically."""
    spec = build_spec("prioritized", world=1)
    primary, standby = replicated_pair(spec)
    try:
        c = ServiceIndexClient(primary.address, rank=0, batch=16,
                               spec=build_spec("prioritized", world=1),
                               backoff_base=0.01, reconnect_timeout=10.0)
        try:
            c.set_epoch(1, weights_delta=[6, 0, 0])
            wait_synced(primary, standby)
            assert standby.spec.weights_for(1) == (7, 1, 1)
            primary.kill()
            # promotion is demand-driven: this request fails over to the
            # standby, which promotes and serves the re-weighted epoch
            served = np.concatenate(list(c.epoch_batches(1)))
            assert standby.role == "primary", "standby never promoted"
        finally:
            c.close()
    finally:
        primary.kill()
        standby.stop()
    ref = build_spec("prioritized", world=1).with_stream_weights(
        {1: (7, 1, 1)})
    assert np.array_equal(served, ref.rank_indices(1, 0))


# ------------------------------------------- reshard + failover union laws
def test_dedup_union_across_mid_epoch_reshard():
    """A 2 -> 3 reshard mid-epoch-1 of a dedup stream: the union of all
    deliveries is exactly epochs 0+1 of the global filtered stream —
    nothing double-served (dedup's own law on top of exactly-once),
    nothing dropped."""
    spec = build_spec("dedup", world=2)
    ref_spec = build_spec("dedup", world=1)
    ref = np.concatenate([ref_spec.rank_indices(e, 0) for e in (0, 1)])
    delivered = {}
    lock = threading.Lock()
    b_hit = threading.Barrier(2)
    b_go = threading.Barrier(2)
    with IndexServer(spec) as srv:
        addr = srv.address

        def worker(r):
            got = []
            c = ServiceIndexClient(addr, rank=r, batch=8,
                                   backoff_base=0.01,
                                   reconnect_timeout=20.0)
            try:
                got.extend(c.epoch_batches(0))
                it = c.epoch_batches(1)
                for _ in range(2):
                    got.append(next(it))
                b_hit.wait(timeout=30.0)
                if r == 0:
                    c.reshard(3)
                b_go.wait(timeout=30.0)
                got.extend(it)
            finally:
                with lock:
                    delivered[r] = got
                c.close()

        def joiner():
            c = ServiceIndexClient(addr, rank=None, batch=8,
                                   backoff_base=0.01,
                                   reconnect_timeout=20.0)
            try:
                got = list(c.epoch_batches(1))
            finally:
                with lock:
                    delivered["j"] = got
                c.close()

        ths = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
        for t in ths:
            t.start()
        import time as _time
        _time.sleep(0.6)
        jt = threading.Thread(target=joiner)
        jt.start()
        for t in ths + [jt]:
            t.join(60.0)
            assert not t.is_alive(), "worker hung"
        assert srv.generation == 1 and srv.spec.world == 3
    union = Counter(int(x) for got in delivered.values()
                    for arr in got for x in np.asarray(arr))
    full = Counter(int(x) for x in ref)
    missing = full - union
    assert not missing, f"dropped: {sorted(missing)[:8]}"
    extras = union - full
    # wrap-pad allowance: whole samples, bounded by one reshard
    assert sum(extras.values()) <= 3, f"extras: {extras}"
    assert set(extras) <= set(full)


def test_dedup_failover_bit_identical_with_snapshot_boundary():
    """Primary killed between epochs: the promoted standby — whose
    state carries the dedup boundary — serves epoch 1 bit-identically,
    so across the failover nothing is re-served or dropped."""
    spec = build_spec("dedup", world=1)
    primary, standby = replicated_pair(spec)
    try:
        c = ServiceIndexClient(primary.address, rank=0, batch=16,
                               backoff_base=0.01, reconnect_timeout=10.0)
        try:
            e0 = np.concatenate(list(c.epoch_batches(0)))
            # force a state record so the standby holds the fold
            primary._repl_append("state", state=primary._state_dict())
            wait_synced(primary, standby)
            sm = standby._state_dict().get("sampling") or {}
            assert sm.get("dedup"), "standby state lost the seen-set"
            primary.kill()
            # promotion is demand-driven: this request fails over to the
            # standby, which promotes and serves epoch 1 from the
            # replicated boundary (no refold from epoch 0 needed)
            e1 = np.concatenate(list(c.epoch_batches(1)))
            assert standby.role == "primary", "standby never promoted"
        finally:
            c.close()
    finally:
        primary.kill()
        standby.stop()
    ref = build_spec("dedup", world=1)
    assert np.array_equal(e0, ref.rank_indices(0, 0))
    assert np.array_equal(e1, ref.rank_indices(1, 0))
    assert not set(e0.tolist()) & set(e1.tolist()), "re-served across kill"


def test_dedup_crash_recovery_from_disk(tmp_path):
    """Restart-from-disk: the snapshotted seen-set boundary short-cuts
    recovery, and the recovered server serves the identical stream."""
    spec = build_spec("dedup", world=1)
    snap = str(tmp_path / "snap.json")
    wal = str(tmp_path / "wal")
    srv = IndexServer(spec, port=0, snapshot_path=snap, wal_dir=wal)
    srv.start()
    host, port = srv.address
    with ServiceIndexClient((host, port), rank=0, batch=16,
                            backoff_base=0.01,
                            reconnect_timeout=10.0) as c:
        e0 = np.concatenate(list(c.epoch_batches(0)))
        c.set_epoch(1)
    srv.kill()
    srv2 = IndexServer(build_spec("dedup", world=1), port=port,
                       snapshot_path=snap, wal_dir=wal)
    srv2.start()
    try:
        assert srv2.epoch == 1
        with ServiceIndexClient((host, port), rank=0, batch=16,
                                backoff_base=0.01,
                                reconnect_timeout=10.0) as c:
            e1 = np.concatenate(list(c.epoch_batches(1)))
    finally:
        srv2.stop()
    ref = build_spec("dedup", world=1)
    assert np.array_equal(e0, ref.rank_indices(0, 0))
    assert np.array_equal(e1, ref.rank_indices(1, 0))
    assert not set(e0.tolist()) & set(e1.tolist())


# ------------------------------------------------------- cost-model plumb
def test_fleetsim_prices_sampling_modes():
    from partiallyshuffledistributedsampler_tpu.autopilot.priors import (
        workload_key,
    )
    from partiallyshuffledistributedsampler_tpu.fleetsim.latency import (
        RegenCostModel,
    )

    m = RegenCostModel()
    n = 50_000_000
    # dedup regen is host-bound: the device line must NOT look cheap
    assert m.estimate_ms("xla", n, "dedup") == m.estimate_ms(
        "native", n, "dedup")
    assert m.pick(n, "dedup")[0] == m.host_backend
    assert m.pick(n)[0] == "xla", "uniform crossover regressed"
    assert m.pick(n, "weighted")[2]["sampling_mode"] == "weighted"
    # priors: sampling workloads get their own warm-start keys, and
    # uniform keys keep their historical form
    uni = PartialShuffleSpec("plain", n=96, window=8, world=2)
    assert workload_key(uni) == "n96:w2"
    assert workload_key(build_spec("dedup", world=2)) == "n96:w2:sdedup"
    assert (workload_key(build_spec("weighted", world=2))
            == "n96:w2:sweighted")


def test_telemetry_records_alias_fallback_event():
    from partiallyshuffledistributedsampler_tpu import faults as F

    telemetry.reset()
    telemetry.configure(enabled=True)
    try:
        spec = build_spec("weighted")
        plan = F.FaultPlan([F.FaultRule("sampling.alias_build", "error",
                                        count=1)])
        with plan, warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            got = spec.rank_indices(0, 0)
        assert plan.fired("sampling.alias_build") >= 1
        assert any("UNIFORM" in str(x.message) for x in w)
        uniform = SamplingSpec.weighted(SIZES, (1, 1, 1), epoch_samples=T,
                                        seed=7, window=8)
        assert np.array_equal(got, uniform.rank_indices(0, 0))
        names = [e["name"] for e in telemetry.recorder().snapshot()
                 if e.get("name")]
        assert "sampling_alias_fallback" in names
    finally:
        telemetry.reset()
        telemetry.configure(enabled=False)


# ------------------------------------------------------- fleetsim integration
def test_fleetsim_cost_model_and_priors_know_sampling_modes():
    """The simulator's regen cost lines and the autopilot's prior keys
    distinguish the non-uniform modes: a dedup fold pays host-side work
    on every backend, and a sampling workload never warm-starts a
    uniform deployment of the same shape (or vice versa)."""
    from partiallyshuffledistributedsampler_tpu.autopilot.priors import (
        workload_key,
    )
    from partiallyshuffledistributedsampler_tpu.fleetsim import (
        FleetSim,
        RegenCostModel,
    )
    from partiallyshuffledistributedsampler_tpu.fleetsim.workload import (
        uniform,
    )

    m = RegenCostModel()
    n = 1 << 20
    base_dev = m.estimate_ms("xla", n)
    # dedup is host-bound regardless of backend: the fold's seen-set
    # probes never ride the device
    assert m.estimate_ms("xla", n, sampling_mode="dedup") > base_dev
    assert (m.estimate_ms("xla", n, sampling_mode="dedup")
            == m.estimate_ms("native", n, sampling_mode="dedup"))
    # weighted/prioritized scale the per-sample rate, same shape
    assert (m.estimate_ms("xla", n, sampling_mode="weighted")
            == pytest.approx(base_dev * 1.0))
    cand, _, info = m.pick(n, sampling_mode="dedup")
    assert info["sampling_mode"] == "dedup"

    # workload keys: uniform keeps its historical form, sampling
    # modes get their own key space
    uni = build_spec("weighted", world=2)
    plain_key = f"n{uni.n}:w2"
    assert workload_key(uni) == f"n{uni.n}:w2:sweighted"
    assert workload_key(build_spec("dedup", world=2)).endswith(":sdedup")

    class _PlainShape:
        n, world = uni.n, 2

    assert workload_key(_PlainShape()) == plain_key

    # the sim threads the mode through to every cost estimate
    sim = FleetSim(world=8, n_shards=2, n=1 << 16,
                   workload=uniform(200.0), seed=3,
                   sampling_mode="dedup")
    sim.run(ticks=2)
    assert sim.sampling_mode == "dedup"
