"""Statistical quality of the shuffle — the guard rail behind the
rounds=24 default (SPEC.md §2).  These are distributional tests with loose
thresholds chosen to be stable across seeds (no flaky 1-in-20 failures):
fail here means the permutation family is structurally biased, not unlucky.
"""

import numpy as np

from partiallyshuffledistributedsampler_tpu.ops import core, cpu


def _perm(m, key):
    return core.swap_or_not(
        np, np.arange(m, dtype=np.uint32), m, np.asarray(key, np.uint32),
        core.DEFAULT_ROUNDS,
    )


def test_position_uniformity_chi_square():
    """Image of position 0 over many keys should be ~uniform over [0, m).
    Chi-square over 16 buckets, 4096 keys: E=256 per bucket; reject only on
    gross bias (threshold ~2x the 99.9th percentile of chi2_15)."""
    m = 257
    hits = np.zeros(16, dtype=np.int64)
    for key in range(4096):
        y = int(_perm(m, key)[0])
        hits[min(15, y * 16 // m)] += 1
    expected = 4096 / 16
    chi2 = ((hits - expected) ** 2 / expected).sum()
    assert chi2 < 80, (chi2, hits)


def test_pairwise_order_decorrelation():
    """P(pi(0) < pi(1)) over keys should be ~1/2 — adjacent inputs must not
    preserve order systematically."""
    m = 512
    keep = sum(
        1 for key in range(2000) if (p := _perm(m, key))[0] < p[1]
    )
    assert 0.44 < keep / 2000 < 0.56


def test_epoch_to_epoch_displacement_uniform():
    """Within one window, the element at offset k should move to a fresh
    ~uniform offset each epoch (no sticky positions across epochs)."""
    n, w = 8192, 1024
    seen = []
    for epoch in range(64):
        idx = cpu.epoch_indices_np(n, w, 3, epoch, 0, 1)
        seen.append(int(idx[0]))
    # 64 draws from the first output slot; its source window varies with the
    # outer bijection, so values spread over [0, n)
    spread = np.ptp(seen)
    assert spread > n // 4
    assert len(set(seen)) > 48  # mostly distinct across epochs


def test_fixed_points_scale_like_uniform():
    """E[#fixed points] = 1 for a uniform permutation; across 50 keys at
    m=2048 the mean must stay O(1) (structural identity-leakage check)."""
    m = 2048
    ident = np.arange(m, dtype=np.uint32)
    counts = [int((_perm(m, k) == ident).sum()) for k in range(50)]
    assert np.mean(counts) < 4.0, counts


def test_window_order_uniformity():
    """The outer bijection's image of slot 0 over epochs covers the window
    range without clumping."""
    n, w = 100_000, 100  # 1000 windows
    firsts = []
    for epoch in range(200):
        first = int(cpu.epoch_indices_np(n, w, 11, epoch, 0, 1)[0])
        firsts.append(first // w)
    assert np.ptp(firsts) > 500      # spans most of the window ids
    assert len(set(firsts)) > 150    # and rarely repeats


def test_rank_streams_uncorrelated():
    """Two ranks' streams in the same epoch share no systematic offset: the
    elementwise difference should look random, not constant.  Matched
    positions usually share a window, so diffs live in (-W, W) — near-full
    coverage of that range (not a handful of values) is the pass bar."""
    w = 256
    a = cpu.epoch_indices_np(10_000, w, 5, 0, 0, 4).astype(np.int64)
    b = cpu.epoch_indices_np(10_000, w, 5, 0, 1, 4).astype(np.int64)
    diffs = np.unique(b - a)
    assert len(diffs) > w, len(diffs)  # observed ~464 of the 511 possible
