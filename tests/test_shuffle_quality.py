"""Statistical quality of the shuffle — the guard rail behind the
rounds=24 default (SPEC.md §2).  These are distributional tests with loose
thresholds chosen to be stable across seeds (no flaky 1-in-20 failures):
fail here means the permutation family is structurally biased, not unlucky.
"""

import numpy as np

from partiallyshuffledistributedsampler_tpu.ops import core, cpu


def _perm(m, key):
    return core.swap_or_not(
        np, np.arange(m, dtype=np.uint32), m, np.asarray(key, np.uint32),
        core.DEFAULT_ROUNDS,
    )


def test_position_uniformity_chi_square():
    """Image of position 0 over many keys should be ~uniform over [0, m).
    Chi-square over 16 buckets, 4096 keys: E=256 per bucket; reject only on
    gross bias (threshold ~2x the 99.9th percentile of chi2_15)."""
    m = 257
    hits = np.zeros(16, dtype=np.int64)
    for key in range(4096):
        y = int(_perm(m, key)[0])
        hits[min(15, y * 16 // m)] += 1
    expected = 4096 / 16
    chi2 = ((hits - expected) ** 2 / expected).sum()
    assert chi2 < 80, (chi2, hits)


def test_pairwise_order_decorrelation():
    """P(pi(0) < pi(1)) over keys should be ~1/2 — adjacent inputs must not
    preserve order systematically."""
    m = 512
    keep = sum(
        1 for key in range(2000) if (p := _perm(m, key))[0] < p[1]
    )
    assert 0.44 < keep / 2000 < 0.56


def test_epoch_to_epoch_displacement_uniform():
    """Within one window, the element at offset k should move to a fresh
    ~uniform offset each epoch (no sticky positions across epochs)."""
    n, w = 8192, 1024
    seen = []
    for epoch in range(64):
        idx = cpu.epoch_indices_np(n, w, 3, epoch, 0, 1)
        seen.append(int(idx[0]))
    # 64 draws from the first output slot; its source window varies with the
    # outer bijection, so values spread over [0, n)
    spread = np.ptp(seen)
    assert spread > n // 4
    assert len(set(seen)) > 48  # mostly distinct across epochs


def test_fixed_points_scale_like_uniform():
    """E[#fixed points] = 1 for a uniform permutation; across 50 keys at
    m=2048 the mean must stay O(1) (structural identity-leakage check)."""
    m = 2048
    ident = np.arange(m, dtype=np.uint32)
    counts = [int((_perm(m, k) == ident).sum()) for k in range(50)]
    assert np.mean(counts) < 4.0, counts


def test_window_order_uniformity():
    """The outer bijection's image of slot 0 over epochs covers the window
    range without clumping."""
    n, w = 100_000, 100  # 1000 windows
    firsts = []
    for epoch in range(200):
        first = int(cpu.epoch_indices_np(n, w, 11, epoch, 0, 1)[0])
        firsts.append(first // w)
    assert np.ptp(firsts) > 500      # spans most of the window ids
    assert len(set(firsts)) > 150    # and rarely repeats


# ------------------------------------------------- production-scale domains
#
# The outer (window-order) bijection's real domains are nw_full = n/W:
# ~122k for the C4 config (1e9 / 8192) and ~1.2M for the Llama-3 10B-index
# config (BASELINE.json configs 3/5).  The toy-domain tests above can't
# certify rounds=24 there, so these run the *full* domain, vectorized
# (numpy, ~1 s at 1.2M).  Calibration measured on this machine (SPEC.md §2
# rounds-sensitivity note): at rounds=8 the displacement chi2 is ~14k/88k
# (df=63) and fixed points are ~m/116 (1047 / 4805 vs E[1]); at rounds=16
# fixed points are still 6/22; at rounds=24 every statistic below sits at
# its uniform null (fixed<=3, chi2~50-85 across 8 keys) and rounds=48 buys
# nothing measurable — 24 is the knee of the curve.

_PROD_DOMAINS = (122_070, 1_220_703)


def _outer_perm_full(m: int, seed: int, epoch: int) -> np.ndarray:
    """The actual outer bijection at its production key schedule."""
    x = np.arange(m, dtype=np.uint32)
    k = core.outer_key(np, core.derive_epoch_key(np, seed, epoch))
    return core.swap_or_not(np, x, m, k, core.DEFAULT_ROUNDS).astype(np.int64)


def test_production_domain_displacement_uniform():
    """Displacement (y - x) mod m over the FULL domain: chi-square against
    uniform over 64 buckets (df=63, 99.9th pct ~103; bar set at 150).  A
    too-low round count shows up here first (measured 14334 at rounds=8)."""
    for m in _PROD_DOMAINS:
        y = _outer_perm_full(m, 7, 3)
        disp = (y - np.arange(m, dtype=np.int64)) % m
        h = np.bincount(disp * 64 // m, minlength=64)
        e = m / 64
        chi2 = float(((h - e) ** 2 / e).sum())
        assert chi2 < 150, (m, chi2)


def test_production_domain_window_destination_mixing():
    """Bucket-to-bucket transition matrix (32x32 over the window-id range)
    must be flat: windows from any storage region scatter across all
    regions.  df=1023 -> mean 1023, 99.9th ~1168; bar at 1400 (measured
    ~960-1035 at rounds=24, 6308+ at rounds=8)."""
    for m in _PROD_DOMAINS:
        x = np.arange(m, dtype=np.int64)
        y = _outer_perm_full(m, 11, 5)
        b = 32
        tm = np.bincount((x * b // m) * b + (y * b // m), minlength=b * b)
        e = m / (b * b)
        chi2 = float(((tm - e) ** 2 / e).sum())
        assert chi2 < 1400, (m, chi2)


def test_production_domain_fixed_points_poisson():
    """#fixed points of a uniform permutation ~ Poisson(1); summed over 8
    independent keys ~ Poisson(8), P(sum > 25) < 1e-6.  rounds=8 measures
    in the THOUSANDS per key here — this is the sharpest rounds detector."""
    for m in _PROD_DOMAINS:
        x = np.arange(m, dtype=np.int64)
        total = sum(
            int((_outer_perm_full(m, key, key * 3 + 1) == x).sum())
            for key in range(8)
        )
        assert total < 25, (m, total)


def test_production_domain_order_decorrelation():
    """Adjacent-pair order preservation P(y[i+1] > y[i]) ~ 1/2 and linear
    correlation corr(x, y) ~ 0 over the full domain (binomial std at
    m=122k is 0.0014 — the 0.49/0.51 bar is >7 sigma)."""
    for m in _PROD_DOMAINS:
        y = _outer_perm_full(m, 3, 9)
        order = float((np.diff(y) > 0).mean())
        assert 0.49 < order < 0.51, (m, order)
        corr = float(np.corrcoef(np.arange(m, dtype=np.int64), y)[0, 1])
        assert abs(corr) < 0.01, (m, corr)


def test_rank_streams_uncorrelated():
    """Two ranks' streams in the same epoch share no systematic offset: the
    elementwise difference should look random, not constant.  Matched
    positions usually share a window, so diffs live in (-W, W) — near-full
    coverage of that range (not a handful of values) is the pass bar."""
    w = 256
    a = cpu.epoch_indices_np(10_000, w, 5, 0, 0, 4).astype(np.int64)
    b = cpu.epoch_indices_np(10_000, w, 5, 0, 1, 4).astype(np.int64)
    diffs = np.unique(b - a)
    assert len(diffs) > w, len(diffs)  # observed ~464 of the 511 possible
