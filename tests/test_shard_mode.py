"""Shard-index mode (SPEC.md §7) — golden-pinned laws + properties.

The golden values freeze the per-shard seed derivation (§7.1), the
within-shard order (§7.2, both full and bounded), and the shuffle-buffer
stream (§7.3): any change to those laws breaks checkpointed shard streams
and must show up here as a failed golden, forcing a spec version bump.
"""

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.sampler.shard_mode import (
    PartialShuffleShardSampler,
    expand_shard_indices,
    expand_shard_indices_np,
    shard_sample_order,
    shard_seed,
    shuffle_buffer,
)

_SIZES = [5, 0, 7, 3, 4]  # shard 1 empty; offsets 0,5,5,12,15


# ------------------------------------------------------------------- goldens

def test_golden_shard_seed_frozen():
    assert shard_seed(3, 2) == 11400714819323198484
    assert shard_seed(0, 0) == 0x9E3779B97F4A7C15


def test_golden_within_shard_order_frozen():
    assert shard_sample_order(2, 7, seed=3, epoch=1).tolist() == [
        5, 3, 6, 1, 2, 4, 0
    ]


def test_golden_expand_frozen():
    got = expand_shard_indices_np([2, 0, 3], _SIZES, seed=3, epoch=1)
    assert got.tolist() == [10, 8, 11, 6, 7, 9, 5, 1, 2, 0, 3, 4, 13, 12, 14]


def test_golden_expand_bounded_frozen():
    got = expand_shard_indices_np(
        [2, 0, 3], _SIZES, seed=3, epoch=1, within_shard_shuffle=2
    )
    assert got.tolist() == [5, 6, 8, 7, 9, 10, 11, 0, 1, 3, 2, 4, 12, 13, 14]


def test_golden_shuffle_buffer_frozen():
    assert list(shuffle_buffer(range(12), 4, seed=5, epoch=0)) == [
        3, 4, 1, 5, 0, 6, 8, 2, 11, 9, 10, 7
    ]


# ---------------------------------------------------------------- properties

def test_expand_is_partition_of_selected_shards():
    """The expansion is a permutation of exactly the selected shards' global
    index ranges."""
    got = expand_shard_indices_np([2, 0, 3], _SIZES, seed=9, epoch=4)
    want = sorted(list(range(5, 12)) + list(range(0, 5)) + list(range(12, 15)))
    assert sorted(got.tolist()) == want


def test_generator_matches_vectorized():
    for kw in (dict(), dict(within_shard_shuffle=2),
               dict(within_shard_shuffle=False)):
        gen = list(expand_shard_indices([2, 0, 3], _SIZES, seed=3, epoch=1, **kw))
        vec = expand_shard_indices_np([2, 0, 3], _SIZES, seed=3, epoch=1, **kw)
        assert gen == vec.tolist()


def test_bounded_mode_displacement_strictly_bounded():
    b = 16
    order = shard_sample_order(0, 1000, seed=7, epoch=2,
                               within_shard_shuffle=b)
    disp = np.abs(order - np.arange(1000))
    assert disp.max() < b
    assert disp.max() > 0  # actually shuffles


def test_sequential_modes():
    for flag in (False, 0, 1):
        got = shard_sample_order(4, 9, seed=1, epoch=0,
                                 within_shard_shuffle=flag)
        assert got.tolist() == list(range(9))


def test_empty_shards_skipped():
    got = expand_shard_indices_np([1, 1], _SIZES, seed=0, epoch=0)
    assert got.tolist() == []


def test_epoch_changes_shard_orders():
    a = expand_shard_indices_np([2], _SIZES, seed=3, epoch=0)
    b = expand_shard_indices_np([2], _SIZES, seed=3, epoch=1)
    assert a.tolist() != b.tolist()
    assert sorted(a.tolist()) == sorted(b.tolist())


def test_shards_have_independent_orders():
    """Equal-sized shards must not share a permutation (the per-shard seed
    exists exactly for this)."""
    a = shard_sample_order(0, 64, seed=3, epoch=0)
    b = shard_sample_order(1, 64, seed=3, epoch=0)
    assert a.tolist() != b.tolist()


# ------------------------------------------------------------ shuffle buffer

def test_shuffle_buffer_is_permutation_and_bounded():
    n, B = 500, 32
    out = list(shuffle_buffer(range(n), B, seed=1, epoch=2))
    assert sorted(out) == list(range(n))
    # the hard bound: when output position k is emitted, upstream has been
    # read only to position k + B - 1, so out[k] - k <= B - 1 (an item can
    # be pulled at most B-1 ahead of schedule); lateness (out[k] < k) is
    # geometric-tailed, not bounded
    ahead = np.asarray(out) - np.arange(n)
    assert ahead.max() <= B - 1
    assert np.abs(ahead).max() > 0


def test_shuffle_buffer_deterministic_and_epoch_varying():
    a = list(shuffle_buffer(range(100), 8, seed=4, epoch=0))
    b = list(shuffle_buffer(range(100), 8, seed=4, epoch=0))
    c = list(shuffle_buffer(range(100), 8, seed=4, epoch=1))
    assert a == b
    assert a != c


def test_shuffle_buffer_size_one_is_identity():
    assert list(shuffle_buffer(range(20), 1, seed=0, epoch=0)) == list(range(20))


def test_shuffle_buffer_rejects_bad_size():
    with pytest.raises(ValueError, match="buffer_size"):
        list(shuffle_buffer(range(5), 0))


# ----------------------------------------------------- end-to-end shard mode

def test_shard_sampler_to_samples_pipeline():
    """The [B] config-4 shape: shard sampler per rank -> expansion -> global
    sample indices; ranks' shard sets are disjoint and cover."""
    num_shards, world = 37, 4
    sizes = [(3 + 7 * s) % 11 + 1 for s in range(num_shards)]
    all_shards = []
    for r in range(world):
        s = PartialShuffleShardSampler(
            num_shards, num_replicas=world, rank=r, window=8, seed=5,
            backend="cpu",
        )
        s.set_epoch(2)
        shards = list(s)
        all_shards += shards
        samples = expand_shard_indices_np(shards, sizes, seed=5, epoch=2)
        assert len(samples) == sum(sizes[i] for i in shards)
    # disjoint cover with wrap-pad duplicates (SURVEY.md §4 invariant 1)
    base = list(range(num_shards))
    pool = sorted(all_shards)
    for v in base:
        pool.remove(v)
    assert len(pool) == -(-num_shards // world) * world - num_shards


def test_batched_expansion_matches_per_shard_loop():
    # the size-class batching must be bit-identical to the per-shard
    # evaluation for every shuffle mode, mixed sizes, any id order
    rng = np.random.default_rng(7)
    sizes = rng.integers(0, 90, size=200).tolist()
    ids = rng.permutation(200)[:120].tolist()
    for wss in (True, False, 7):
        got = expand_shard_indices_np(
            ids, sizes, seed=11, epoch=3, within_shard_shuffle=wss
        )
        ref_parts = [
            int(np.concatenate([[0], np.cumsum(sizes)[:-1]])[s])
            + shard_sample_order(s, sizes[s], seed=11, epoch=3,
                                 within_shard_shuffle=wss)
            for s in ids if sizes[s]
        ]
        ref = (np.concatenate(ref_parts) if ref_parts
               else np.empty(0, np.int64))
        np.testing.assert_array_equal(got, ref)
        # generator path streams the same values in the same order
        assert list(expand_shard_indices(
            ids, sizes, seed=11, epoch=3, within_shard_shuffle=wss
        )) == got.tolist()


def test_batched_expansion_wide_seed():
    # the vectorized key fold must match fold_seed(shard_seed(...)) for
    # seeds wider than 64 bits too (fold commutes with the XOR)
    wide = (1 << 77) + 12345
    got = expand_shard_indices_np([3, 1], [8, 8, 8, 8], seed=wide, epoch=2)
    ref = np.concatenate([
        24 + shard_sample_order(3, 8, seed=wide, epoch=2),
        8 + shard_sample_order(1, 8, seed=wide, epoch=2),
    ])
    np.testing.assert_array_equal(got, ref)


def test_device_expansion_matches_host():
    # expand_shard_indices_jax runs the identical uint32 program on the
    # device: bit-identical to the host expansion for every shuffle mode,
    # uniform and mixed sizes, and reusable across epochs (epoch traced)
    from partiallyshuffledistributedsampler_tpu.sampler import (
        expand_shard_indices_jax,
    )

    rng = np.random.default_rng(3)
    uniform = [40] * 60
    mixed = rng.integers(0, 50, size=60).tolist()
    ids = rng.permutation(60)[:45].tolist()
    # True then 1 in sequence: True == 1 hash-collides, so a single-field
    # program cache would serve the full-shuffle executable for window=1;
    # np.int64(9) must mean window 9 (not bool-coerce to a full shuffle)
    for sizes in (uniform, mixed):
        for wss in (True, 1, False, 9, np.int64(9)):
            for ep in (0, 5):
                host = expand_shard_indices_np(
                    ids, sizes, seed=4, epoch=ep, within_shard_shuffle=wss
                )
                dev = np.asarray(expand_shard_indices_jax(
                    ids, sizes, seed=4, epoch=ep, within_shard_shuffle=wss
                ))
                np.testing.assert_array_equal(dev, host)
    # reseeds reuse the executable (seed is traced): different seed, same
    # program cache entry, still bit-identical
    host = expand_shard_indices_np(ids, uniform, seed=99, epoch=1)
    dev = np.asarray(expand_shard_indices_jax(ids, uniform, seed=99, epoch=1))
    np.testing.assert_array_equal(dev, host)


def test_shard_sampler_device_epoch_indices():
    # the one-call JAX-native shard-mode epoch: sampler shard stream +
    # device expansion, equal to composing the pieces by hand, with no
    # consumption-tracking side effects
    s = PartialShuffleShardSampler(64, num_replicas=4, rank=2, seed=6,
                                   backend="cpu")
    s.set_epoch(3)
    sizes = [25] * 64
    dev = np.asarray(s.device_epoch_indices(sizes, within_shard_shuffle=5))
    assert s.state_dict()["offset"] == 0  # the device call consumed nothing
    ref = expand_shard_indices_np(list(s), sizes, seed=6, epoch=3,
                                  within_shard_shuffle=5)
    np.testing.assert_array_equal(dev, ref)


def test_device_epoch_indices_preserves_xla_prefetch():
    # reading the epoch for device expansion must not steal the xla
    # backend's set_epoch prefetch from the upcoming training __iter__
    s = PartialShuffleShardSampler(64, num_replicas=4, rank=1, seed=6,
                                   backend="xla")
    s.set_epoch(2)
    assert s._pending is not None
    s.device_epoch_indices([10] * 64)
    assert s._pending is not None and s._pending_epoch == 2
    list(s)  # the training pass still gets the prefetched buffer
    assert s._pending is None


def test_shard_sampler_elastic_reshard():
    # shard-mode inherits the §6 elastic law: resharding a shard sampler's
    # checkpoint serves exactly the un-consumed shard stream
    old_world, new_world, num_shards = 3, 5, 97
    olds = [
        PartialShuffleShardSampler(num_shards, num_replicas=old_world,
                                   rank=r, seed=8, backend="cpu")
        for r in range(old_world)
    ]
    consumed, consumed_ids = 7, []
    for s in olds:
        s.set_epoch(4)
        it = iter(s)
        consumed_ids += [next(it) for _ in range(consumed)]
        it.close()
    state = olds[0].state_dict()
    remainder_ids = []
    for r in range(new_world):
        es = PartialShuffleShardSampler.reshard_from_state_dict(
            state, num_replicas=new_world, rank=r, backend="cpu"
        )
        remainder_ids += list(es)
    from partiallyshuffledistributedsampler_tpu.ops import cpu as _cpu

    stream = _cpu.full_epoch_stream_np(num_shards, 64, 8, 4,
                                       world=old_world)
    from conftest import assert_exactly_once

    assert_exactly_once(consumed_ids, remainder_ids, stream, old_world,
                        consumed, "strided", new_world)


# ------------------------------------------------ round-5 bucketed device path
def test_bucketed_device_expansion_bit_identical():
    """A variable-length corpus (hundreds of DISTINCT shard sizes, well
    past _MAX_CLASS_PROGRAMS) must expand on device through the
    power-of-two bucketed programs — bit-identical to the host expansion
    across every shuffle mode, including zero-size and size-1 shards."""
    from partiallyshuffledistributedsampler_tpu.sampler.shard_mode import (
        _MAX_CLASS_PROGRAMS, expand_shard_indices_jax,
    )

    rng = np.random.default_rng(7)
    sizes = np.concatenate([
        rng.integers(1, 400, 300), [0, 0, 1, 1, 2],
        rng.integers(200, 2000, 200),
    ])
    sid_stream = rng.permutation(len(sizes))[:400]
    assert len(set(int(s) for s in sizes[sid_stream])) > _MAX_CLASS_PROGRAMS
    for wss in (True, False, 0, 3, 64, 5000):
        a = expand_shard_indices_np(sid_stream, sizes, seed=5, epoch=2,
                                    within_shard_shuffle=wss)
        b = np.asarray(expand_shard_indices_jax(
            sid_stream, sizes, seed=5, epoch=2, within_shard_shuffle=wss))
        assert np.array_equal(a, b), wss


def test_bucketed_compile_count_is_bounded():
    """The bucketed path must compile O(log size-range) programs, not
    O(distinct sizes): two corpora with disjoint size sets but the same
    power-of-two buckets share every cached executable."""
    from partiallyshuffledistributedsampler_tpu.sampler.shard_mode import (
        _bucket_expand_jit, expand_shard_indices_jax,
    )

    rng = np.random.default_rng(1)
    sizes_a = rng.integers(100, 1000, 64) * 2      # even sizes
    sizes_b = rng.integers(100, 1000, 64) * 2 + 1  # odd sizes (disjoint)
    sid = np.arange(64)
    np.asarray(expand_shard_indices_jax(sid, sizes_a, seed=1, epoch=0))
    info = _bucket_expand_jit.cache_info()
    np.asarray(expand_shard_indices_jax(sid, sizes_b, seed=1, epoch=0))
    info2 = _bucket_expand_jit.cache_info()
    # same pow2 buckets -> zero NEW compiled programs for the second corpus
    assert info2.currsize == info.currsize
    assert info2.hits > info.hits
