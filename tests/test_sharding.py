"""Scale-out serving plane: shards behind the rank-space router.

The acceptance law (docs/SHARDING.md): sharding is a *deployment* choice,
never a semantics change — the per-rank streams served by an N-shard
plane are bit-identical to a single ``IndexServer``'s in every spec
mode, including across a shard failover and a cross-shard reshard
barrier, and the router is never on the data path (a direct-connected
client keeps streaming while the router is down).

Covered here: ``ShardMap`` derivation/lookup/wire laws; the 3-shard ×
plain/mixture/shard bit-identity matrix (folded and per-rank); the
``wrong_shard`` redirect without a router round-trip; kill-one-shard
with standby promotion (union law, zero dup/lost); the two-phase
cross-shard reshard barrier with rank migration between shards; a
router restart mid-epoch (direct clients unaffected, new clients
block-and-retry, the map version survives via the router's snapshot);
and tenant attach across shards.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    ServiceIndexClient,
)
from partiallyshuffledistributedsampler_tpu.service import protocol as P
from partiallyshuffledistributedsampler_tpu.sharding import (
    ShardMap,
    ShardPlane,
    ShardRouter,
)

from test_elastic_service import (
    MAX_UNIT,
    assert_union_law,
    build_spec,
    epoch_union_ref,
)
from test_failover import wait_for, wait_synced

pytestmark = pytest.mark.sharding


def _stream(addr, rank, spec=None, **kw):
    kw.setdefault("batch", 23)
    kw.setdefault("backoff_base", 0.01)
    with ServiceIndexClient(addr, rank=rank, spec=spec, **kw) as c:
        got = list(c.epoch_batches(0))
    return (np.concatenate(got) if got else np.empty(0, np.int64))


# ------------------------------------------------------------- ShardMap
def test_shardmap_canonical_partition_and_lookup():
    m = ShardMap.for_world(10, 3)
    assert m.slices == ((0, 3), (3, 6), (6, 10))
    assert [m.owner(r) for r in range(10)] == \
        [0, 0, 0, 1, 1, 1, 2, 2, 2, 2]
    assert m.owns(1, 4) and not m.owns(1, 6)
    with pytest.raises(ValueError):
        m.owner(10)
    # more shards than ranks: tail shards own empty slices, every rank
    # still has exactly one owner
    small = ShardMap.for_world(2, 4)
    assert {small.owner(0), small.owner(1)} <= set(range(4))
    assert sum(hi - lo for lo, hi in small.slices) == 2


def test_shardmap_rejects_non_contiguous_cover():
    with pytest.raises(ValueError):
        ShardMap(6, [(0, 2), (3, 6)])      # gap
    with pytest.raises(ValueError):
        ShardMap(6, [(0, 4), (2, 6)])      # overlap
    with pytest.raises(ValueError):
        ShardMap(6, [(0, 2), (2, 4)])      # short cover


def test_shardmap_wire_roundtrip_and_versioning():
    m = ShardMap.for_world(7, 3)
    m.set_addr(1, ("127.0.0.1", 4242))
    m2 = ShardMap.from_wire(m.to_wire())
    assert m2 == m and m2.fingerprint() == m.fingerprint()
    reb = m.rebalanced(5)
    assert reb.version == m.version + 1
    assert reb.world == 5 and reb.n_shards == m.n_shards
    assert reb.addr(1) == ("127.0.0.1", 4242)
    assert reb.fingerprint() != m.fingerprint()


# ---------------------------------------------- 3-shard bit-identity matrix
@pytest.mark.parametrize("mode", ["plain", "mixture", "shard"])
def test_three_shard_streams_bit_identical_to_single_server(mode):
    """Every rank dials the ROUTER, is redirected to its shard, and
    streams exactly what a single ``IndexServer`` serves it — per rank
    and folded — in all three spec modes."""
    spec = build_spec(mode, 6)
    with IndexServer(spec) as srv:
        ref = {r: _stream(srv.address, r) for r in range(6)}
    with ShardPlane(spec, 3) as plane:
        got = {}
        for r in range(6):
            with ServiceIndexClient(plane.address, rank=r, batch=23,
                                    backoff_base=0.01) as c:
                arrs = list(c.epoch_batches(0))
                got[r] = np.concatenate(arrs)
                # the client ended up direct-connected to its shard, map
                # in hand — never streaming through the router
                assert c.shard_map is not None
                assert c.address != plane.router.address
    for r in range(6):
        assert np.array_equal(got[r], ref[r]), (
            f"rank {r} diverged from the single-server stream ({mode})")
    folded = np.concatenate([got[r] for r in range(6)])
    assert np.array_equal(folded, epoch_union_ref(spec)), (
        f"folded 3-shard stream diverged ({mode})")


def test_wrong_shard_redirect_without_router():
    """A client pointed at the WRONG shard is redirected by the typed
    ``wrong_shard`` refusal alone — the attached map re-routes it with
    no router round-trip, and the stream is exact."""
    spec = build_spec("plain", 6)
    with ShardPlane(spec, 3) as plane:
        wrong = plane.shards[0].address     # shard 0 does not own rank 5
        with ServiceIndexClient(wrong, rank=5, batch=23,
                                backoff_base=0.01) as c:
            got = np.concatenate(list(c.epoch_batches(0)))
            counters = c.metrics.report()["counters"]
        assert counters.get("wrong_shard_redirects", 0) >= 1
        srv_counters = plane.shards[0].metrics.report()["counters"]
        assert srv_counters.get("wrong_shard_hellos", 0) >= 1
    assert np.array_equal(got, np.asarray(spec.rank_indices(0, 5)))


# ----------------------------------------------------- kill-one-shard drill
def test_kill_one_shard_standby_promotes_union_law():
    """One shard's primary is hard-killed mid-epoch: its ranks finish on
    the promoted standby, the other shards never notice, and the folded
    stream is bit-identical (zero duplicated or lost samples)."""
    spec = build_spec("plain", 6)
    delivered = {}
    lock = threading.Lock()
    b_streamed = threading.Barrier(7)
    b_killed = threading.Barrier(7)
    with ShardPlane(spec, 3, standby=True) as plane:
        victim = plane.shards[1]            # owns ranks [2, 4)
        victim_sb = plane.standbys[1]

        def worker(r):
            got = []
            c = ServiceIndexClient(plane.address, rank=r, batch=23,
                                   backoff_base=0.01,
                                   reconnect_timeout=10.0)
            try:
                it = c.epoch_batches(0)
                got.append(next(it))
                b_streamed.wait(timeout=30.0)
                b_killed.wait(timeout=30.0)
                for arr in it:
                    got.append(arr)
            finally:
                with lock:
                    delivered[r] = (got, c.metrics.report()["counters"])
                c.close()

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(6)]
        for t in threads:
            t.start()
        b_streamed.wait(timeout=30.0)
        wait_synced(victim, victim_sb)
        victim.kill()
        b_killed.wait(timeout=30.0)
        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "sharded failover worker hung"
        assert victim_sb.role == "primary", "shard standby never promoted"
    for r in range(6):
        got, counters = delivered[r]
        assert np.array_equal(np.concatenate(got),
                              np.asarray(spec.rank_indices(0, r))), (
            f"rank {r} stream diverged across the shard failover")
        assert counters.get("degraded_mode", 0) == 0
        if r in (2, 3):
            assert counters.get("failovers", 0) >= 1
        else:
            # sibling shards never noticed
            assert counters.get("failovers", 0) == 0


# ------------------------------------------- cross-shard reshard barrier
def test_cross_shard_reshard_barrier_union_law():
    """World 6 -> 4 across three shards mid-epoch, through the router's
    two-phase barrier: every shard freezes, drains to ONE global unit
    barrier and commits with the v2 map — the union of pre-barrier and
    post-barrier deliveries obeys the exactly-once law, and a rank whose
    owner changed re-routes via ``wrong_shard`` and keeps streaming."""
    spec = build_spec("plain", 6)
    ref = epoch_union_ref(spec)
    delivered = {}
    lock = threading.Lock()
    b_hit = threading.Barrier(7)
    b_go = threading.Barrier(7)
    with ShardPlane(spec, 3) as plane:

        def worker(r):
            got = []
            c = ServiceIndexClient(plane.address, rank=r, batch=23,
                                   backoff_base=0.01,
                                   reconnect_timeout=20.0)
            try:
                it = c.epoch_batches(0)
                for _ in range(1 + r):
                    try:
                        got.append(next(it))
                    except StopIteration:
                        break
                b_hit.wait(timeout=30.0)
                b_go.wait(timeout=30.0)
                for arr in it:
                    got.append(arr)
            finally:
                with lock:
                    delivered[r] = (got, c.metrics.report()["counters"])
                c.close()

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(6)]
        for t in threads:
            t.start()
        b_hit.wait(timeout=30.0)
        barrier_err = []

        def run_barrier():
            try:
                plane.router.reshard(4)
            except Exception as exc:  # surfaced to the main thread below
                barrier_err.append(exc)

        barrier_thread = threading.Thread(target=run_barrier)
        barrier_thread.start()
        # release the workers only once every shard is actually frozen,
        # so the barrier genuinely lands MID-epoch (not after it)
        wait_for(lambda: all(s._reshard is not None for s in plane.shards),
                 timeout=10.0)
        b_go.wait(timeout=30.0)
        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "cross-shard reshard worker hung"
        barrier_thread.join(timeout=60.0)
        assert not barrier_thread.is_alive(), "router barrier hung"
        assert not barrier_err, f"router barrier failed: {barrier_err!r}"
        # every shard committed the same cascade and adopted the v2 map
        for srv in plane.shards:
            assert srv.spec.world == 4
            assert srv.generation == 1
            assert srv.shard_map.version == 2
        layers = {tuple(map(tuple, srv._state_dict()["layers"]))
                  for srv in plane.shards}
        assert len(layers) == 1, (
            f"shards committed diverging cascades: {layers}")
    union = np.concatenate(
        [np.concatenate(v) if v else np.empty(0, np.int64)
         for v, _ in delivered.values()])
    assert_union_law(union, ref, new_world=4, max_unit=MAX_UNIT["plain"])
    # rank 3's owner moved (shard 1 [2,4) -> shard 2 [3,4)): it must
    # have ridden a wrong_shard redirect, not ended early
    assert delivered[3][1].get("wrong_shard_redirects", 0) >= 1


# ------------------------------------------------- router restart drill
def test_router_restart_mid_epoch():
    """The router is a control-plane-only process: killing it mid-epoch
    leaves every direct-connected client streaming; a NEW client blocks
    and retries until the router returns on the same port; the restarted
    router recovers the CURRENT map version from its own snapshot."""
    spec = build_spec("plain", 6)
    with _plane_with_snapshots(spec) as (plane, snap):
        # bump the map version first so the snapshot carries v2; streams
        # now follow the committed post-reshard cascade at world 4
        plane.router.reshard(4)
        assert plane.router._map.version == 2
        layers = plane.shards[0]._state_dict()["layers"]
        new_spec = spec.with_world(4)
        router_addr = plane.router.address
        with ServiceIndexClient(plane.address, rank=0, batch=23,
                                backoff_base=0.01) as c:
            it = c.epoch_batches(0)
            first = next(it)
            plane.router.stop()             # snapshot written on the way out
            rest = list(it)                 # direct-connected: unaffected
            got = np.concatenate([first] + rest)
        assert np.array_equal(
            got, np.asarray(new_spec.rank_indices(0, 0, layers=layers)))

        # a new client dialing the dead router blocks and retries...
        late = {}

        def late_client():
            # lazy connect: the first request rides the retry layer, so
            # the dead router reads as "keep knocking", not a hard fail
            c = ServiceIndexClient(router_addr, rank=1, batch=23,
                                   backoff_base=0.05,
                                   reconnect_timeout=20.0)
            try:
                late["got"] = np.concatenate(list(c.epoch_batches(0)))
            finally:
                c.close()

        t = threading.Thread(target=late_client)
        t.start()
        time.sleep(0.3)
        assert t.is_alive(), "new client gave up instead of retrying"
        # ...until the router returns on the same port, from a STALE
        # constructor map — the snapshot must restore v2
        stale = ShardMap.for_world(6, 3)
        router2 = ShardRouter(spec, stale, "127.0.0.1", router_addr[1],
                              snapshot_path=snap)
        try:
            router2.start()
            assert router2._map.version == 2, (
                "map version lost across the router restart")
            assert router2._map.world == 4
            t.join(timeout=30.0)
            assert not t.is_alive(), "late client never completed"
        finally:
            router2.stop()
    assert np.array_equal(
        late["got"], np.asarray(new_spec.rank_indices(0, 1, layers=layers)))


class _plane_with_snapshots:
    """A started 3-shard plane with a tmp snapshot dir, yielding
    ``(plane, router_snapshot_path)``."""

    def __init__(self, spec):
        import tempfile
        self._tmp = tempfile.TemporaryDirectory(prefix="psds-sharding-")
        self.plane = ShardPlane(spec, 3, snapshot_dir=self._tmp.name)

    def __enter__(self):
        self.plane.start()
        import os
        return self.plane, os.path.join(self._tmp.name, "router.json")

    def __exit__(self, *exc):
        self.plane.stop()
        self._tmp.cleanup()


# ---------------------------------------------------------------- tenancy
def test_attach_tenant_across_shards():
    """``attach_tenant`` admits a namespace on every owning shard without
    claiming any rank lease; tenant clients then stream bit-identically
    through the plane."""
    spec = build_spec("plain", 6)
    other = build_spec("shard", 6)
    with ShardPlane(spec, 3, multi_tenant=True) as plane:
        attached = plane.router.attach_tenant(other)
        assert attached == [0, 1, 2]
        for r in (0, 5):
            got = _stream(plane.address, r, spec=other,
                          reconnect_timeout=10.0)
            assert np.array_equal(
                got, np.asarray(other.rank_indices(0, r))), (
                f"tenant rank {r} diverged through the sharded plane")
