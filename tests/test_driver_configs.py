"""Every BASELINE.json driver config, exercised at its REAL index-space
scale via the random-access primitive (stream_indices_at) — spot-checking a
1B/10B config costs O(probe), not O(n/world).

Configs ([B]):
  1. CIFAR-10 torchvision DDP, window=512, 2 ranks (CPU reference)
  2. ImageNet-1k ResNet-50 DDP, window=8192, 8 chips
  3. C4 tokenized shards (1B samples), GPT-2-small, v5e-64
  4. WebDataset tar shards, partial-shuffle over shard indices
  5. Llama-3 8B pretrain, 10B-sample index space, v5p-256
"""

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops import core, cpu
from partiallyshuffledistributedsampler_tpu.ops.cpu import stream_indices_at_np

CONFIGS = {
    "cifar10": dict(n=50_000, window=512, world=2),
    "imagenet": dict(n=1_281_167, window=8192, world=8),
    "c4_1b": dict(n=1_000_000_000, window=8192, world=64),
    "webdataset_shards": dict(n=100_000, window=64, world=8),  # shard ids
    "llama_10b": dict(n=10_000_000_000, window=8192, world=256),
}


@pytest.mark.parametrize("name", list(CONFIGS))
def test_random_access_matches_full_generation(name):
    cfg = CONFIGS[name]
    n, w, world = cfg["n"], cfg["window"], cfg["world"]
    seed, epoch, rank = 42, 3, world - 1
    k = 512  # probe the first k entries of the rank's stream
    positions = rank + world * np.arange(k, dtype=np.uint64)
    spot = stream_indices_at_np(positions, n, w, seed, epoch)
    if n * 1.0 / world <= 1e7:  # full generation affordable: compare directly
        full = cpu.epoch_indices_np(n, w, seed, epoch, rank, world)
        np.testing.assert_array_equal(spot, full[:k])
    # in all cases: valid range, right dtype, deterministic
    assert (spot >= 0).all() and (spot < n).all()
    assert spot.dtype == (np.int32 if n <= 0x7FFFFFFF else np.int64)
    np.testing.assert_array_equal(
        spot, stream_indices_at_np(positions, n, w, seed, epoch)
    )


@pytest.mark.parametrize("name", ["cifar10", "imagenet", "webdataset_shards"])
def test_windowing_law_at_scale(name):
    """The window-block law checked *in place* at each config's real n:
    probe one full output slot; its contents must be exactly one source
    window's index set."""
    cfg = CONFIGS[name]
    n, w = cfg["n"], cfg["window"]
    slot = 3  # an arbitrary full output slot
    positions = slot * w + np.arange(w, dtype=np.uint64)
    got = np.sort(stream_indices_at_np(positions, n, w, 7, 1))
    k = got[0] // w
    np.testing.assert_array_equal(got, np.arange(k * w, (k + 1) * w))


def test_random_access_billion_scale_properties():
    # 1B config: probe two epochs at scattered positions; disjoint epochs
    # must decorrelate, same epoch must agree with the strided shard law
    cfg = CONFIGS["c4_1b"]
    n, w, world = cfg["n"], cfg["window"], cfg["world"]
    rng = np.random.default_rng(0)
    positions = rng.integers(0, n, size=4096).astype(np.uint64)
    a = stream_indices_at_np(positions, n, w, 5, 0)
    b = stream_indices_at_np(positions, n, w, 5, 1)
    assert (a != b).mean() > 0.5
    # bijectivity smoke: distinct positions within one window stay distinct
    wpos = 123 * w + np.arange(min(w, 4096), dtype=np.uint64)
    out = stream_indices_at_np(wpos, n, w, 5, 0)
    assert len(np.unique(out)) == len(wpos)


def test_random_access_jax_parity():
    from partiallyshuffledistributedsampler_tpu.ops.xla import (
        stream_indices_at_jax,
    )

    n, w = 1_000_000, 512
    positions = np.arange(0, 10_000, 7, dtype=np.uint32)
    ref = stream_indices_at_np(positions, n, w, 9, 4)
    got = np.asarray(stream_indices_at_jax(positions, n, w, 9, 4))
    np.testing.assert_array_equal(got, ref)


def test_resume_equals_random_access():
    # mid-epoch resume law: epoch_indices[k:] == stream at positions k..
    n, w, world, rank = 10_000, 256, 4, 2
    full = cpu.epoch_indices_np(n, w, 1, 2, rank, world)
    k = 1000
    num_samples, _ = core.shard_sizes(n, world, False)
    positions = rank + world * np.arange(k, num_samples, dtype=np.uint64)
    np.testing.assert_array_equal(
        full[k:], stream_indices_at_np(positions, n, w, 1, 2)
    )


def test_negative_seed_parity_across_backends():
    from partiallyshuffledistributedsampler_tpu.ops.xla import epoch_indices_jax

    ref = cpu.epoch_indices_np(1000, 64, -12345, 0, 0, 2)
    got = np.asarray(epoch_indices_jax(1000, 64, -12345, 0, 0, 2))
    np.testing.assert_array_equal(got, ref)
