"""Elastic resharding (SPEC.md §6) + stateful-sampler behavior.

The elastic law's tested invariant: for any (old_world, new_world) pair —
including non-divisible ones — the old run's consumed prefix plus the union
of the new ranks' remainder streams covers the epoch's total_size stream
positions exactly once, modulo the spec'd wrap-padding duplicates.

Also covers the round-2 stateful fixes: automatic consumption tracking
(state_dict() with no args mid-epoch), config validation on load, and the
offset-aware __len__ (ADVICE round 1).
"""

import itertools

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu import PartiallyShuffleDistributedSampler
from partiallyshuffledistributedsampler_tpu.ops import core, cpu


def _epoch_stream(n, window, seed, epoch, world, drop_last=False, partition="strided"):
    """Full global epoch stream [0, total_size) as index values (numpy ref)."""
    num_samples, total = core.shard_sizes(n, world, drop_last)
    pos = np.arange(total, dtype=np.uint64) % np.uint64(n)
    return np.asarray(
        core.stream_indices_at_generic(np, pos, n, window, seed, epoch)
    )


@pytest.mark.parametrize("old_world,new_world", [(4, 3), (3, 5), (8, 2), (5, 7), (2, 2)])
@pytest.mark.parametrize("partition", ["strided", "blocked"])
def test_elastic_exactly_once(old_world, new_world, partition):
    n, window, seed, epoch = 1003, 64, 17, 3
    consumed = 37  # per old rank, mid-epoch

    old = [
        PartiallyShuffleDistributedSampler(
            n, num_replicas=old_world, rank=r, window=window, seed=seed,
            partition=partition, backend="cpu",
        )
        for r in range(old_world)
    ]
    for s in old:
        s.set_epoch(epoch)
    consumed_vals = []
    for s in old:
        it = iter(s)
        consumed_vals += [next(it) for _ in range(consumed)]
        it.close()
    state = old[0].state_dict()  # auto-tracked: consumed==37
    assert state["offset"] == consumed

    new = [
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state, num_replicas=new_world, rank=r, backend="cpu"
        )
        for r in range(new_world)
    ]
    remainder_vals = []
    for s in new:
        got = list(s)
        assert len(got) == len(s) == s._effective_num_samples
        remainder_vals += got

    # exactly-once: consumed + remainder == full epoch stream + wrap-pad
    # extras drawn only from the unconsumed portion (shared Counter-based
    # assertion — tests/test_hypothesis_properties.py)
    from conftest import assert_exactly_once

    stream = _epoch_stream(n, window, seed, epoch, old_world)
    assert_exactly_once(consumed_vals, remainder_vals, stream, old_world,
                        consumed, partition, new_world)


def test_elastic_epoch_zero_consumed():
    """Resharding at an epoch boundary (consumed=0) = plain new-world epoch."""
    n, window, seed = 200, 16, 5
    s_old = PartiallyShuffleDistributedSampler(
        n, num_replicas=4, rank=0, window=window, seed=seed, backend="cpu"
    )
    s_old.set_epoch(2)
    state = s_old.state_dict()
    got = list(
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state, num_replicas=2, rank=1, backend="cpu"
        )
    )
    # consumed=0 remainder stream == the padded epoch stream re-partitioned,
    # which for strided is exactly the ordinary new-world epoch *when the old
    # padding is world-divisible by the new world*; here total(4)=200=total(2)
    want = cpu.epoch_indices_np(n, window, seed, 2, 1, 2).tolist()
    assert got == want


def test_elastic_fully_consumed_yields_empty():
    s_old = PartiallyShuffleDistributedSampler(
        100, num_replicas=4, rank=0, window=16, backend="cpu"
    )
    s_old.set_epoch(1)
    state = s_old.state_dict(consumed=s_old.num_samples)
    s_new = PartiallyShuffleDistributedSampler.reshard_from_state_dict(
        state, num_replicas=3, rank=0, backend="cpu"
    )
    assert len(s_new) == 0 and list(s_new) == []


@pytest.mark.parametrize("old_world,new_world", [(4, 7), (3, 5)])
def test_elastic_drop_last_no_duplicates(old_world, new_world):
    """drop_last's at-most-once promise survives resharding: the remainder
    tail is dropped instead of wrap-padded (SPEC.md §6)."""
    n, window, seed, epoch, consumed = 1003, 64, 2, 1, 13
    old = [
        PartiallyShuffleDistributedSampler(
            n, num_replicas=old_world, rank=r, window=window, seed=seed,
            drop_last=True, backend="cpu",
        )
        for r in range(old_world)
    ]
    consumed_vals = []
    for s in old:
        s.set_epoch(epoch)
        it = iter(s)
        consumed_vals += [next(it) for _ in range(consumed)]
        it.close()
    state = old[0].state_dict(consumed=consumed)
    remainder_vals = []
    for r in range(new_world):
        remainder_vals += list(
            PartiallyShuffleDistributedSampler.reshard_from_state_dict(
                state, num_replicas=new_world, rank=r, backend="cpu"
            )
        )
    combined = consumed_vals + remainder_vals
    assert len(combined) == len(set(combined))  # at most once — no wrap-pad
    old_ns = n // old_world
    R = (old_ns - consumed) * old_world
    assert len(remainder_vals) == (R // new_world) * new_world  # tail dropped


def test_elastic_epoch_indices_other_epoch_is_ordinary():
    """epoch_indices(E') for E' != the resumed epoch must return the
    ordinary full epoch, not remainder-shaped indices."""
    s_old = PartiallyShuffleDistributedSampler(
        500, num_replicas=2, rank=0, window=32, seed=9, backend="cpu"
    )
    s_old.set_epoch(3)
    state = s_old.state_dict(consumed=100)
    s = PartiallyShuffleDistributedSampler.reshard_from_state_dict(
        state, num_replicas=5, rank=3, backend="cpu"
    )
    nxt = s.epoch_indices(4)
    np.testing.assert_array_equal(nxt, cpu.epoch_indices_np(500, 32, 9, 4, 3, 5))
    # and the resumed epoch itself still serves the remainder
    assert len(s.epoch_indices()) == s._effective_num_samples


def test_load_state_dict_failure_leaves_sampler_untouched():
    s = PartiallyShuffleDistributedSampler(
        100, num_replicas=2, rank=0, window=16, seed=5, backend="cpu"
    )
    s.set_epoch(2)
    before = list(s)
    with pytest.raises(ValueError, match="offset"):
        s.load_state_dict(
            {"spec_version": 1, "seed": 9, "epoch": 7, "offset": 10_000}
        )
    assert s.seed == 5 and s.epoch == 2 and s._elastic is None
    assert list(s) == before


def test_elastic_next_epoch_is_ordinary():
    """set_epoch to a different epoch ends the remainder regime."""
    s_old = PartiallyShuffleDistributedSampler(
        500, num_replicas=2, rank=0, window=32, seed=9, backend="cpu"
    )
    s_old.set_epoch(0)
    state = s_old.state_dict(consumed=100)
    s = PartiallyShuffleDistributedSampler.reshard_from_state_dict(
        state, num_replicas=5, rank=3, backend="cpu"
    )
    list(s)  # drain the remainder epoch
    s.set_epoch(1)
    assert s._elastic is None
    assert list(s) == cpu.epoch_indices_np(500, 32, 9, 1, 3, 5).tolist()
    assert len(s) == s.num_samples


def test_elastic_xla_matches_cpu():
    state = {
        "spec_version": 1, "seed": 3, "epoch": 2, "offset": 11,
        "n": 777, "num_replicas": 3, "window": 32, "rounds": 24,
        "order_windows": True, "partition": "strided", "shuffle": True,
        "drop_last": False,
    }
    got_cpu = list(
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state, num_replicas=2, rank=1, backend="cpu"
        )
    )
    got_xla = list(
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state, num_replicas=2, rank=1, backend="xla"
        )
    )
    assert got_cpu == got_xla


def test_elastic_state_roundtrip_mid_remainder():
    """A checkpoint taken mid-remainder-epoch resumes exactly (same world)."""
    s_old = PartiallyShuffleDistributedSampler(
        400, num_replicas=4, rank=0, window=16, seed=1, backend="cpu"
    )
    s_old.set_epoch(5)
    state = s_old.state_dict(consumed=20)
    s = PartiallyShuffleDistributedSampler.reshard_from_state_dict(
        state, num_replicas=3, rank=2, backend="cpu"
    )
    it = iter(s)
    first = [next(it) for _ in range(7)]
    mid_state = s.state_dict()
    assert mid_state["elastic"] == {"layers": [[4, 20]]}
    it.close()

    s2 = PartiallyShuffleDistributedSampler(
        400, num_replicas=3, rank=2, window=16, seed=1, backend="cpu"
    )
    s2.load_state_dict(mid_state)
    rest = list(s2)
    full = list(
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state, num_replicas=3, rank=2, backend="cpu"
        )
    )
    assert first + rest == full


def test_set_epoch_resets_consumed_counter():
    """Checkpoint between set_epoch(E+1) and the first batch must record
    offset 0 for the new epoch, not the previous epoch's full count (which
    would silently skip the whole epoch on resume)."""
    s = PartiallyShuffleDistributedSampler(
        100, num_replicas=2, rank=0, window=16, backend="cpu"
    )
    s.set_epoch(0)
    assert len(list(s)) == 50  # epoch 0 fully consumed
    s.set_epoch(1)
    state = s.state_dict()
    assert state == {**state, "epoch": 1, "offset": 0}
    s2 = PartiallyShuffleDistributedSampler(
        100, num_replicas=2, rank=0, window=16, backend="cpu"
    )
    s2.load_state_dict(state)
    assert len(list(s2)) == 50  # nothing skipped


def test_set_epoch_same_epoch_keeps_resume_offset():
    """load_state_dict then set_epoch(state['epoch']) — the canonical resume
    loop — must not wipe the mid-epoch offset."""
    s = PartiallyShuffleDistributedSampler(
        100, num_replicas=2, rank=0, window=16, backend="cpu"
    )
    s.load_state_dict({"spec_version": 1, "seed": 0, "epoch": 3, "offset": 20})
    s.set_epoch(3)
    assert len(list(s)) == 30


def test_load_state_dict_discards_stale_xla_prefetch():
    """A load that changes (seed, epoch) must not serve the previously
    prefetched device buffer — that would be a silent reshuffle."""
    s = PartiallyShuffleDistributedSampler(
        500, num_replicas=2, rank=0, window=32, seed=0, backend="xla"
    )
    s.set_epoch(3)  # dispatches the seed-0 epoch-3 regen into _pending
    s.load_state_dict({"spec_version": 1, "seed": 7, "epoch": 3, "offset": 0})
    assert s._pending is None
    assert list(s) == cpu.epoch_indices_np(500, 32, 7, 3, 0, 2).tolist()


def test_prefetch_pattern_does_not_corrupt_consumed():
    """set_epoch(e+1) mid-epoch (the advertised async-prefetch pattern) must
    not let the still-running epoch-e generator inflate the new epoch's
    consumed counter."""
    s = PartiallyShuffleDistributedSampler(
        300, num_replicas=2, rank=0, window=16, backend="cpu"
    )
    s.set_epoch(0)
    it = iter(s)
    for _ in range(100):
        next(it)
    s.set_epoch(1)  # prefetch next epoch while epoch 0 finishes
    rest = list(it)
    assert len(rest) == 50  # epoch 0 drains fully
    state = s.state_dict()
    assert (state["epoch"], state["offset"]) == (1, 0)  # nothing skipped


def test_remaining_positions_rejects_fully_consumed():
    with pytest.raises(ValueError, match="fully consumed"):
        core.remaining_stream_positions(np, np.arange(3), 4, 25, 25, "blocked", np.uint64)


def test_reshard_rejects_other_spec_version():
    state = {
        "spec_version": 99, "seed": 0, "epoch": 0, "offset": 5, "n": 100,
        "num_replicas": 2,
    }
    with pytest.raises(ValueError, match="spec version"):
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state, num_replicas=3, rank=0, backend="cpu"
        )


def test_reshard_missing_field_is_informative():
    state = {"spec_version": 1, "offset": 5, "n": 100, "num_replicas": 2,
             "epoch": 0}
    with pytest.raises(ValueError, match="seed"):
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state, num_replicas=3, rank=0, backend="cpu"
        )


# ------------------------------------------------------- cascading reshards

def _drain(sampler, k):
    it = iter(sampler)
    vals = [next(it) for _ in range(k)]
    it.close()
    return vals


@pytest.mark.parametrize("worlds", [(4, 3, 5), (5, 2, 7), (3, 3, 3)])
@pytest.mark.parametrize("partition", ["strided", "blocked"])
def test_cascading_reshard_exactly_once(worlds, partition):
    """V -> W -> X with both reshards mid-epoch (SPEC.md §6.1): every layer's
    consumed prefix plus the innermost ranks' remainder streams covers the
    full epoch stream exactly once, modulo wrap-pad duplicates."""
    V, W, X = worlds
    n, window, seed, epoch = 911, 64, 23, 2
    c1, c2 = 29, 11  # per-rank consumption at layer 0 and layer 1

    old = [
        PartiallyShuffleDistributedSampler(
            n, num_replicas=V, rank=r, window=window, seed=seed,
            partition=partition, backend="cpu",
        )
        for r in range(V)
    ]
    consumed = []
    for s in old:
        s.set_epoch(epoch)
        consumed += _drain(s, c1)
    state1 = old[0].state_dict(consumed=c1)

    mid = [
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state1, num_replicas=W, rank=r, backend="cpu"
        )
        for r in range(W)
    ]
    for s in mid:
        assert s._effective_num_samples > c2  # c2 must be mid-remainder
        consumed += _drain(s, c2)
    state2 = mid[0].state_dict(consumed=c2)
    assert state2["elastic"] == {"layers": [[V, c1]]}

    new = [
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state2, num_replicas=X, rank=r, backend="cpu"
        )
        for r in range(X)
    ]
    remainder = []
    for s in new:
        assert s._elastic["chain"][0][0] == V
        assert s._elastic["chain"][1][0] == W
        got = list(s)
        assert len(got) == len(s) == s._effective_num_samples
        remainder += got

    # exactly-once over the base epoch stream, with the wrap-pad extras of
    # BOTH inner layers drawn from legal stream values
    stream = _epoch_stream(n, window, seed, epoch, V)
    combined = sorted(consumed + remainder)
    full = sorted(stream.tolist())
    extra = list(combined)
    for v in full:
        extra.remove(v)  # raises if any epoch position is missing
    stream_set = set(stream.tolist())
    assert all(v in stream_set for v in extra)
    # pad counts: layer-1 epoch padded R1 -> ns1*W, layer-2 padded R2 -> ns2*X
    ns0 = -(-n // V)
    R1 = (ns0 * V) - c1 * V
    ns1 = -(-R1 // W)
    R2 = (ns1 - c2) * W
    ns2 = -(-R2 // X)
    assert len(extra) == (ns1 * W - R1) + (ns2 * X - R2)


def test_cascading_reshard_xla_matches_cpu():
    state = {
        "spec_version": 1, "seed": 3, "epoch": 2, "offset": 9,
        "n": 777, "num_replicas": 3, "window": 32, "rounds": 24,
        "order_windows": True, "partition": "strided", "shuffle": True,
        "drop_last": False, "elastic": {"layers": [[5, 40]]},
    }
    for rank in range(2):
        got_cpu = list(
            PartiallyShuffleDistributedSampler.reshard_from_state_dict(
                state, num_replicas=2, rank=rank, backend="cpu"
            )
        )
        got_xla = list(
            PartiallyShuffleDistributedSampler.reshard_from_state_dict(
                state, num_replicas=2, rank=rank, backend="xla"
            )
        )
        assert got_cpu == got_xla


def test_cascading_reshard_checkpoint_roundtrip():
    """A mid-remainder checkpoint of a cascade resumes exactly."""
    state = {
        "spec_version": 1, "seed": 7, "epoch": 4, "offset": 13,
        "n": 500, "num_replicas": 4, "window": 16, "rounds": 24,
        "order_windows": True, "partition": "strided", "shuffle": True,
        "drop_last": False, "elastic": {"layers": [[6, 11]]},
    }
    s = PartiallyShuffleDistributedSampler.reshard_from_state_dict(
        state, num_replicas=2, rank=1, backend="cpu"
    )
    head = _drain(s, 8)
    mid = s.state_dict()
    assert mid["elastic"] == {"layers": [[6, 11], [4, 13]]}
    s2 = PartiallyShuffleDistributedSampler(
        500, num_replicas=2, rank=1, window=16, seed=7, backend="cpu"
    )
    s2.load_state_dict(mid)
    full = list(
        PartiallyShuffleDistributedSampler.reshard_from_state_dict(
            state, num_replicas=2, rank=1, backend="cpu"
        )
    )
    assert head + list(s2) == full


def test_legacy_single_reshard_state_format_accepted():
    """Round-2 checkpoints wrote elastic as {"old_world", "consumed"}; the
    cascade-aware loader must read them as a one-layer chain."""
    legacy = {
        "spec_version": 1, "seed": 1, "epoch": 0, "offset": 3, "n": 200,
        "num_replicas": 2, "window": 16, "rounds": 24, "order_windows": True,
        "partition": "strided", "shuffle": True, "drop_last": False,
        "elastic": {"old_world": 4, "consumed": 10},
    }
    modern = {**legacy, "elastic": {"layers": [[4, 10]]}}
    a = PartiallyShuffleDistributedSampler(
        200, num_replicas=2, rank=0, window=16, seed=1, backend="cpu"
    )
    a.load_state_dict(legacy)
    b = PartiallyShuffleDistributedSampler(
        200, num_replicas=2, rank=0, window=16, seed=1, backend="cpu"
    )
    b.load_state_dict(modern)
    assert list(a) == list(b)


# ---------------------------------------------------------------- state fixes

def test_auto_consumption_tracking_partial_iter():
    s = PartiallyShuffleDistributedSampler(
        300, num_replicas=2, rank=1, window=32, seed=4, backend="cpu"
    )
    s.set_epoch(1)
    it = iter(s)
    head = [next(it) for _ in range(13)]
    state = s.state_dict()  # NO consumed argument
    assert state["offset"] == 13
    it.close()

    s2 = PartiallyShuffleDistributedSampler(
        300, num_replicas=2, rank=1, window=32, backend="cpu"
    )
    s2.load_state_dict(state)
    assert head + list(s2) == cpu.epoch_indices_np(300, 32, 4, 1, 1, 2).tolist()


def test_explicit_consumed_still_overrides():
    s = PartiallyShuffleDistributedSampler(
        100, num_replicas=1, rank=0, window=16, backend="cpu"
    )
    list(s)  # consume all
    assert s.state_dict()["offset"] == s.num_samples
    assert s.state_dict(consumed=7)["offset"] == 7


def test_state_dict_config_mismatch_rejected():
    s = PartiallyShuffleDistributedSampler(
        100, num_replicas=2, rank=0, window=16, backend="cpu"
    )
    state = s.state_dict()
    for field, bad in [
        ("window", 32), ("num_replicas", 4), ("rounds", 8), ("n", 101),
        ("order_windows", False), ("partition", "blocked"),
        ("shuffle", False), ("drop_last", True),
    ]:
        s2 = PartiallyShuffleDistributedSampler(
            100, num_replicas=2, rank=0, window=16, backend="cpu"
        )
        broken = dict(state)
        broken[field] = bad
        with pytest.raises(ValueError, match=field):
            s2.load_state_dict(broken)


def test_legacy_state_without_config_loads():
    """Round-1 checkpoints (no config fields) still load."""
    s = PartiallyShuffleDistributedSampler(
        100, num_replicas=2, rank=0, window=16, backend="cpu"
    )
    s.load_state_dict({"spec_version": 1, "seed": 2, "epoch": 3, "offset": 4})
    assert (s.seed, s.epoch, s._offset) == (2, 3, 4)


def test_len_reflects_resume_offset():
    s = PartiallyShuffleDistributedSampler(
        100, num_replicas=2, rank=0, window=16, backend="cpu"
    )
    assert len(s) == 50
    s.load_state_dict(s.state_dict(consumed=20))
    assert len(s) == 30  # the resumed epoch really yields 30
    assert len(list(s)) == 30
    assert len(s) == 50  # reverts once the resumed epoch has begun


def test_chunked_streaming_byte_equal():
    """The chunked __iter__ emits exactly the bulk sequence (VERDICT #3)."""
    s = PartiallyShuffleDistributedSampler(
        300_000, num_replicas=2, rank=0, window=1024, seed=8, backend="cpu"
    )
    s.set_epoch(2)
    assert s.STREAM_CHUNK < s.num_samples  # the test actually crosses chunks
    got = np.fromiter(iter(s), dtype=np.int64, count=s.num_samples)
    want = cpu.epoch_indices_np(300_000, 1024, 8, 2, 0, 2)
    np.testing.assert_array_equal(got, want.astype(np.int64))


def test_use_pallas_plumbed_through_shim():
    """backend='xla' + use_pallas=True must serve the same bits as the
    default path (VERDICT #10); on the CPU test platform 'auto' resolves to
    the XLA lowering and True forces the interpreted kernel."""
    kw = dict(num_replicas=2, rank=1, window=64, seed=3, backend="xla")
    a = PartiallyShuffleDistributedSampler(3_000, use_pallas=True, **kw)
    b = PartiallyShuffleDistributedSampler(3_000, use_pallas="auto", **kw)
    a.set_epoch(2), b.set_epoch(2)
    assert list(a) == list(b) == cpu.epoch_indices_np(
        3_000, 64, 3, 2, 1, 2
    ).tolist()
    with pytest.raises(ValueError, match="use_pallas"):
        PartiallyShuffleDistributedSampler(100, use_pallas="yes", **kw)


def test_stream_indices_at_jax_guards_big_n_without_x64():
    """ADVICE round 1 (medium): the random-access path must refuse n >= 2^31
    when x64 is off instead of silently returning wrong int32 indices."""
    import jax

    from partiallyshuffledistributedsampler_tpu.ops.xla import stream_indices_at_jax

    if jax.config.read("jax_enable_x64"):
        pytest.skip("x64 globally enabled; guard not reachable")
    with pytest.raises(ValueError, match="x64"):
        stream_indices_at_jax(np.arange(4), 2**31 + 10, 8192, 0, 0)


def test_identity_from_mesh_interleaved_assignment(monkeypatch):
    """identity_from_mesh must read rank off the mesh layout, not assume
    contiguous equal blocks per process (VERDICT weak #5)."""
    import jax

    from partiallyshuffledistributedsampler_tpu.parallel import mesh as mesh_mod

    devs = jax.devices()[:8]

    class FakeDev:
        def __init__(self, d, pidx):
            self._d = d
            self.process_index = pidx

        def __getattr__(self, a):
            return getattr(self._d, a)

    # uneven + interleaved: process 1 owns mesh positions 2 and 5 only
    owners = [0, 0, 1, 0, 0, 1, 0, 0]
    fake = np.asarray([FakeDev(d, o) for d, o in zip(devs, owners)], dtype=object)
    m = jax.sharding.Mesh(fake, ("data",))
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    world, first = mesh_mod.identity_from_mesh(m)
    assert (world, first) == (8, 2)
    # the full (non-contiguous) rank set is what bookkeeping must use
    assert mesh_mod.local_ranks_from_mesh(m) == [2, 5]
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    assert mesh_mod.identity_from_mesh(m) == (8, 0)
    assert mesh_mod.local_ranks_from_mesh(m) == [0, 1, 3, 4, 6, 7]
    monkeypatch.setattr(jax, "process_index", lambda: 7)
    with pytest.raises(ValueError, match="owns no devices"):
        mesh_mod.identity_from_mesh(m)


def test_elastic_chain_empty_layers_named_error():
    import pytest

    from partiallyshuffledistributedsampler_tpu.ops import core

    with pytest.raises(ValueError, match="empty"):
        core.elastic_chain(100, [], 4, False)
