"""Hot-standby replication: WAL shipping, failover, split-brain fencing.

The acceptance drill (docs/RESILIENCE.md "Replication & failover"): with
a standby attached, ``kill -9`` of the primary mid-epoch costs the
clients a latency blip and nothing else — zero degraded-mode entries,
zero duplicated or dropped samples, the merged stream bit-identical to
an unkilled run — in all three spec modes and across a reshard drain
boundary.  A fenced zombie primary must refuse every state-mutating
request with a typed ``fenced`` error carrying the new term.

Covered here: the kill-mid-epoch matrix over plain/mixture/shard; the
``HostDataLoader`` riding through a failover without a degraded entry;
a primary killed at a reshard drain boundary (union law holds on the
promoted standby); WAL catch-up after the standby joins late; snapshot
CRC refusal of a torn file (satellite of the same PR); and the fencing
semantics of a zombie that survives its own demotion.
"""

from __future__ import annotations

import json
import threading
import time
import warnings

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.sampler.host_loader import (
    HostDataLoader,
)
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    PartialShuffleSpec,
    ServiceIndexClient,
)
from partiallyshuffledistributedsampler_tpu.service import protocol as P
from partiallyshuffledistributedsampler_tpu.utils.checkpoint import (
    load_sampler_state,
    save_sampler_state,
)

from test_elastic_service import (
    MAX_UNIT,
    assert_union_law,
    build_spec,
    epoch_union_ref,
)

pytestmark = pytest.mark.failover


def replicated_pair(spec, feed_timeout=0.25, **primary_kw):
    """A started (primary, standby) pair shipping the WAL over loopback."""
    standby = IndexServer(spec, role="standby", repl_feed_timeout=feed_timeout)
    standby.start()
    primary = IndexServer(spec, standby=standby.address,
                          repl_feed_timeout=feed_timeout, **primary_kw)
    primary.start()
    return primary, standby


def wait_for(cond, timeout=10.0, interval=0.01):
    t0 = time.monotonic()
    while not cond():
        if time.monotonic() - t0 > timeout:
            raise AssertionError("condition not reached within deadline")
        time.sleep(interval)


def wait_synced(primary, standby, timeout=10.0):
    """Block until the standby has applied everything the log holds."""
    wait_for(lambda: (primary._shipper is not None
                      and primary._shipper.synced.is_set()
                      and standby._applied_lsn >= primary._repl_log.lsn),
             timeout=timeout)


# ---------------------------------------------------- kill-mid-epoch matrix
@pytest.mark.parametrize("mode", ["plain", "mixture", "shard"])
def test_kill_primary_mid_epoch_bit_identical(mode):
    """Both ranks stream a batch, the primary is hard-killed, both finish
    on the promoted standby with streams bit-identical to an unkilled
    run — exactly-once across the failover, no degraded fallback."""
    spec = build_spec(mode, 2)
    primary, standby = replicated_pair(spec)
    delivered = {}
    lock = threading.Lock()
    b_streamed = threading.Barrier(3)
    b_killed = threading.Barrier(3)

    def worker(r):
        got = []
        c = ServiceIndexClient(primary.address, rank=r, batch=23, spec=spec,
                               backoff_base=0.01, reconnect_timeout=2.0)
        try:
            it = c.epoch_batches(0)
            got.append(next(it))
            b_streamed.wait(timeout=30.0)
            b_killed.wait(timeout=30.0)
            for arr in it:
                got.append(arr)
        finally:
            with lock:
                delivered[r] = (got, c.metrics.report()["counters"])
            c.close()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    try:
        for t in threads:
            t.start()
        b_streamed.wait(timeout=30.0)
        wait_synced(primary, standby)
        primary.kill()
        b_killed.wait(timeout=30.0)
        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "failover worker hung"
    finally:
        primary.kill()
        standby.stop()
    assert standby.role == "primary", "standby never promoted"
    assert standby.term >= 1
    for r in range(2):
        got, counters = delivered[r]
        ref = np.asarray(spec.rank_indices(0, r))
        assert np.array_equal(np.concatenate(got), ref), (
            f"rank {r} stream diverged across the failover ({mode})")
        assert counters.get("failovers", 0) >= 1
        assert counters.get("degraded_mode", 0) == 0


def test_loader_failover_never_enters_degraded_mode():
    """The HostDataLoader sees the failover only as latency: the dead
    primary is absorbed INSIDE the client, so the loader stays attached
    and its stream bit-matches a purely local loader."""
    X = np.arange(997, dtype=np.int64)
    local = HostDataLoader(X, window=64, batch=64, seed=7, rank=0, world=1)
    spec = PartialShuffleSpec.plain(997, window=64, seed=7, world=1)
    primary, standby = replicated_pair(spec)
    client = ServiceIndexClient(primary.address, rank=0, batch=64, spec=spec,
                                backoff_base=0.01, reconnect_timeout=2.0)
    loader = HostDataLoader(X, window=64, batch=64, seed=7, rank=0, world=1,
                            index_client=client)
    try:
        assert np.array_equal(loader.epoch_indices(0),
                              local.epoch_indices(0))
        wait_synced(primary, standby)
        primary.kill()
        got = loader.epoch_indices(1)
        assert np.array_equal(got, local.epoch_indices(1))
        assert loader.degraded is False
        counters = client.metrics.report()["counters"]
        assert counters.get("degraded_mode", 0) == 0
        assert counters.get("failovers", 0) >= 1
    finally:
        client.close()
        primary.kill()
        standby.stop()


# ------------------------------------------------- reshard drain boundary
def test_kill_primary_at_drain_boundary_union_law():
    """The primary dies after freezing the drain barrier but before the
    commit: the standby inherits the replicated barrier state, promotes,
    finishes the drain, and the union law still holds."""
    spec = build_spec("plain", 2)
    ref = epoch_union_ref(spec)
    primary, standby = replicated_pair(spec)
    delivered = {}
    lock = threading.Lock()
    b_hit = threading.Barrier(2)
    # THREE parties: both workers AND the main thread — the workers must
    # not resume their streams until the primary is already dead, else a
    # fast drain can complete the whole epoch before the kill lands and
    # the standby is never asked to promote (the race this test means to
    # pin, not dodge)
    b_go = threading.Barrier(3)

    def worker(r):
        got = []
        c = ServiceIndexClient(primary.address, rank=r, batch=23, spec=spec,
                               backoff_base=0.01, reconnect_timeout=3.0)
        try:
            it = c.epoch_batches(0)
            for _ in range(1 + r):
                try:
                    got.append(next(it))
                except StopIteration:
                    break
            b_hit.wait(timeout=30.0)
            if r == 0:
                c.reshard(1)
            b_go.wait(timeout=30.0)
            for arr in it:
                got.append(arr)
        finally:
            with lock:
                delivered[r] = got
            c.close()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    try:
        for t in threads:
            t.start()
        # the drain barrier froze (reshard() returned past b_hit); make
        # sure the standby holds the frozen-barrier WAL record, then
        # kill the primary before the workers resume and commit
        wait_for(lambda: primary._reshard is not None
                 or primary._state_dict()["generation"] >= 1, timeout=30.0)
        wait_synced(primary, standby)
        primary.kill()
        b_go.wait(timeout=30.0)  # release the workers onto the dead primary
        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "drain-boundary worker hung"
    finally:
        primary.kill()
        standby.stop()
    assert standby.role == "primary"
    union = np.concatenate(
        [np.concatenate(v) if v else np.empty(0, np.int64)
         for v in delivered.values()])
    assert_union_law(union, ref, new_world=1, max_unit=MAX_UNIT["plain"])


# -------------------------------------------------------- WAL catch-up
def test_standby_resyncs_after_log_tail_rotation():
    """A standby that falls behind the in-memory tail is healed by a
    fresh snapshot SYNC, not fed a gapped stream."""
    spec = PartialShuffleSpec.plain(530, window=32, seed=7, world=1)
    primary, standby = replicated_pair(spec)
    try:
        with ServiceIndexClient(primary.address, rank=0, batch=37, spec=spec,
                                backoff_base=0.01) as c:
            c.epoch_indices(0)
        wait_synced(primary, standby)
        # force a gap: pretend the standby saw a far-future stream, then
        # reset it so the next APPEND's from_lsn looks discontiguous
        with standby._lock:
            standby._applied_lsn = 0
        with ServiceIndexClient(primary.address, rank=0, batch=37, spec=spec,
                                backoff_base=0.01) as c:
            c.epoch_indices(1)
        wait_synced(primary, standby)
        assert standby._applied_lsn == primary._repl_log.lsn
        assert standby._cursors[0]["epoch"] == 1
    finally:
        primary.stop()
        standby.stop()


# --------------------------------------------------- split-brain fencing
def test_zombie_primary_is_fenced_after_promotion():
    """The old primary survives its own demotion: a forced promotion on
    the standby bumps the term, and every write the zombie sees after
    learning of it is refused with a typed ``fenced`` error carrying the
    new term — the zombie's epoch state never mutates."""
    spec = PartialShuffleSpec.plain(530, window=32, seed=7, world=1)
    # huge feed timeout: the standby will NOT self-promote, we force it
    primary, standby = replicated_pair(spec, feed_timeout=60.0)
    import socket as _socket

    def raw_write(addr, header, msg=P.MSG_SET_EPOCH):
        sock = _socket.create_connection(addr, timeout=5.0)
        try:
            P.send_msg(sock, P.MSG_HELLO,
                       {"proto": P.PROTOCOL_VERSION, "rank": 0, "batch": 32})
            m, h, _ = P.recv_msg(sock)
            if m == P.MSG_ERROR:
                return m, h
            P.send_msg(sock, msg, header)
            m, h, _ = P.recv_msg(sock)
            return m, h
        finally:
            sock.close()

    try:
        with ServiceIndexClient(primary.address, rank=0, batch=37,
                                spec=spec, backoff_base=0.01) as c:
            c.epoch_indices(0)
        wait_synced(primary, standby)
        epoch_before = primary.epoch
        assert standby._try_promote(force=True)
        assert standby.term == primary.term + 1
        # a write stamped with the new term reaches the zombie: it must
        # fence itself on the spot and refuse with the typed error
        m, h = raw_write(primary.address,
                         {"epoch": 5, "term": standby.term})
        assert m == P.MSG_ERROR and h["code"] == "fenced"
        assert h["term"] >= standby.term
        assert h["serving"] is False
        # once fenced, even a term-less legacy write is refused
        m, h = raw_write(primary.address, {"epoch": 6})
        assert m == P.MSG_ERROR and h["code"] == "fenced"
        assert h["term"] >= standby.term
        assert primary.epoch == epoch_before, "zombie write mutated state"
        assert primary._fenced_term is not None
        counters = primary.metrics.report()["counters"]
        assert counters.get("fenced_writes", 0) >= 1
    finally:
        primary.stop()
        standby.stop()


def test_fenced_client_fails_over_to_serving_peer():
    """A client talking to the zombie follows the fencing term to the
    promoted standby and keeps streaming — no degraded entry."""
    spec = PartialShuffleSpec.plain(530, window=32, seed=7, world=1)
    primary, standby = replicated_pair(spec, feed_timeout=60.0)
    client = ServiceIndexClient(primary.address, rank=0, batch=37, spec=spec,
                                backoff_base=0.01, reconnect_timeout=2.0)
    try:
        it = client.epoch_batches(0)
        got = [next(it)]
        wait_synced(primary, standby)
        assert standby._try_promote(force=True)
        # fence the zombie out-of-band (the shipper would do this on its
        # next APPEND; do it directly so the test is deterministic)
        primary._fence(standby.term)
        got.extend(it)
        ref = np.asarray(spec.rank_indices(0, 0))
        assert np.array_equal(np.concatenate(got), ref)
        counters = client.metrics.report()["counters"]
        assert counters.get("fenced_replies", 0) >= 1
        assert counters.get("degraded_mode", 0) == 0
        assert client.term >= standby.term
    finally:
        client.close()
        primary.stop()
        standby.stop()


# ----------------------------------------------- snapshot durability (CRC)
def test_snapshot_embeds_crc_and_refuses_torn_file(tmp_path):
    spec = PartialShuffleSpec.plain(530, window=32, seed=7, world=1)
    path = str(tmp_path / "snap.json")
    with IndexServer(spec, snapshot_path=path, snapshot_interval=1) as srv:
        with ServiceIndexClient(srv.address, rank=0, batch=37,
                                spec=spec) as c:
            c.set_epoch(3)
            c.epoch_indices(3)
        srv._write_snapshot(force=True)
    state = load_sampler_state(path)
    assert "crc32" in state
    # clean restart adopts the snapshot
    with IndexServer(spec, snapshot_path=path) as srv2:
        assert srv2.epoch == 3
        assert srv2.metrics.report()["counters"].get("snapshot_corrupt",
                                                     0) == 0
    # tear the payload without touching the recorded CRC
    state["epoch"] = 4
    with open(path, "w") as f:
        json.dump(state, f)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with IndexServer(spec, snapshot_path=path) as srv3:
            assert srv3.epoch == 0, "torn snapshot must not be loaded"
            assert srv3.metrics.report()["counters"].get(
                "snapshot_corrupt", 0) >= 1
    assert any("snapshot" in str(w.message).lower() for w in caught)


def test_save_sampler_state_durable_roundtrip(tmp_path):
    path = str(tmp_path / "s.json")
    save_sampler_state(path, {"a": 1}, durable=True)
    assert load_sampler_state(path) == {"a": 1}
    save_sampler_state(path, {"a": 2}, durable=True)
    assert load_sampler_state(path) == {"a": 2}


# --------------------------------------------------------- wire surface
def test_welcome_advertises_standby_and_term():
    spec = PartialShuffleSpec.plain(530, window=32, seed=7, world=1)
    primary, standby = replicated_pair(spec)
    try:
        with ServiceIndexClient(primary.address, rank=0, batch=37,
                                spec=spec) as c:
            c.heartbeat()
            assert c.standby_address == standby.address
            assert c.term == primary.term
    finally:
        primary.stop()
        standby.stop()


def test_standby_refuses_client_writes_while_feed_is_fresh():
    spec = PartialShuffleSpec.plain(530, window=32, seed=7, world=1)
    primary, standby = replicated_pair(spec, feed_timeout=60.0)
    try:
        wait_synced(primary, standby)
        import socket as _socket
        sock = _socket.create_connection(standby.address, timeout=5.0)
        try:
            P.send_msg(sock, P.MSG_HELLO,
                       {"proto": P.PROTOCOL_VERSION, "rank": 0, "batch": 32})
            msg, header, _ = P.recv_msg(sock)
        finally:
            sock.close()
        assert msg == P.MSG_ERROR
        assert header["code"] == "standby"
        assert tuple(header["primary"]) == primary.address
    finally:
        primary.stop()
        standby.stop()
