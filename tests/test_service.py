"""Index-serving service: daemon + clients == the local sampler, always.

Law under test: for any spec (plain / mixture / shard) the concatenated
batch stream a ``ServiceIndexClient`` delivers for ``(seed, epoch, rank)``
is bit-identical to ``PartialShuffleSpec.rank_indices`` — across many
concurrent clients, reconnects, a mid-epoch server kill + snapshot
restart, backpressure throttling, and lease eviction.  The transport may
retry and resend; the *delivered* stream must never gap or duplicate.
"""

import socket
import threading
import time

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops.cpu import epoch_indices_np
from partiallyshuffledistributedsampler_tpu.ops.mixture import MixtureSpec
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    PartialShuffleSpec,
    ServiceError,
    ServiceIndexClient,
    ServiceMetrics,
)
from partiallyshuffledistributedsampler_tpu.service import protocol as P


def plain_spec(world=4, **kw):
    kw.setdefault("n", 530)
    kw.setdefault("window", 32)
    return PartialShuffleSpec.plain(kw.pop("n"), world=world, seed=7, **kw)


def mixture_spec(world=4):
    ms = MixtureSpec([100, 200, 50], [5, 3, 2], block=16)
    return PartialShuffleSpec.mixture(ms, seed=3, world=world,
                                      epoch_samples=300)


def shard_spec(world=4):
    return PartialShuffleSpec.shard([17, 5, 29, 11, 40, 8, 23, 9], window=4,
                                    seed=9, world=world,
                                    within_shard_shuffle=True)


SPECS = {"plain": plain_spec, "mixture": mixture_spec, "shard": shard_spec}


# --------------------------------------------------------------- protocol
def test_protocol_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        arr = np.arange(100, dtype=np.int64) * 3
        header, payload = P.encode_indices(arr)
        header["seq"] = 5
        P.send_msg(a, P.MSG_BATCH, header, payload)
        msg, h, pl = P.recv_msg(b)
        assert msg == P.MSG_BATCH and h["seq"] == 5
        assert np.array_equal(P.decode_indices(h, pl), arr)
    finally:
        a.close()
        b.close()


def test_protocol_rejects_malformed_frames():
    a, b = socket.socketpair()
    try:
        a.sendall((1 << 30).to_bytes(4, "big"))  # body_len over MAX_FRAME
        a.close()
        with pytest.raises(P.ProtocolError):
            P.recv_msg(b)
    finally:
        b.close()


def test_protocol_closed_peer_raises_connection_error():
    a, b = socket.socketpair()
    a.close()
    try:
        with pytest.raises(ConnectionError):
            P.recv_msg(b)
    finally:
        b.close()


def test_decode_rejects_length_mismatch():
    with pytest.raises(P.ProtocolError):
        P.decode_indices({"dtype": "<i8", "count": 10}, b"\0" * 16)


# ------------------------------------------------------------------- spec
def test_spec_wire_roundtrip_all_modes():
    for name, build in SPECS.items():
        spec = build()
        back = PartialShuffleSpec.from_wire(spec.to_wire())
        assert back == spec, name
        assert back.fingerprint() == spec.fingerprint()


def test_spec_backend_outside_fingerprint():
    a = plain_spec(world=2)
    b = PartialShuffleSpec.from_wire(a.to_wire(), backend="cpu")
    assert a.fingerprint() == b.fingerprint()


def test_spec_plain_matches_reference_stream():
    spec = plain_spec(world=2)
    for rank in range(2):
        ref = epoch_indices_np(530, 32, 7, 4, rank, 2)
        assert np.array_equal(spec.rank_indices(4, rank), ref)


def test_spec_rejects_unknown_kwargs():
    with pytest.raises(TypeError):
        PartialShuffleSpec.plain(100, window=8, banana=True)


# ------------------------------------------------- served == local streams
@pytest.mark.parametrize("mode", sorted(SPECS))
def test_four_clients_stream_equals_local(mode):
    spec = SPECS[mode](world=4)
    results, errors = {}, []

    def run(rank):
        try:
            with ServiceIndexClient((host, port), rank=rank, batch=41) as c:
                results[rank] = c.epoch_indices(2)
        except BaseException as exc:  # surfaced below
            errors.append((rank, exc))

    with IndexServer(spec) as srv:
        host, port = srv.address
        threads = [threading.Thread(target=run, args=(r,)) for r in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors, errors
    for rank in range(4):
        assert np.array_equal(results[rank], spec.rank_indices(2, rank)), rank


def test_auto_rank_claims_are_distinct():
    spec = plain_spec(world=3)
    with IndexServer(spec) as srv:
        clients = [ServiceIndexClient(srv.address) for _ in range(3)]
        try:
            for c in clients:
                c._ensure_connected()
            assert sorted(c.rank for c in clients) == [0, 1, 2]
        finally:
            for c in clients:
                c.close()


def test_batches_follow_transport_batch_size():
    spec = plain_spec(world=1, n=300)
    with IndexServer(spec) as srv:
        with ServiceIndexClient(srv.address, batch=64) as c:
            sizes = [len(b) for b in c.epoch_batches(0)]
    total = spec.num_samples(0)
    assert sum(sizes) == total
    assert all(s == 64 for s in sizes[:-1])


def test_spec_fingerprint_mismatch_refused():
    spec = plain_spec(world=2)
    other = plain_spec(world=2, n=531)
    with IndexServer(spec) as srv:
        c = ServiceIndexClient(srv.address, spec=other, reconnect_timeout=1.0)
        with pytest.raises(ServiceError) as ei:
            c._ensure_connected()
        # typed refusal carrying both world-stripped fingerprints
        assert ei.value.code == "spec_mismatch"
        assert ei.value.header["server_fingerprint"] == \
            spec.fingerprint(include_world=False)
        assert ei.value.header["client_fingerprint"] == \
            other.fingerprint(include_world=False)


# --------------------------------------------------- backpressure + leases
def _raw_hello(addr, rank, batch=32):
    sock = socket.create_connection(addr, timeout=5.0)
    P.send_msg(sock, P.MSG_HELLO,
               {"proto": P.PROTOCOL_VERSION, "rank": rank, "batch": batch})
    msg, header, _ = P.recv_msg(sock)
    return sock, msg, header


def test_backpressure_throttles_runaway_seq():
    spec = plain_spec(world=1)
    with IndexServer(spec, max_inflight=2) as srv:
        sock, msg, _ = _raw_hello(srv.address, rank=0)
        try:
            assert msg == P.MSG_WELCOME
            # nothing acked yet: seq 3 > acked(-1) + max_inflight(2)
            P.send_msg(sock, P.MSG_GET_BATCH,
                       {"rank": 0, "epoch": 0, "seq": 3, "ack": -1})
            msg, header, _ = P.recv_msg(sock)
            assert msg == P.MSG_ERROR and header["code"] == "throttle"
            assert header["retry_ms"] > 0
            # acking up to 1 opens the window for seq 3
            P.send_msg(sock, P.MSG_GET_BATCH,
                       {"rank": 0, "epoch": 0, "seq": 3, "ack": 1})
            msg, header, _ = P.recv_msg(sock)
            assert msg == P.MSG_BATCH and header["seq"] == 3
        finally:
            sock.close()
    assert srv.metrics.report()["counters"].get("throttled", 0) >= 1


def test_rank_lease_conflict_and_release_on_disconnect():
    spec = plain_spec(world=1)
    with IndexServer(spec) as srv:
        holder, msg, _ = _raw_hello(srv.address, rank=0)
        assert msg == P.MSG_WELCOME
        rival, msg, header = _raw_hello(srv.address, rank=0)
        rival.close()
        assert msg == P.MSG_ERROR and header["code"] == "rank_taken"
        holder.close()  # disconnect frees the lease immediately
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            again, msg, _ = _raw_hello(srv.address, rank=0)
            again.close()
            if msg == P.MSG_WELCOME:
                break
            time.sleep(0.02)
        assert msg == P.MSG_WELCOME


def test_heartbeat_timeout_evicts_silent_client():
    spec = plain_spec(world=1)
    with IndexServer(spec, heartbeat_timeout=0.15) as srv:
        silent, msg, _ = _raw_hello(srv.address, rank=0)
        try:
            assert msg == P.MSG_WELCOME
            time.sleep(0.3)  # no heartbeats: lease goes stale
            fresh, msg, _ = _raw_hello(srv.address, rank=0)
            fresh.close()
            assert msg == P.MSG_WELCOME  # stale lease evicted at claim
        finally:
            silent.close()
    assert srv.metrics.report()["counters"].get("evictions", 0) >= 1


def test_lease_eviction_timing_exact_with_injected_clock():
    """Eviction timing pinned down deterministically: a lease is held
    through exactly ``heartbeat_timeout`` of silence and reclaimable
    immediately past it — no sleeps, the server runs on a fake clock."""
    class FakeClock:
        def __init__(self):
            self.t = 1000.0

        def __call__(self):
            return self.t

    clk = FakeClock()
    spec = plain_spec(world=1)
    with IndexServer(spec, heartbeat_timeout=10.0, clock=clk) as srv:
        holder, msg, _ = _raw_hello(srv.address, rank=0)
        try:
            assert msg == P.MSG_WELCOME
            # silence for EXACTLY the ttl: still leased (eviction is
            # strictly-greater-than, so a heartbeat landing on the
            # deadline keeps its lease)
            clk.t += 10.0
            srv._sweep_leases()
            rival, msg, header = _raw_hello(srv.address, rank=0)
            rival.close()
            assert msg == P.MSG_ERROR and header["code"] == "rank_taken"
            assert srv.metrics.report()["counters"].get("evictions", 0) == 0
            # one tick past the ttl: swept, counted, and reclaimable
            clk.t += 0.001
            srv._sweep_leases()
            assert srv.metrics.report()["counters"].get("evictions", 0) == 1
            fresh, msg, _ = _raw_hello(srv.address, rank=0)
            fresh.close()
            assert msg == P.MSG_WELCOME
        finally:
            holder.close()


def test_heartbeat_keeps_lease_alive():
    spec = plain_spec(world=1)
    with IndexServer(spec, heartbeat_timeout=0.4) as srv:
        with ServiceIndexClient(srv.address, rank=0) as c:
            for _ in range(4):
                time.sleep(0.1)
                c.heartbeat()
            rival, msg, header = _raw_hello(srv.address, rank=0)
            rival.close()
            assert msg == P.MSG_ERROR and header["code"] == "rank_taken"


# ------------------------------------------------------- resends + resume
def test_replayed_seq_is_idempotent():
    spec = plain_spec(world=1)
    with IndexServer(spec) as srv:
        sock, msg, _ = _raw_hello(srv.address, rank=0)
        try:
            replies = []
            for _ in range(2):  # same seq twice: a reconnect replay
                P.send_msg(sock, P.MSG_GET_BATCH,
                           {"rank": 0, "epoch": 1, "seq": 0, "ack": -1})
                _, header, payload = P.recv_msg(sock)
                replies.append(P.decode_indices(header, payload))
            assert np.array_equal(replies[0], replies[1])
        finally:
            sock.close()
    assert srv.metrics.report()["counters"].get("resends", 0) >= 1


def test_client_state_dict_resumes_exactly_once():
    spec = plain_spec(world=1)
    with IndexServer(spec) as srv:
        c = ServiceIndexClient(srv.address, batch=32)
        first = []
        for i, arr in enumerate(c.epoch_batches(3)):
            first.append(arr)
            if i == 2:
                state = c.state_dict()
                break
        c.close()
        c2 = ServiceIndexClient(srv.address, batch=32)
        c2.load_state_dict(state)
        rest = list(c2.resume_batches())
        c2.close()
    stream = np.concatenate(first + rest)
    assert np.array_equal(stream, spec.rank_indices(3, 0))


# --------------------------------------------- kill mid-epoch, restart
@pytest.mark.parametrize("mode", sorted(SPECS))
def test_server_kill_and_restart_stream_bit_identical(mode):
    """The acceptance law: a server killed mid-epoch and restarted from
    its snapshot serves the remaining batches with no gap and no
    duplicate — the client's delivered stream equals the local run."""
    spec = SPECS[mode](world=2)
    results, errors = {}, []

    def run(rank, barrier):
        try:
            c = ServiceIndexClient((host, port), rank=rank, batch=23,
                                   reconnect_timeout=20.0)
            got = []
            for i, arr in enumerate(c.epoch_batches(6)):
                got.append(arr)
                if i == 2:
                    barrier.wait(timeout=10.0)  # both ranks mid-epoch
                    barrier.wait(timeout=10.0)  # server is down + back up
            results[rank] = np.concatenate(got)
            c.close()
        except BaseException as exc:
            errors.append((rank, exc))

    snap = None
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        snap = td + "/service.json"
        srv = IndexServer(spec, snapshot_path=snap, snapshot_interval=1)
        host, port = srv.start()
        barrier = threading.Barrier(3)
        threads = [threading.Thread(target=run, args=(r, barrier))
                   for r in range(2)]
        for t in threads:
            t.start()
        barrier.wait(timeout=10.0)  # all clients hold mid-epoch
        srv.stop()
        srv2 = IndexServer(spec, host=host, port=port, snapshot_path=snap,
                           snapshot_interval=1)
        srv2.start()
        barrier.wait(timeout=10.0)  # release the clients
        for t in threads:
            t.join(timeout=30.0)
        srv2.stop()
    assert not errors, errors
    for rank in range(2):
        assert np.array_equal(results[rank], spec.rank_indices(6, rank)), rank


def test_snapshot_restores_epoch_and_refuses_wrong_spec(tmp_path):
    snap = str(tmp_path / "svc.json")
    spec = plain_spec(world=1)
    with IndexServer(spec, snapshot_path=snap, snapshot_interval=1) as srv:
        with ServiceIndexClient(srv.address) as c:
            c.set_epoch(9)
    srv2 = IndexServer(spec, snapshot_path=snap)
    srv2.start()
    try:
        with ServiceIndexClient(srv2.address) as c:
            assert c.server_epoch == 9
    finally:
        srv2.stop()
    with pytest.raises(ValueError):
        IndexServer(plain_spec(world=1, n=531), snapshot_path=snap).start()


# ------------------------------------------------------- loader + metrics
def test_host_loader_consumes_service_stream():
    from partiallyshuffledistributedsampler_tpu.sampler import HostDataLoader

    data = {"x": np.arange(530 * 2).reshape(530, 2), "y": np.arange(530)}
    spec = plain_spec(world=2)
    with IndexServer(spec) as srv:
        with ServiceIndexClient(srv.address, rank=1, batch=64) as c:
            served = HostDataLoader(data, window=32, seed=7, rank=1, world=2,
                                    batch=64, index_client=c)
            got = [np.asarray(b["y"]) for b in served.epoch(2)]
    ref = spec.rank_indices(2, 1)
    whole = len(ref) // 64
    for b, s in zip(got, range(whole)):
        assert np.array_equal(b, ref[s * 64:(s + 1) * 64])


def test_service_metrics_per_client_report():
    reg_metrics = ServiceMetrics()
    spec = plain_spec(world=2)
    with IndexServer(spec, metrics=reg_metrics) as srv:
        with ServiceIndexClient(srv.address, rank=0, batch=64) as c:
            c.epoch_indices(0)
            report = c.server_metrics()
    assert report["counters"]["batches_served"] >= 1
    assert report["clients"]["0"]["batches_served"] >= 1
    assert "epoch_regen_ms" in report["timers"]


@pytest.mark.slow
def test_soak_many_epochs_many_clients():
    """Soak: 4 clients x 5 epochs with a throttling window of 1 — every
    delivered stream still equals the local run."""
    spec = plain_spec(world=4)
    with IndexServer(spec, max_inflight=1) as srv:
        host, port = srv.address
        errors = []

        def run(rank):
            try:
                with ServiceIndexClient((host, port), rank=rank,
                                        batch=17) as c:
                    for epoch in range(5):
                        got = c.epoch_indices(epoch)
                        ref = spec.rank_indices(epoch, rank)
                        assert np.array_equal(got, ref), (rank, epoch)
            except BaseException as exc:
                errors.append((rank, exc))

        threads = [threading.Thread(target=run, args=(r,)) for r in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
