"""Self-tests for the analysis subsystem (docs/ANALYSIS.md).

Three layers:

* the repo gate — every lint pass over the package itself must report
  zero findings (the same bar ``make analyze`` enforces in CI);
* golden-snippet tests per static pass, including the waiver syntax and
  its no-empty-reason rule;
* the runtime sanitizer — a deliberate AB/BA lock inversion must produce
  a cycle report naming both acquisition stacks, ``TrackedLock`` must
  stay exact through ``threading.Condition``, ``new_lock`` must be raw
  (zero-cost) when the sanitizer is off, and the thread-leak detector
  must both catch a lingering non-daemon thread and go quiet once it
  exits.  Plus the ride-along regression: abandoning a pending
  ``_AsyncRegen`` must join its worker thread.
"""

import textwrap
import threading
import time

import pytest

from partiallyshuffledistributedsampler_tpu.analysis import lint, lockorder
from partiallyshuffledistributedsampler_tpu.analysis.lint import (
    PASSES,
    check_clocks,
    check_guarded_by,
    check_silent_except,
    default_root,
    doc_metric_tokens,
    lint_fault_sites,
    lint_metrics_docs,
    lint_protocol,
    run_all,
)


def _src(text: str) -> str:
    return textwrap.dedent(text)


# --------------------------------------------------------------- repo gate
@pytest.mark.parametrize("name", sorted(PASSES))
def test_repo_has_zero_findings(name):
    findings = PASSES[name](default_root())
    assert not findings, "\n".join(f.render() for f in findings)


def test_run_all_rejects_unknown_pass():
    with pytest.raises(ValueError):
        run_all(default_root(), ["no-such-pass"])


# ----------------------------------------------------- guarded-by golden
def test_guarded_by_flags_unlocked_access():
    findings = check_guarded_by(_src("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0  # guarded by: self._lock

            def bad(self):
                return self.x
    """), "snippet.py")
    assert len(findings) == 1
    assert "C.bad" in findings[0].message and "self.x" in findings[0].message


def test_guarded_by_accepts_with_lock_and_locked_suffix():
    findings = check_guarded_by(_src("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0  # guarded by: self._lock

            def good(self):
                with self._lock:
                    return self.x

            def _read_locked(self):
                return self.x
    """), "snippet.py")
    assert findings == []


def test_guarded_by_condition_aliases_its_lock():
    findings = check_guarded_by(_src("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self.x = 0  # guarded by: self._lock

            def good(self):
                with self._cond:
                    self.x += 1
    """), "snippet.py")
    assert findings == []


def test_guarded_by_waiver_needs_a_reason():
    base = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0  # guarded by: self._lock

            def racy(self):
                return self.x  # lint: allow-unguarded(%s)
    """
    assert check_guarded_by(_src(base % "monotonic flag, stale read ok"),
                            "snippet.py") == []
    findings = check_guarded_by(_src(base % ""), "snippet.py")
    assert len(findings) == 1
    assert "needs a reason" in findings[0].message


# --------------------------------------------------------- clocks golden
def test_clocks_only_applies_to_injectable_modules():
    wallclock = """
        import time

        def stamp(%s):
            return time.time()
    """
    # no clock= parameter anywhere: wall clock is fine
    assert check_clocks(_src(wallclock % ""), "snippet.py") == []
    # an injectable module must route through the injected clock
    findings = check_clocks(_src(wallclock % "clock=time.time"),
                            "snippet.py")
    assert len(findings) == 1
    assert "injectable clock=" in findings[0].message


def test_clocks_flags_datetime_now_and_accepts_waiver():
    findings = check_clocks(_src("""
        import datetime

        def stamp(clock=None):
            return datetime.datetime.now()
    """), "snippet.py")
    assert len(findings) == 1
    waived = check_clocks(_src("""
        import time

        def stamp(clock=None):
            return time.time()  # lint: allow-wallclock(log line only)
    """), "snippet.py")
    assert waived == []


# ------------------------------------------------- silent-except golden
def test_silent_except_flags_bare_pass():
    findings = check_silent_except(_src("""
        def f():
            try:
                work()
            except Exception:
                pass
    """), "snippet.py")
    assert len(findings) == 1


@pytest.mark.parametrize("body", [
    "raise",                       # re-raise
    "metrics.inc('errors')",       # counter bump
    "log(exc)",                    # the exception is used
])
def test_silent_except_accepts_handled_errors(body):
    findings = check_silent_except(_src(f"""
        def f():
            try:
                work()
            except Exception as exc:
                {body}
    """), "snippet.py")
    assert findings == []


def test_silent_except_import_guard_exempt_but_not_assign_only():
    guard = check_silent_except(_src("""
        try:
            import torch
            HAVE_TORCH = True
        except Exception:
            HAVE_TORCH = False
    """), "snippet.py")
    assert guard == []
    # a try body with no import is NOT an import guard
    findings = check_silent_except(_src("""
        def f(exc, ids):
            try:
                exc.tag = ids
            except Exception:
                pass
    """), "snippet.py")
    assert len(findings) == 1


def test_silent_except_waiver_and_empty_reason():
    assert check_silent_except(_src("""
        def f():
            try:
                work()
            except Exception:  # lint: allow-broad-except(best effort)
                pass
    """), "snippet.py") == []
    findings = check_silent_except(_src("""
        def f():
            try:
                work()
            except Exception:  # lint: allow-broad-except()
                pass
    """), "snippet.py")
    assert len(findings) == 1
    assert "needs a reason" in findings[0].message


# --------------------------------------------- fault-sites golden (tmp repo)
def _mini_repo(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text), encoding="utf-8")
    return tmp_path


def test_fault_sites_drift_both_directions(tmp_path):
    pkg = lint._PKG
    root = _mini_repo(tmp_path, {
        f"{pkg}/faults/plan.py": """
            SITES = frozenset({"net.send", "never.drawn"})
        """,
        f"{pkg}/mod.py": """
            def f(F):
                F.draw("net.send")
                F.fire("not.registered")
        """,
    })
    findings = lint_fault_sites(root)
    msgs = [f.message for f in findings]
    assert len(findings) == 2
    assert any("'not.registered'" in m and "absent from" in m for m in msgs)
    assert any("'never.drawn'" in m and "no code draws" in m for m in msgs)


# ------------------------------------------------ protocol golden (tmp repo)
def test_protocol_dead_opcode_and_unhandled_error_code(tmp_path):
    pkg = lint._PKG
    root = _mini_repo(tmp_path, {
        f"{pkg}/service/protocol.py": """
            MSG_PING = 1
            MSG_PONG = 2
            MSG_DEAD = 3
        """,
        f"{pkg}/service/server.py": """
            from . import protocol as P

            def serve(sock, msg):
                if msg == P.MSG_PING:
                    P.send_msg(sock, P.MSG_PONG, {"code": "weird_code"})
        """,
        f"{pkg}/service/client.py": """
            from . import protocol as P

            HANDLED = ("ok_code",)
            _PING, _PONG = P.MSG_PING, P.MSG_PONG
        """,
        f"{pkg}/service/replication.py": """
            # no error-code handling here
        """,
    })
    findings = lint_protocol(root)
    msgs = [f.message for f in findings]
    assert any("MSG_DEAD" in m and "dead opcode" in m for m in msgs)
    assert any("MSG_DEAD" in m and "no server dispatch arm" in m
               for m in msgs)
    assert any("'weird_code'" in m for m in msgs)
    assert not any("MSG_PING" in m or "MSG_PONG" in m for m in msgs)


# -------------------------------------------- metrics-docs golden (tmp repo)
def test_doc_metric_tokens_need_metric_context():
    text = _src("""
        The `epoch_regen_ms` timer tracks regen latency.

        This paragraph mentions `some_kwarg` but no metric words.
    """)
    tokens = doc_metric_tokens(text)
    assert "epoch_regen_ms" in tokens
    assert "some_kwarg" not in tokens


def test_metrics_docs_drift(tmp_path):
    pkg = lint._PKG
    root = _mini_repo(tmp_path, {
        f"{pkg}/mod.py": """
            def f(registry):
                registry.inc("hits_total")
        """,
        "docs/GOOD.md": """
            The `hits_total` counter counts hits.
        """,
        "docs/BAD.md": """
            The `missing_total` counter does not exist in code.
        """,
    })
    findings = lint_metrics_docs(root)
    assert len(findings) == 1
    assert findings[0].path == "docs/BAD.md"
    assert "`missing_total`" in findings[0].message


# ----------------------------------------------------- runtime sanitizer
@pytest.fixture
def sanitizer():
    """Enable the sanitizer for one test, restoring the prior state (the
    suite may already run under PSDS_SANITIZE=1) and clearing whatever
    graph state the test recorded."""
    prior = lockorder.is_enabled()
    lockorder.enable()
    yield lockorder
    lockorder.reset()
    if not prior:
        lockorder.disable()


def test_lock_inversion_reports_both_stacks(sanitizer):
    a = lockorder.TrackedLock("test.A")
    b = lockorder.TrackedLock("test.B")

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    for fn in (order_ab, order_ba):  # sequential: no real deadlock risk
        t = threading.Thread(target=fn)
        t.start()
        t.join()

    reports = lockorder.violations()
    assert len(reports) == 1
    rep = reports[0]
    assert set(rep["this_edge"]) == {"test.A", "test.B"}
    assert set(rep["other_edge"]) == {"test.A", "test.B"}
    assert rep["this_edge"] != rep["other_edge"]
    # both acquisition stacks are captured and name their call sites
    assert "order_ba" in rep["this_stack"]
    assert "order_ab" in rep["other_stack"]
    rendered = lockorder.render_violations(reports)
    assert "test.A" in rendered and "test.B" in rendered
    assert "order_ab" in rendered and "order_ba" in rendered


def test_consistent_order_records_no_violation(sanitizer):
    a = lockorder.TrackedLock("test.outer")
    b = lockorder.TrackedLock("test.inner")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockorder.violations() == []
    assert lockorder.stats()["edges"] >= 1


def test_tracked_lock_works_under_condition(sanitizer):
    lk = lockorder.TrackedLock("test.cond")
    cond = threading.Condition(lk)
    box = []

    def waiter():
        with cond:
            while not box:
                cond.wait(1.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        box.append(1)
        cond.notify_all()
    t.join(2.0)
    assert not t.is_alive()
    assert not lk.locked()
    # the held-set bookkeeping survived wait()'s release/re-acquire
    assert getattr(lockorder._STATE.tls, "held", []) == []
    assert lockorder.violations() == []


def test_new_lock_is_raw_when_disabled():
    prior = lockorder.is_enabled()
    lockorder.disable()
    try:
        raw = lockorder.new_lock("test.off")
        assert type(raw) is type(threading.Lock())
    finally:
        if prior:
            lockorder.enable()
    if prior:
        assert isinstance(lockorder.new_lock("test.on"),
                          lockorder.TrackedLock)


def test_thread_leak_detector_names_the_stuck_frame():
    base = lockorder.thread_snapshot()
    release = threading.Event()
    t = threading.Thread(target=release.wait, name="deliberate-leak",
                         daemon=False)
    t.start()
    try:
        leaked = lockorder.leaked_threads(base, grace_s=0.2)
        assert [x.name for x in leaked] == ["deliberate-leak"]
        stacks = lockorder.thread_stacks(leaked)
        assert "wait" in stacks["deliberate-leak"]
    finally:
        release.set()
        t.join()
    assert lockorder.leaked_threads(base, grace_s=1.0) == []


# ------------------------------------------------- _AsyncRegen ride-along
def test_load_state_dict_joins_pending_regen():
    torch = pytest.importorskip("torch")  # noqa: F841
    from partiallyshuffledistributedsampler_tpu import (
        PartiallyShuffleDistributedSampler,
    )
    from partiallyshuffledistributedsampler_tpu.sampler.torch_shim import (
        _AsyncRegen,
    )

    s = PartiallyShuffleDistributedSampler(
        1000, num_replicas=2, rank=0, window=64, backend="cpu")
    s.set_epoch(1)
    pending = s._pending
    assert isinstance(pending, _AsyncRegen)
    s.load_state_dict(s.state_dict())
    # the abandoned prefetch worker was joined, not leaked
    assert not pending._t.is_alive()
    assert s._pending is None
    assert list(s)  # the sampler still serves the restored epoch


def test_mixture_load_state_dict_joins_pending_regen():
    torch = pytest.importorskip("torch")  # noqa: F841
    from partiallyshuffledistributedsampler_tpu.sampler.mixture import (
        PartialShuffleMixtureSampler,
    )
    from partiallyshuffledistributedsampler_tpu.sampler.torch_shim import (
        _AsyncRegen,
    )

    s = PartialShuffleMixtureSampler(
        [100, 200, 50], [5, 3, 2], num_replicas=2, rank=0, block=16,
        backend="cpu")
    s.set_epoch(1)
    pending = s._pending
    assert isinstance(pending, _AsyncRegen)
    s.load_state_dict(s.state_dict())
    assert not pending._t.is_alive()
    assert s._pending is None
