"""End-to-end sharded training on the virtual 8-device CPU mesh: the
minimum slice of SURVEY.md §7 build order #3/#4 at test scale."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.models import (
    GPTConfig,
    demo_training_run,
    forward,
    init_params,
    make_mesh,
)

TINY = GPTConfig(vocab_size=64, seq_len=16, d_model=32, n_layers=1,
                 n_heads=2, d_ff=64)


def test_forward_shapes():
    params = init_params(TINY, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, TINY.seq_len), jnp.int32)
    logits = forward(TINY, params, tokens)
    assert logits.shape == (2, TINY.seq_len, TINY.vocab_size)
    assert logits.dtype == jnp.float32


def test_mesh_factorization():
    m = make_mesh(8)
    assert dict(m.shape) == {"dp": 4, "tp": 2}
    m1 = make_mesh(1)
    assert dict(m1.shape) == {"dp": 1, "tp": 1}


def test_params_actually_sharded_over_tp():
    from partiallyshuffledistributedsampler_tpu.models.train import (
        create_sharded_state,
    )

    mesh = make_mesh(8)
    params, opt_state, _ = create_sharded_state(TINY, mesh)
    qkv = params["block0"]["qkv"]["kernel"]
    assert "tp" in str(qkv.sharding.spec)  # column-parallel over tp
    # a device's local shard really holds half the output features
    local = qkv.addressable_shards[0].data
    assert local.shape == (qkv.shape[0], qkv.shape[1] // 2)
    # optimizer state inherited the same sharding leaf-for-leaf
    mu_qkv = opt_state[0].mu["block0"]["qkv"]["kernel"]
    assert mu_qkv.sharding == qkv.sharding


def test_training_runs_and_losses_finite():
    mesh = make_mesh(8)
    losses = demo_training_run(
        mesh, TINY, n_samples=64, window=16, batch_per_dp=2,
        steps_per_epoch=2, epochs=2,
    )
    assert len(losses) == 4
    assert all(np.isfinite(l) for l in losses)


def test_scanned_epoch_runner_matches_step_loop():
    # make_epoch_runner (lax.scan over sharded steps, one dispatch/epoch)
    # must produce the same training trajectory as the per-step loop
    mesh = make_mesh(8)
    kw = dict(n_samples=64, window=16, batch_per_dp=2, steps_per_epoch=2,
              epochs=2)
    stepped = demo_training_run(mesh, TINY, **kw)
    scanned = demo_training_run(mesh, TINY, scan_epochs=True, **kw)
    assert len(scanned) == len(stepped) == 4
    np.testing.assert_allclose(scanned, stepped, rtol=1e-5, atol=1e-6)


def test_one_program_run_matches_step_loop():
    # make_run_runner: the ENTIRE run in one program — shard_map regen
    # (ICI seed agreement included) scanned inside the jitted epochs loop
    # — must reproduce the per-step trajectory
    mesh = make_mesh(8)
    kw = dict(n_samples=64, window=16, batch_per_dp=2, steps_per_epoch=2,
              epochs=2)
    stepped = demo_training_run(mesh, TINY, **kw)
    whole = demo_training_run(mesh, TINY, one_program=True, **kw)
    assert len(whole) == len(stepped) == 4
    np.testing.assert_allclose(whole, stepped, rtol=1e-5, atol=1e-6)


def test_mixture_run_runner_matches_manual_epochs():
    """make_mixture_run_runner (the §8 whole-run program: mesh-sharded
    mixture regen scanned in-program) must reproduce the trajectory of
    manually driving make_epoch_runner over sharded_mixture_indices
    epoch by epoch — same model, same tokens, same seed."""
    from partiallyshuffledistributedsampler_tpu.models import (
        make_epoch_runner, make_mixture_run_runner,
    )
    from partiallyshuffledistributedsampler_tpu.models.train import (
        create_sharded_state,
    )
    from partiallyshuffledistributedsampler_tpu.ops.mixture import (
        MixtureSpec,
    )
    from partiallyshuffledistributedsampler_tpu.parallel import (
        make_seed_triple, sharded_mixture_indices,
    )

    mesh = make_mesh(8)
    spec = MixtureSpec([60, 40, 20], [3, 2, 1], windows=8, block=12)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (spec.total_sources_len, TINY.seq_len + 1),
        0, TINY.vocab_size, dtype=jnp.int32,
    )
    params, opt, tx = create_sharded_state(TINY, mesh, seed=3)
    run = make_mixture_run_runner(TINY, tx, mesh, 2, 2, 2, spec)
    triple = make_seed_triple(mesh, 5, 0, axis="dp")
    _p, _o, ls = run(params, opt, tokens, triple, jnp.int32(0))
    whole = np.asarray(ls).reshape(-1)

    params2, opt2, tx2 = create_sharded_state(TINY, mesh, seed=3)
    epoch_run = make_epoch_runner(TINY, tx2, mesh, 2, 2)
    manual = []
    for e in range(2):
        idx = sharded_mixture_indices(mesh, spec, 5, e, axis="dp")
        params2, opt2, el = epoch_run(params2, opt2, tokens, idx)
        manual.extend(float(l) for l in np.asarray(el))
    assert len(whole) == len(manual) == 4
    np.testing.assert_allclose(whole, manual, rtol=1e-5, atol=1e-6)


def test_training_deterministic_across_meshes():
    # dp=4,tp=2 vs dp=2,tp=2: same data order per epoch (the sampler contract
    # holds per dp-world); losses differ because dp-world differs — but a
    # fixed mesh rerun must be bit-reproducible.
    mesh = make_mesh(8)
    a = demo_training_run(mesh, TINY, n_samples=64, window=16,
                          batch_per_dp=2, steps_per_epoch=2, epochs=1)
    b = demo_training_run(mesh, TINY, n_samples=64, window=16,
                          batch_per_dp=2, steps_per_epoch=2, epochs=1)
    assert a == b
