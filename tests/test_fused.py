"""Serve-path fusion: the pipelined client is an optimisation, never a
semantics change.

The law (docs/SERVICE.md "Serve-path fusion"): with ``lookahead > 1``
the client keeps a window of GET_BATCH requests in flight and the
server's replies queue in the socket buffer — but the delivered stream
must stay bit-identical to the guarded request-reply path through every
hazard the guarded path survives, because the ack cursor advances only
on yield and everything in flight past it is unacked.  Covered here:

* multi-epoch pipelined streams bit-identical to ``spec.rank_indices``
  in all three spec modes, with the coalesced multi-frame send observed
  actually happening (the fast path engaged, not silently bypassed);
* a mid-stream reshard freeze with pipelined clients: the
  prefetched-but-unacked window is refused/discarded and replayed
  through the guarded path — the union law holds exactly-once;
* a primary hard-killed under pipelined clients: both ranks finish on
  the promoted standby bit-identically, zero degraded entries;
* the WELCOME ``max_inflight`` clamp on an over-eager ``lookahead``;
* the loader's ``boundary_prefetch`` arm bit-matching the serial arm.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.sampler.host_loader import (
    HostDataLoader,
)
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    ServiceIndexClient,
)
from partiallyshuffledistributedsampler_tpu.service import protocol as P

from test_elastic_service import (
    MAX_UNIT,
    assert_union_law,
    build_spec,
    epoch_union_ref,
)
from test_failover import replicated_pair, wait_synced

pytestmark = pytest.mark.fused


class _SendSpy:
    """Record the frame counts of every coalesced ``send_msgs`` call so a
    test can prove the pipelined window actually opened (>1 frame in one
    send), not just that the stream happened to be correct."""

    def __init__(self, monkeypatch):
        self.frame_counts = []
        real = P.send_msgs

        def spy(sock, msgs, **kw):
            self.frame_counts.append(len(msgs))
            return real(sock, msgs, **kw)

        monkeypatch.setattr(P, "send_msgs", spy)

    @property
    def coalesced(self):
        return max(self.frame_counts, default=0) > 1


# --------------------------------------------------- steady-state streams
@pytest.mark.parametrize("mode", ["plain", "mixture", "shard"])
def test_pipelined_stream_bit_identical_across_epochs(mode, monkeypatch):
    """Three consecutive epochs through one ``lookahead=4`` client are
    bit-identical to the spec, and the multi-frame coalesced send is
    observed (the fast path engaged across the epoch boundaries)."""
    spy = _SendSpy(monkeypatch)
    spec = build_spec(mode, 2)
    with IndexServer(spec) as srv:
        with ServiceIndexClient(srv.address, rank=0, batch=23,
                                lookahead=4) as c:
            for epoch in range(3):
                got = np.concatenate(list(c.epoch_batches(epoch)))
                ref = np.asarray(spec.rank_indices(epoch, 0))
                assert np.array_equal(got, ref), (
                    f"pipelined stream diverged at epoch {epoch} ({mode})")
            counters = c.metrics.report()["counters"]
    assert spy.coalesced, "the pipelined window never coalesced a send"
    # one RPC per delivered batch plus one guarded terminal EOF poll per
    # epoch — pipelining must not inflate the request count
    steps = sum(-(-len(np.asarray(spec.rank_indices(e, 0))) // 23)
                for e in range(3))
    assert counters["batches_served"] == steps
    assert counters["rpcs_per_step"] == steps + 3


def test_lookahead_clamped_by_welcome_max_inflight():
    """An over-eager ``lookahead`` is clamped to the server's WELCOME
    ``max_inflight`` advertisement; the stream stays exact."""
    spec = build_spec("plain", 1)
    with IndexServer(spec) as srv:
        with ServiceIndexClient(srv.address, rank=0, batch=64,
                                lookahead=4096) as c:
            got = np.concatenate(list(c.epoch_batches(0)))
            assert c._server_max_inflight is not None
            assert c._pipe_limit() <= c._server_max_inflight
        assert np.array_equal(got, np.asarray(spec.rank_indices(0, 0)))


# ------------------------------------------------------- reshard freeze
@pytest.mark.parametrize("mode", ["plain", "mixture", "shard"])
def test_reshard_freeze_replays_prefetched_unacked(mode):
    """A reshard barrier freezes the epoch while every client holds a
    pipelined window of prefetched-but-unacked batches.  Those replies
    are discarded unacked and re-requested through the guarded path, so
    the union of pre-barrier and post-barrier deliveries obeys the
    exactly-once union law — nothing dropped, nothing double-served
    beyond the wrap-pad allowance."""
    old_world, new_world = 4, 3
    spec = build_spec(mode, old_world)
    ref = epoch_union_ref(spec)
    delivered = {}
    lock = threading.Lock()
    b_hit = threading.Barrier(old_world)
    b_go = threading.Barrier(old_world)
    with IndexServer(spec) as srv:
        addr = srv.address

        def worker(r):
            got = []
            c = ServiceIndexClient(addr, rank=r, batch=23, lookahead=4,
                                   backoff_base=0.01,
                                   reconnect_timeout=20.0)
            try:
                it = c.epoch_batches(0)
                for _ in range(1 + r):
                    try:
                        got.append(next(it))
                    except StopIteration:
                        break
                b_hit.wait(timeout=30.0)
                if r == 0:
                    c.reshard(new_world)
                b_go.wait(timeout=30.0)
                for arr in it:
                    got.append(arr)
            finally:
                with lock:
                    delivered[r] = got
                c.close()

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(old_world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "pipelined reshard worker hung"
    union = np.concatenate(
        [np.concatenate(v) if v else np.empty(0, np.int64)
         for v in delivered.values()])
    assert_union_law(union, ref, new_world=new_world,
                     max_unit=MAX_UNIT[mode])


# ------------------------------------------------------------- failover
@pytest.mark.parametrize("mode", ["plain", "mixture", "shard"])
def test_failover_pipelined_streams_bit_identical(mode):
    """Primary hard-killed while both ranks hold pipelined windows: the
    in-flight prefetched batches die with the connection (all unacked),
    the clients replay them from the promoted standby, and the streams
    are bit-identical to an unkilled run with zero degraded entries."""
    spec = build_spec(mode, 2)
    primary, standby = replicated_pair(spec)
    delivered = {}
    lock = threading.Lock()
    b_streamed = threading.Barrier(3)
    b_killed = threading.Barrier(3)

    def worker(r):
        got = []
        c = ServiceIndexClient(primary.address, rank=r, batch=23, spec=spec,
                               lookahead=4, backoff_base=0.01,
                               reconnect_timeout=2.0)
        try:
            it = c.epoch_batches(0)
            got.append(next(it))
            b_streamed.wait(timeout=30.0)
            b_killed.wait(timeout=30.0)
            for arr in it:
                got.append(arr)
        finally:
            with lock:
                delivered[r] = (got, c.metrics.report()["counters"])
            c.close()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    try:
        for t in threads:
            t.start()
        b_streamed.wait(timeout=30.0)
        wait_synced(primary, standby)
        primary.kill()
        b_killed.wait(timeout=30.0)
        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "pipelined failover worker hung"
    finally:
        primary.kill()
        standby.stop()
    assert standby.role == "primary", "standby never promoted"
    for r in range(2):
        got, counters = delivered[r]
        ref = np.asarray(spec.rank_indices(0, r))
        assert np.array_equal(np.concatenate(got), ref), (
            f"rank {r} pipelined stream diverged across failover ({mode})")
        assert counters.get("degraded_mode", 0) == 0


# --------------------------------------------------- torn mid-pipeline
def test_connection_torn_mid_pipeline_resumes_exactly_once():
    """Tearing the socket while a pipelined window is in flight loses
    every queued reply — all unacked — and the guarded path replays them
    after the reconnect: one contiguous exactly-once stream."""
    spec = build_spec("plain", 1)
    with IndexServer(spec) as srv:
        with ServiceIndexClient(srv.address, rank=0, batch=23, lookahead=4,
                                backoff_base=0.01) as c:
            got = []
            it = c.epoch_batches(0)
            for _ in range(3):
                got.append(next(it))
            c._sock.shutdown(2)  # tear mid-window, replies still queued
            got.extend(it)
            counters = c.metrics.report()["counters"]
        assert counters.get("reconnects", 0) >= 1
        assert np.array_equal(np.concatenate(got),
                              np.asarray(spec.rank_indices(0, 0)))


# ------------------------------------------------ loader boundary ring
@pytest.mark.parametrize("mode", ["plain", "mixture", "shard"])
def test_boundary_prefetch_bit_identical(mode):
    """The loader's boundary-prefetch worker must be pure overlap: the
    epoch streams with ``boundary_prefetch`` on and off are identical in
    every spec mode, across the boundary the worker pre-computed."""
    kw = {"batch": 23, "seed": 7, "rank": 0, "world": 2}
    if mode == "plain":
        args, extra = (np.arange(997),), {"window": 64}
    elif mode == "mixture":
        from partiallyshuffledistributedsampler_tpu.ops.mixture import (
            MixtureSpec,
        )
        mx = MixtureSpec([400, 300, 200], [5, 3, 2], windows=32)
        args, extra = (np.arange(900),), {"mixture": mx,
                                          "epoch_samples": 600}
    else:
        sizes = [13, 7, 29, 17, 11, 23, 5, 19]
        args, extra = (np.arange(sum(sizes)),), {"window": 4,
                                                 "shard_sizes": sizes}
    serial = HostDataLoader(*args, boundary_prefetch=False, **kw, **extra)
    fused = HostDataLoader(*args, boundary_prefetch=True, **kw, **extra)
    for epoch in range(3):
        a = [np.asarray(b) for b in serial.epoch(epoch)]
        # give the boundary worker a chance to win the race so the
        # adopted-prefetch path (not just the fallback) is what's tested
        time.sleep(0.05)
        b = [np.asarray(x) for x in fused.epoch(epoch)]
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert np.array_equal(x, y), (
                f"boundary prefetch changed the stream at epoch {epoch}")


# ---------------------------------------- mid-stream window shrink (clamp)
class _WindowSpy:
    """Record the unacked span (``seq - ack``) every pipelined GET_BATCH
    commits to, split around a caller-flipped marker, so a test can
    prove the in-flight window both ramped AND later shrank."""

    def __init__(self, monkeypatch, limit_fn=None):
        self.spans = []          # (span, after_marker, adopted_limit)
        self.after = False
        real = P.send_msgs

        def spy(sock, msgs, **kw):
            lim = None if limit_fn is None else limit_fn()
            for m, h in msgs:
                if m == P.MSG_GET_BATCH:
                    self.spans.append((int(h["seq"]) - int(h["ack"]),
                                       self.after, lim))
            return real(sock, msgs, **kw)

        monkeypatch.setattr(P, "send_msgs", spy)

    def split(self):
        pre = [s for s, after, _ in self.spans if not after]
        post = [s for s, after, _ in self.spans if after]
        return pre, post


def test_pipelined_window_shrinks_on_midstream_clamp(monkeypatch):
    """A failover re-HELLO can adopt a SMALLER ``max_inflight`` while the
    pipelined generator is mid-stream: an already-ramped window must
    shrink to the new clamp — every request committed after the adoption
    stays within it (the limit is re-read each iteration, not latched at
    entry) — and the stream stays bit-identical."""
    spy = _WindowSpy(monkeypatch)
    spec = build_spec("plain", 1)
    with IndexServer(spec) as srv:
        with ServiceIndexClient(srv.address, rank=0, batch=16,
                                lookahead=8) as c:
            got = []
            for i, arr in enumerate(c.epoch_batches(0)):
                got.append(arr)
                if i == 9:
                    # what a concurrent failover re-HELLO would adopt
                    # from a peer advertising a smaller window
                    c._server_max_inflight = 2
                    spy.after = True
    pre, post = spy.split()
    assert max(pre) > 2, "the window never ramped past the later clamp"
    assert post, "no requests were committed after the clamp shrank"
    assert max(post) <= 2, (
        f"a request rode the stale pre-shrink window: spans {post} "
        "exceed the adopted max_inflight=2")
    assert np.array_equal(np.concatenate(got),
                          np.asarray(spec.rank_indices(0, 0)))


def test_failover_to_smaller_window_peer_never_overruns(monkeypatch):
    """The end-to-end contract behind the clamp: a ramped ``lookahead=8``
    client hard-loses its ``max_inflight=8`` primary and finishes on a
    ``max_inflight=2`` standby — the standby must never see an unacked
    span beyond its own advertisement (zero throttle refusals) and the
    stream stays bit-identical."""
    holder = {}
    spy = _WindowSpy(monkeypatch,
                     limit_fn=lambda: holder["c"]._server_max_inflight)
    spec = build_spec("plain", 1)
    standby = IndexServer(spec, role="standby", repl_feed_timeout=0.25,
                          max_inflight=2)
    standby.start()
    primary = IndexServer(spec, standby=standby.address,
                          repl_feed_timeout=0.25, max_inflight=8)
    primary.start()
    c = ServiceIndexClient(primary.address, rank=0, batch=16, lookahead=8,
                           backoff_base=0.01, reconnect_timeout=5.0)
    holder["c"] = c
    try:
        got = []
        for i, arr in enumerate(c.epoch_batches(0)):
            got.append(arr)
            if i == 9:
                wait_synced(primary, standby)
                primary.kill()
                spy.after = True
        counters = c.metrics.report()["counters"]
    finally:
        c.close()
        primary.kill()
        standby.stop()
    assert c._server_max_inflight == 2, "the standby's clamp never adopted"
    # spans committed to the dead primary's socket before the client saw
    # the reset never reach the standby; the contract binds every send
    # made AFTER the re-HELLO adopted the standby's advertisement
    post = [s for s, _, lim in spy.spans if lim == 2]
    assert post and max(post) <= 2, (
        f"the standby saw an unacked span beyond its window: {post}")
    assert counters.get("throttled", 0) == 0
    assert counters.get("failovers", 0) >= 1
    assert np.array_equal(np.concatenate(got),
                          np.asarray(spec.rank_indices(0, 0)))
