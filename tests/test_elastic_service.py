"""Elastic membership: coordinated mid-epoch resharding through the daemon.

The acceptance law (SPEC.md §6, served elastically): for a world change
``old_world -> new_world`` mid-epoch, the union of pre-barrier batches
delivered to the old ranks and post-barrier batches delivered to the new
ranks equals the uninterrupted epoch stream as a multiset, modulo the new
partition's wrap-padding — whose extras are bounded by ``new_world`` base
units (samples, or whole shards in shard mode) per committed reshard and
must replay existing epoch values, never invent or drop any.

Covered here: the explicit ``RESHARD`` matrix over (4,3), (3,5), (8,2) ×
all three spec modes; ``LEAVE`` preemption drains (graceful and
grace-expired); membership-timeout eviction with an injected clock; the
kill-the-daemon-between-barrier-and-first-post-reshard-batch resume from
snapshot v2; a two-reshard cascade with restarts between; protocol
version negotiation; the typed ``ReshardInProgress`` back-pressure; and
``HostDataLoader`` riding through a world change with its degraded-mode
composition bit-matching the live composite stream.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import warnings
from collections import Counter

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops.mixture import MixtureSpec
from partiallyshuffledistributedsampler_tpu.sampler.host_loader import (
    HostDataLoader,
)
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    PartialShuffleSpec,
    ServiceIndexClient,
)
from partiallyshuffledistributedsampler_tpu.service import protocol as P
from partiallyshuffledistributedsampler_tpu.service.client import (
    ReshardInProgress,
    ServiceError,
)

pytestmark = pytest.mark.elastic

_SHARD_SIZES = [13, 7, 29, 17, 11, 23, 5, 19, 31, 37, 3, 41, 43, 9, 21, 15]


def build_spec(mode, world):
    if mode == "plain":
        return PartialShuffleSpec.plain(997, window=64, seed=7, world=world)
    if mode == "mixture":
        mx = MixtureSpec([400, 300, 200], [5, 3, 2], windows=32)
        return PartialShuffleSpec.mixture(mx, seed=7, world=world,
                                          epoch_samples=600)
    return PartialShuffleSpec.shard(_SHARD_SIZES, window=4, seed=7,
                                    world=world)


#: wrap-pad extras come in whole base units: one sample, or one shard
MAX_UNIT = {"plain": 1, "mixture": 1, "shard": max(_SHARD_SIZES)}


def epoch_union_ref(spec, epoch=0):
    return np.concatenate([np.asarray(spec.rank_indices(epoch, r))
                           for r in range(spec.world)])


def assert_union_law(union, ref, *, new_world, max_unit, reshards=1):
    """No epoch value missing; extras bounded by the wrap-pad allowance
    and drawn only from values the epoch actually contains."""
    combined = Counter(np.asarray(union).tolist())
    full = Counter(np.asarray(ref).tolist())
    missing = full - combined
    assert not missing, (
        f"dropped epoch values: {list(missing.items())[:8]}")
    extras = combined - full
    n_extra = sum(extras.values())
    assert n_extra <= reshards * new_world * max_unit, (
        f"{n_extra} extras exceed the wrap-pad allowance "
        f"{reshards} x {new_world} x {max_unit}")
    assert set(extras) <= set(full), "extras invented unknown values"


# ------------------------------------------------------ RESHARD matrix
@pytest.mark.parametrize("mode", ["plain", "mixture", "shard"])
@pytest.mark.parametrize("old_world,new_world", [(4, 3), (3, 5), (8, 2)])
def test_reshard_matrix_exactly_once(mode, old_world, new_world):
    """Live threaded clients, barrier frozen mid-stream: union of old
    ranks' pre-barrier and new ranks' post-barrier deliveries is the
    uninterrupted epoch modulo wrap-padding, for shrink AND growth."""
    spec = build_spec(mode, old_world)
    ref = epoch_union_ref(spec)
    delivered = {}
    lock = threading.Lock()
    b_hit = threading.Barrier(old_world)
    b_go = threading.Barrier(old_world)
    with IndexServer(spec) as srv:
        addr = srv.address

        def worker(r):
            got = []
            c = ServiceIndexClient(addr, rank=r, batch=23,
                                   backoff_base=0.01,
                                   reconnect_timeout=20.0)
            try:
                it = c.epoch_batches(0)
                for _ in range(1 + r):
                    try:
                        got.append(next(it))
                    except StopIteration:
                        break
                b_hit.wait(timeout=30.0)
                if r == 0:
                    c.reshard(new_world)
                b_go.wait(timeout=30.0)
                for arr in it:
                    got.append(arr)
            finally:
                with lock:
                    delivered[r] = got
                c.close()

        def joiner(j):
            c = ServiceIndexClient(addr, rank=None, batch=23,
                                   backoff_base=0.01,
                                   reconnect_timeout=20.0)
            try:
                got = list(c.epoch_batches(0))
            finally:
                with lock:
                    delivered[("joiner", j)] = got
                c.close()

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(old_world)]
        for t in threads:
            t.start()
        if new_world > old_world:
            time.sleep(0.6)  # let the barrier commit before joiners dial
            for j in range(new_world - old_world):
                jt = threading.Thread(target=joiner, args=(j,))
                jt.start()
                threads.append(jt)
        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "elastic worker hung"
        snap = srv._state_dict()
    assert snap["generation"] == 1
    assert len(snap["layers"]) == 1 and snap["layers"][0][0] == old_world
    union = np.concatenate(
        [np.concatenate(v) if v else np.empty(0, np.int64)
         for v in delivered.values()])
    assert_union_law(union, ref, new_world=new_world,
                     max_unit=MAX_UNIT[mode])


# ----------------------------------------------------------- LEAVE drain
@pytest.mark.parametrize("mode", ["plain", "mixture", "shard"])
def test_leave_drains_to_barrier_then_terminal_eof(mode):
    """A LEAVE keeps serving the leaver its pre-barrier allocation, ends
    its stream with the terminal drain eof, and the displaced survivor
    adopts the freed slot — 2 -> 1 has no wrap-pad, so the union is
    exactly the uninterrupted epoch."""
    spec = build_spec(mode, 2)
    ref = epoch_union_ref(spec)
    with IndexServer(spec) as srv:
        c0 = ServiceIndexClient(srv.address, rank=0, batch=31,
                                backoff_base=0.01, reconnect_timeout=10.0)
        c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                                backoff_base=0.01, reconnect_timeout=10.0)
        try:
            it0 = c0.epoch_batches(0)
            it1 = c1.epoch_batches(0)
            got0 = [next(it0)]
            got1 = [next(it1), next(it1)]
            rep = c0.leave(grace_ms=60_000)
            assert rep["reshard"] is True
            assert rep["target_world"] == 1
            target = rep["target_samples"]
            assert target is not None and target >= 31
            got0.extend(it0)  # drains to the barrier, then terminal eof
            leaver = np.concatenate(got0)
            assert len(leaver) == target
            assert np.array_equal(
                leaver, np.asarray(spec.rank_indices(0, 0))[:target])
            got1.extend(it1)  # displaced; rejoins as the world-1 rank 0
            assert c1.generation == 1
            assert c1.rank == 0 and c1.world == 1
            assert c1.metrics.report()["counters"].get(
                "reshards_ridden", 0) >= 1
            union = np.concatenate([leaver, np.concatenate(got1)])
            assert np.array_equal(np.sort(union), np.sort(ref))
            counters = srv.metrics.report()["counters"]
            assert counters.get("leaves", 0) >= 1
            assert counters.get("reshards", 0) == 1
            assert counters.get("orphaned", 0) == 0
        finally:
            c0.close()
            c1.close()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def test_leave_grace_expiry_orphans_the_remainder():
    """A leaver that stops consuming past its grace deadline is declared
    dead; its unserved span becomes orphan descriptors served as the new
    rank 0's prefix — nothing is lost."""
    spec = build_spec("plain", 2)
    ref = epoch_union_ref(spec)
    clk = FakeClock()
    srv = IndexServer(spec, clock=clk)
    srv.start()
    c0 = ServiceIndexClient(srv.address, rank=0, batch=31,
                            backoff_base=0.01, reconnect_timeout=10.0)
    c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                            backoff_base=0.01, reconnect_timeout=10.0)
    try:
        it0 = c0.epoch_batches(0)
        it1 = c1.epoch_batches(0)
        got0 = [next(it0)]
        got1 = [next(it1), next(it1)]
        rep = c0.leave(grace_ms=100)
        assert rep["reshard"] is True
        c1.heartbeat()  # flush the survivor's delivered ack: it drains
        # the leaver goes silent instead of draining; its grace expires
        clk.t += 1.0
        srv._sweep_leases()
        snap = srv._state_dict()
        assert snap["generation"] == 1
        assert snap["orphans"], "grace expiry must orphan the remainder"
        assert srv.metrics.report()["counters"].get("orphaned", 0) == 31
        got1.extend(it1)  # adopts rank 0: orphan prefix + world-1 stream
        union = np.concatenate(got0 + got1)
        assert np.array_equal(np.sort(union), np.sort(ref))
    finally:
        c0.close()
        c1.close()
        srv.stop()


def test_membership_timeout_evicts_vacant_rank_and_reshards():
    """A rank whose lease stays vacant past membership_timeout is
    resharded out by the sweep — no LEAVE, no RESHARD RPC — and its
    consumed watermark bounds the orphaned span."""
    spec = build_spec("plain", 2)
    ref = epoch_union_ref(spec)
    clk = FakeClock()
    srv = IndexServer(spec, membership_timeout=5.0, clock=clk)
    srv.start()
    c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                            backoff_base=0.01, reconnect_timeout=10.0)
    try:
        c0 = ServiceIndexClient(srv.address, rank=0, batch=31)
        it0 = c0.epoch_batches(0)
        got0 = [next(it0)]
        c0.close()  # preempted without notice: lease goes vacant
        it1 = c1.epoch_batches(0)
        got1 = [next(it1), next(it1)]
        clk.t += 6.0
        srv._sweep_leases()  # triggers the eviction reshard (drain phase)
        c1.heartbeat()       # survivor's delivered ack completes the drain
        snap = srv._state_dict()
        assert snap["generation"] == 1, "sweep must trigger the reshard"
        assert srv.metrics.report()["counters"].get("reshard_triggers",
                                                    0) >= 1
        got1.extend(it1)
        union = np.concatenate(got0 + got1)
        assert np.array_equal(np.sort(union), np.sort(ref))
    finally:
        c1.close()
        srv.stop()


# ------------------------------------------------- kill + restart resume
@pytest.mark.parametrize("mode", ["plain", "shard"])
def test_kill_restart_between_barrier_and_first_post_batch(mode, tmp_path):
    """The daemon dies right after the barrier commits and before any
    post-reshard batch is served; the restarted daemon resumes the
    cascade from snapshot v2 and the union law still holds."""
    spec = build_spec(mode, 4)
    ref = epoch_union_ref(spec)
    snap_path = str(tmp_path / "snap.json")
    srv = IndexServer(spec, snapshot_path=snap_path, snapshot_interval=1)
    host, port = srv.start()
    clients = [ServiceIndexClient((host, port), rank=r, batch=23,
                                  backoff_base=0.01, reconnect_timeout=20.0)
               for r in range(4)]
    its = [c.epoch_batches(0) for c in clients]
    srv2 = None
    try:
        pre = {r: [next(its[r]), next(its[r])] for r in range(4)}
        rep = clients[0].reshard(3)
        # the barrier commits only on ACKED delivery, and acks trail the
        # last delivered batch by one request — never inside the trigger
        assert rep["committed"] is False
        # drain every rank to its clamped per-rank target (in shard mode
        # the barrier cuts on whole SHARDS, so the targets differ), then
        # flush the final delivery acks by heartbeat — the last commits
        targets = {int(r): int(t)
                   for r, t in srv._reshard["targets"].items()}
        for r in range(4):
            need = targets[r] - 46
            while need > 0:
                arr = next(its[r])
                pre[r].append(arr)
                need -= len(arr)
            assert need == 0, "drain overshot the barrier target"
        for c in clients:
            c.heartbeat()
        state = json.loads(open(snap_path).read())
        assert state["format"] == 2
        assert state["generation"] == 1
        assert len(state["layers"]) == 1 and state["layers"][0][0] == 4
        srv.stop()  # killed before ANY post-reshard batch was served
        srv2 = IndexServer(spec, host=host, port=port,
                           snapshot_path=snap_path, snapshot_interval=1)
        srv2.start()
        post = {}
        for r in range(3):
            post[r] = list(its[r])
            got = (np.concatenate(post[r]) if post[r]
                   else np.empty(0, np.int64))
            want = np.asarray(spec.with_world(3).rank_indices(
                0, r, layers=[tuple(state["layers"][0])]))
            assert np.array_equal(got, want), f"rank {r} post-reshard"
        # the displaced rank finds no free unserved slot and bows out
        post[3] = list(its[3])
        assert post[3] == []
        assert clients[3].metrics.report()["counters"].get(
            "membership_lost", 0) >= 1
        union = np.concatenate(
            [np.concatenate(pre[r]) for r in range(4)]
            + [np.concatenate(post[r]) for r in range(3)])
        assert_union_law(union, ref, new_world=3, max_unit=MAX_UNIT[mode])
    finally:
        for c in clients:
            c.close()
        srv.stop()
        if srv2 is not None:
            srv2.stop()


def test_cascading_reshards_with_restart_between():
    """Two successive world changes mid-remainder (4 -> 3 -> 2) with the
    daemon killed and restarted after each commit: the cascade layers
    stack per SPEC.md §6 and every generation's stream is bit-exact."""
    spec = build_spec("plain", 4)
    ref = epoch_union_ref(spec)
    snap_path = None
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        snap_path = td + "/snap.json"
        srv = IndexServer(spec, snapshot_path=snap_path, snapshot_interval=1)
        host, port = srv.start()
        delivered = []

        # generation 0: four ranks consume equally, then the world shrinks
        gen0 = [ServiceIndexClient((host, port), rank=r, batch=23)
                for r in range(4)]
        its = [c.epoch_batches(0) for c in gen0]
        for it in its:
            delivered.append(next(it))
            delivered.append(next(it))
        assert gen0[0].reshard(3)["committed"] is False
        for c in gen0:
            c.heartbeat()  # flush delivery acks; the last one commits
        for c in gen0:
            c.close()
        srv.stop()

        srv = IndexServer(spec, host=host, port=port,
                          snapshot_path=snap_path, snapshot_interval=1)
        srv.start()
        layers1 = [(4, 46)]
        gen1 = [ServiceIndexClient((host, port), rank=r, batch=23)
                for r in range(3)]
        its = [c.epoch_batches(0) for c in gen1]
        for r, it in enumerate(its):
            arr = next(it)
            want = np.asarray(spec.with_world(3).rank_indices(
                0, r, layers=layers1))[:23]
            assert np.array_equal(arr, want), f"gen1 rank {r}"
            delivered.append(arr)
        assert gen1[0].reshard(2)["committed"] is False
        for c in gen1:
            c.heartbeat()  # flush delivery acks; the last one commits
        state = json.loads(open(snap_path).read())
        assert state["format"] == 2
        assert [tuple(l) for l in state["layers"]] == [(4, 46), (3, 23)]
        for c in gen1:
            c.close()
        srv.stop()

        srv = IndexServer(spec, host=host, port=port,
                          snapshot_path=snap_path, snapshot_interval=1)
        srv.start()
        layers2 = [(4, 46), (3, 23)]
        gen2 = [ServiceIndexClient((host, port), rank=r, batch=23)
                for r in range(2)]
        try:
            for r, c in enumerate(gen2):
                got = c.epoch_indices(0)
                want = np.asarray(spec.with_world(2).rank_indices(
                    0, r, layers=layers2))
                assert np.array_equal(got, want), f"gen2 rank {r}"
                delivered.append(got)
        finally:
            for c in gen2:
                c.close()
            srv.stop()
    union = np.concatenate(delivered)
    # two committed reshards: each contributes at most its new world's
    # wrap-pad (plain mode: one sample per pad slot)
    assert_union_law(union, ref, new_world=3, max_unit=1, reshards=2)


# ------------------------------------------------------ typed back-pressure
def test_reshard_in_progress_is_a_typed_error():
    """A rank that drained to its barrier target cannot wait forever on
    a straggler: past its retry deadline it surfaces ReshardInProgress
    (a ServiceError with code 'reshard'), not a hang."""
    spec = build_spec("plain", 2)
    with IndexServer(spec) as srv:
        c0 = ServiceIndexClient(srv.address, rank=0, batch=31,
                                backoff_base=0.01, reconnect_timeout=10.0)
        c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                                backoff_base=0.01, reconnect_timeout=0.6)
        try:
            it0 = c0.epoch_batches(0)
            next(it0)  # the straggler: behind the barrier, never drains
            it1 = c1.epoch_batches(0)
            next(it1)
            next(it1)
            assert c1.reshard(1)["committed"] is False
            t0 = time.monotonic()
            with pytest.raises(ReshardInProgress) as ei:
                next(it1)
            assert time.monotonic() - t0 < 8.0
            assert isinstance(ei.value, ServiceError)
            assert ei.value.code == "reshard"
            assert c1.metrics.report()["counters"].get(
                "reshard_waits", 0) >= 1
        finally:
            c0.close()
            c1.close()


def test_fresh_autoclaim_refuses_partially_served_slot():
    """The double-delivery guard: a displaced client's rank=-1 rejoin
    must not adopt a slot whose current-generation stream was already
    partly served — replaying it from seq 0 would duplicate batches."""
    spec = build_spec("plain", 2)
    with IndexServer(spec) as srv:
        c0 = ServiceIndexClient(srv.address, rank=0, batch=31,
                                backoff_base=0.01, reconnect_timeout=10.0)
        c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                                backoff_base=0.01, reconnect_timeout=10.0)
        try:
            it0 = c0.epoch_batches(0)
            it1 = c1.epoch_batches(0)
            got0 = [next(it0), next(it0)]
            got1 = [next(it1), next(it1)]
            assert c0.reshard(1)["committed"] is False
            c1.heartbeat()  # c1's delivered ack: its drain completes
            got0.append(next(it0))  # c0's own ack commits; first
            # post-reshard batch arrives through the `resharded` adopt
            c0.close()  # lease freed, but the slot is partly served
            # wait for the server to process c0's disconnect: until the
            # lease release lands, the resharded header c1 is about to
            # draw reflects a still-live slot 0 and the auto-claim path
            # under test never runs
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with srv._lock:
                    lease = srv._leases.get(0)
                    if lease is None or lease.get("owner") is None:
                        break
                time.sleep(0.01)
            rest1 = list(it1)  # displaced; the only slot is not adoptable
            assert rest1 == []
            assert c1.rank is None
            assert c1.metrics.report()["counters"].get(
                "membership_lost", 0) >= 1
        finally:
            c0.close()
            c1.close()


def test_protocol_version_mismatch_is_refused_with_both_ints():
    spec = build_spec("plain", 1)
    with IndexServer(spec) as srv:
        sock = socket.create_connection(srv.address, timeout=5.0)
        try:
            P.send_msg(sock, P.MSG_HELLO,
                       {"proto": 1, "rank": 0, "batch": 32})
            msg, header, _ = P.recv_msg(sock)
        finally:
            sock.close()
    assert msg == P.MSG_ERROR
    assert header["code"] == "protocol_version"
    assert header["server_proto"] == P.PROTOCOL_VERSION
    assert header["client_proto"] == 1


# ----------------------------------------- barrier/delivery race regressions
def test_freeze_race_does_not_double_serve():
    """A GET_BATCH already past its admission check when the barrier
    freezes must not deliver an unclamped batch beyond the frozen
    watermarks: the counting tail refuses it and the retry is served
    clamped — no span rides both the pre-commit stream and the
    repartitioned remainder."""
    spec = build_spec("plain", 2)
    ref = epoch_union_ref(spec)
    srv = IndexServer(spec)
    srv.start()
    in_window = threading.Event()
    go = threading.Event()
    armed = threading.Event()
    real = srv._rank_array

    def stalled_rank_array(epoch, rank):
        arr = real(epoch, rank)
        if rank == 0 and armed.is_set():
            # hold THIS request between its admission check and its
            # counting tail while the barrier freezes underneath it
            armed.clear()
            in_window.set()
            go.wait(timeout=30.0)
        return arr

    srv._rank_array = stalled_rank_array
    c0 = ServiceIndexClient(srv.address, rank=0, batch=31,
                            backoff_base=0.01, reconnect_timeout=20.0)
    c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                            backoff_base=0.01, reconnect_timeout=20.0)
    got0, got1 = [], []
    try:
        it0 = c0.epoch_batches(0)
        it1 = c1.epoch_batches(0)
        got0.extend([next(it0), next(it0)])
        got1.append(next(it1))
        armed.set()
        t0 = threading.Thread(target=lambda: got0.extend(it0))
        t0.start()
        assert in_window.wait(timeout=30.0), "race window never opened"
        # barrier freezes at rank 0's watermark 62 while its seq-2
        # request is paused holding an unclamped [62, 93) slice
        assert c1.reshard(1)["committed"] is False
        go.set()
        got1.extend(it1)  # drains rank 1, commits, bows out displaced
        t0.join(timeout=60.0)
        assert not t0.is_alive(), "rank 0 hung riding the freeze race"
        assert c0.generation == 1 and c0.rank == 0
    finally:
        c0.close()
        c1.close()
        srv.stop()
    union = np.concatenate(got0 + got1)
    # 2 -> 1 has no wrap-pad: any double-served span shows as an extra
    assert np.array_equal(np.sort(union), np.sort(ref))


def test_lost_final_drain_reply_stays_resendable():
    """The barrier commits on ACKED delivery: a rank whose final
    pre-barrier reply was lost can resend it after the drain began —
    the un-acked past-target request draws a retryable error, never a
    commit that drops the span."""
    spec = build_spec("plain", 2)
    with IndexServer(spec) as srv:
        c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                                backoff_base=0.01, reconnect_timeout=10.0)
        sock = socket.create_connection(srv.address, timeout=5.0)
        try:
            P.send_msg(sock, P.MSG_HELLO,
                       {"proto": P.PROTOCOL_VERSION, "rank": 0,
                        "batch": 31})
            msg, header, _ = P.recv_msg(sock)
            assert msg == P.MSG_WELCOME

            def get(seq, ack):
                P.send_msg(sock, P.MSG_GET_BATCH,
                           {"rank": 0, "epoch": 0, "seq": seq,
                            "ack": ack, "gen": 0})
                return P.recv_msg(sock)

            _, h0, p0 = get(0, -1)
            _, h1, p1 = get(1, 0)   # delivered... but imagine it lost
            it1 = c1.epoch_batches(0)
            next(it1), next(it1)
            c1.heartbeat()          # rank 1 acks its full 62: drained
            assert c1.reshard(1)["committed"] is False
            # rank 0 asks past its target WITHOUT acking seq 1: the
            # commit must wait (acked watermark 31 < target 62)
            msg, h, _ = get(2, 0)
            assert msg == P.MSG_ERROR and h["code"] == "reshard"
            assert srv._state_dict()["generation"] == 0
            # the lost reply is resent, bit-identical, mid-drain
            msg, h1b, p1b = get(1, 0)
            assert msg == P.MSG_BATCH and p1b == p1
            # only the ack past the target completes the drain
            msg, h, _ = get(2, 1)
            assert msg == P.MSG_ERROR and h["code"] == "resharded"
            assert srv._state_dict()["generation"] == 1
        finally:
            sock.close()
            c1.close()


def test_restored_drain_times_out_missing_participant(tmp_path):
    """A daemon restarted mid-drain seeds the membership_timeout clock
    for every un-drained participant, so a drain whose leaver never
    reconnects commits (orphaning the remainder) instead of
    deadlocking every survivor forever."""
    spec = build_spec("plain", 2)
    ref = epoch_union_ref(spec)
    snap_path = str(tmp_path / "snap.json")
    srv = IndexServer(spec, snapshot_path=snap_path, snapshot_interval=1)
    host, port = srv.start()
    c0 = ServiceIndexClient((host, port), rank=0, batch=31,
                            backoff_base=0.01, reconnect_timeout=10.0)
    c1 = ServiceIndexClient((host, port), rank=1, batch=31,
                            backoff_base=0.01, reconnect_timeout=10.0)
    clk = FakeClock()
    srv2 = None
    try:
        it0 = c0.epoch_batches(0)
        it1 = c1.epoch_batches(0)
        got0 = [next(it0)]
        got1 = [next(it1), next(it1)]
        assert c0.leave()["reshard"] is True  # no grace bound at all
        srv.stop()  # killed mid-drain; the leaver never comes back
        c0.close()
        srv2 = IndexServer(spec, host=host, port=port,
                           snapshot_path=snap_path, snapshot_interval=1,
                           membership_timeout=5.0, clock=clk)
        srv2.start()
        assert srv2._reshard is not None
        # the survivor already served its full pre-barrier target before
        # the restart; only the delivered ack is outstanding — an idle
        # heartbeat (re-leasing on reconnect) completes its drain
        c1.heartbeat()
        assert srv2._state_dict()["generation"] == 0
        clk.t += 6.0
        srv2._sweep_leases()     # vacancy clock expired: rank 0 is dead
        snap = srv2._state_dict()
        assert snap["generation"] == 1, "restored drain must time out"
        assert snap["orphans"], "dead leaver's remainder must be orphaned"
        got1.extend(it1)         # adopts rank 0: orphan prefix + stream
        union = np.concatenate(got0 + got1)
        assert np.array_equal(np.sort(union), np.sort(ref))
    finally:
        c0.close()
        c1.close()
        srv.stop()
        if srv2 is not None:
            srv2.stop()


def test_trigger_failure_after_freeze_unfreezes(monkeypatch):
    """An exception anywhere between the freeze and the drain flip —
    including the per-rank target computation — resets the in-flight
    reshard instead of leaving the server frozen (every request drawing
    an endless retry) until restart."""
    spec = build_spec("plain", 2)
    with IndexServer(spec) as srv:
        c0 = ServiceIndexClient(srv.address, rank=0, batch=31,
                                backoff_base=0.01, reconnect_timeout=10.0)
        c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                                backoff_base=0.01, reconnect_timeout=10.0)
        try:
            it0 = c0.epoch_batches(0)
            it1 = c1.epoch_batches(0)
            got0 = [next(it0)]
            got1 = [next(it1)]
            real_inc = srv.metrics.inc

            def boom(name, *a, **kw):
                if name == "reshard_triggers":
                    raise RuntimeError("injected target-computation fault")
                return real_inc(name, *a, **kw)

            monkeypatch.setattr(srv.metrics, "inc", boom)
            with pytest.raises(RuntimeError):
                srv._trigger_reshard(1)
            monkeypatch.setattr(srv.metrics, "inc", real_inc)
            assert srv._reshard is None, "failed trigger left a freeze"
            # not bricked: both streams still serve to their epoch end
            got0.extend(it0)
            got1.extend(it1)
            assert np.array_equal(
                np.concatenate(got0), np.asarray(spec.rank_indices(0, 0)))
            assert np.array_equal(
                np.concatenate(got1), np.asarray(spec.rank_indices(0, 1)))
        finally:
            c0.close()
            c1.close()


# --------------------------------------------- loader ride-through + degraded
def test_loader_rides_through_world_change_and_degraded_composition():
    """HostDataLoader(index_client=...) sees one contiguous epoch across
    a server-driven world change; once the daemon dies, the degraded
    fallback recomposes the SAME stream from the adopted membership."""
    spec = build_spec("plain", 2)
    X = np.arange(997, dtype=np.int64)
    srv = IndexServer(spec)
    srv.start()
    c1 = ServiceIndexClient(srv.address, rank=1, batch=31,
                            backoff_base=0.01, reconnect_timeout=10.0)
    c0 = ServiceIndexClient(srv.address, rank=0, batch=31,
                            backoff_base=0.01, reconnect_timeout=0.6)
    loader = HostDataLoader(X, window=64, batch=64, seed=7, rank=0, world=2,
                            index_client=c0)
    try:
        it1 = c1.epoch_batches(0)
        got1 = [next(it1), next(it1)]
        assert c1.leave(grace_ms=60_000)["reshard"] is True
        got1.extend(it1)  # leaver drains to its barrier, terminal eof
        # the loader's epoch pull crosses the commit transparently
        live = loader.epoch_indices(0)
        assert c0.generation == 1 and c0.world == 1
        assert not loader.degraded
        expected = np.concatenate([
            np.asarray(spec.rank_indices(0, 0))[:62],
            np.asarray(spec.with_world(1).rank_indices(
                0, 0, layers=[(2, 62)])),
        ])
        assert np.array_equal(live, expected)
        union = np.concatenate(got1 + [live])
        assert np.array_equal(np.sort(union),
                              np.sort(epoch_union_ref(spec)))
        # daemon gone: the degraded composition must reproduce the live
        # elastic stream from the client's membership trail
        srv.stop()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            degraded0 = loader.epoch_indices(0)
            degraded1 = loader.epoch_indices(1)
        assert loader.degraded
        assert np.array_equal(degraded0, live)
        # epochs after the elastic one are plain new-world partitions
        assert np.array_equal(
            degraded1,
            np.asarray(spec.with_world(1).rank_indices(1, 0)))
    finally:
        c0.close()
        c1.close()
        srv.stop()
