"""autopilot/: closed-loop self-tuning and the elastic shard map.

The acceptance laws (docs/AUTOPILOT.md): the policy is a *deterministic*
function of its state and the windowed observation — same trajectory on
every replay, including on a promoted standby that inherited the WAL's
``autopilot`` records; knob tunes converge the transport batch toward
the target RPC rate on the BASELINE workload shapes; structural moves
(split / merge / migrate) never change served bits — a stream folded
across any shard-map transform is bit-identical to a static single
``IndexServer``; and a disabled autopilot costs zero protocol bytes.

Covered here: policy convergence on two BASELINE workload shapes under a
fake clock; decision determinism + ``state_dict`` replay; the shed arm
scaling the typed-backpressure table; the ``BackpressurePolicy`` table
itself; metric ``snapshot()``/``delta()``; live knob tuning end-to-end
(WELCOME/heartbeat → client adoption at an epoch boundary); the
split-under-hotspot drill with no operator action; merge + migrate
bit-identity vs a single server; controller-state inheritance across a
primary kill; and chaos coverage for every new fault site.
"""

from __future__ import annotations

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu import faults as F
from partiallyshuffledistributedsampler_tpu.autopilot import (
    Autopilot,
    AutopilotPolicy,
    PolicyConfig,
)
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    PartialShuffleSpec,
    ServiceIndexClient,
)
from partiallyshuffledistributedsampler_tpu.service.backpressure import (
    DEFAULT_RETRY_MS,
    MAX_RETRY_MS,
    BackpressurePolicy,
)
from partiallyshuffledistributedsampler_tpu.sharding import ShardPlane
from partiallyshuffledistributedsampler_tpu.utils.metrics import (
    MetricsRegistry,
    histogram_delta,
    registry_delta,
)

from test_failover import replicated_pair, wait_for, wait_synced

pytestmark = pytest.mark.autopilot


class FakeClock:
    """Deterministic monotonic seconds for policy/controller tests."""

    def __init__(self, t0: float = 100.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += float(dt)
        return self.t


# --------------------------------------------------------------- policy
#: two BASELINE.json workload shapes (configs[0] and [1]): total sample
#: throughput the serving plane sustains, and the batch clients start at
BASELINE_WORKLOADS = [
    # "CIFAR-10 torchvision DDP, window=512, 2 ranks (CPU reference)"
    pytest.param(50_000.0, 512, id="cifar10-w512-2ranks"),
    # "ImageNet-1k ResNet-50 DDP, window=8192, 8 TPU v4 chips"
    pytest.param(160_000.0, 1024, id="imagenet-w8192-8chips"),
]


def _run_tune_loop(policy, clock, throughput, batch, ticks=32):
    """Simulate the observe→decide→adopt loop: each tick serves one
    second of the workload at the currently adopted batch."""
    trajectory = []
    for _ in range(ticks):
        clock.advance(1.0)
        served = max(1, int(throughput / batch))
        obs = {"now": clock(), "window_s": 1.0, "served": served,
               "throttled": 0, "batch": batch}
        for d in policy.decide(obs):
            assert d.kind == "tune"
            if "batch_hint" in d.args:
                batch = int(d.args["batch_hint"])
        trajectory.append(batch)
    return trajectory


@pytest.mark.parametrize("throughput,batch0", BASELINE_WORKLOADS)
def test_policy_batch_converges_on_baseline_workloads(throughput, batch0):
    """On both BASELINE shapes the tune arm converges the transport
    batch to a fixpoint whose RPC rate sits inside the target band
    (target/4, target], and then goes quiet — no oscillation."""
    cfg = PolicyConfig(min_batch=256)
    clock = FakeClock()
    policy = AutopilotPolicy(cfg, clock=clock)
    traj = _run_tune_loop(policy, clock, throughput, batch0)
    settled = traj[-8:]
    assert len(set(settled)) == 1, f"batch oscillates: {traj}"
    rate = throughput / settled[-1]
    assert rate <= cfg.target_rpc_per_s
    assert rate > cfg.target_rpc_per_s / 4 or settled[-1] == cfg.max_batch


def test_policy_decisions_deterministic_and_replayable():
    """Same config + same observation sequence → the identical decision
    list; and a fresh policy loading a mid-run ``state_dict`` continues
    the exact trajectory (the WAL-replay law)."""
    cfg = PolicyConfig(min_batch=256, calm_ticks_to_narrow=2)
    obs_seq = [
        {"now": 10.0 + i, "window_s": 1.0, "served": 400, "throttled": t,
         "batch": 512}
        for i, t in enumerate([0, 6, 0, 0, 5, 0, 0, 0])
    ]

    def run(policy, seq):
        return [policy.decide(dict(o)) for o in seq]

    a = run(AutopilotPolicy(cfg, clock=FakeClock()), obs_seq)
    b = run(AutopilotPolicy(cfg, clock=FakeClock()), obs_seq)
    assert a == b
    # replay: snapshot after 4 ticks, resume a fresh policy from it
    p1 = AutopilotPolicy(cfg, clock=FakeClock())
    head = run(p1, obs_seq[:4])
    mid = p1.state_dict()
    p2 = AutopilotPolicy(cfg, clock=FakeClock())
    p2.load_state_dict(mid)
    assert run(p1, obs_seq[4:]) == run(p2, obs_seq[4:])
    assert head  # the head produced decisions at all (tune + shed)


def test_policy_shed_arm_scales_and_decays():
    """Sustained throttle refusals double the shed scale up to the cap;
    calm windows decay it back to 1 — classic AIMD-shaped hysteresis."""
    cfg = PolicyConfig(shed_threshold=4, max_shed_scale=8.0)
    clock = FakeClock()
    policy = AutopilotPolicy(cfg, clock=clock)
    scales = []
    for throttled in [8, 8, 8, 8, 0, 0, 0, 0]:
        clock.advance(1.0)
        policy.decide({"now": clock(), "window_s": 1.0, "served": 10,
                       "throttled": throttled, "batch": 1024,
                       "max_inflight": 64})
        scales.append(policy.state_dict()["scale"])
    assert scales[:4] == [2.0, 4.0, 8.0, 8.0]  # capped at max_shed_scale
    assert scales[-1] == 1.0                   # fully decayed when calm


def test_policy_structural_decisions():
    """The shard-map arm picks, in fixed priority: split the hottest
    qualifying shard, merge the coldest adjacent pair, migrate across a
    hot/cold boundary — with deterministic tie-breaks and one shared
    cooldown."""
    cfg = PolicyConfig(hot_factor=1.5, cold_factor=0.25, split_p99_ms=5.0,
                       struct_cooldown_s=0.0)
    clock = FakeClock()
    policy = AutopilotPolicy(cfg, clock=clock)

    def struct(shards):
        clock.advance(1.0)
        ds = policy.decide({"now": clock(), "window_s": 1.0,
                            "served": 0, "throttled": 0,
                            "shards": shards})
        return [d for d in ds if d.kind in ("split", "merge", "migrate")]

    # hot + slow + wide enough → split wins
    ds = struct({0: {"served": 300, "lo": 0, "hi": 4, "ranks": 4,
                     "p99_ms": 30.0},
                 1: {"served": 10, "lo": 4, "hi": 8, "ranks": 4,
                     "p99_ms": 1.0}})
    assert [d.kind for d in ds] == ["split"] and ds[0].target == 0
    # two cold adjacent shards fold into the lower slice
    ds = struct({0: {"served": 300, "lo": 0, "hi": 4, "ranks": 4},
                 1: {"served": 1, "lo": 4, "hi": 6, "ranks": 2},
                 2: {"served": 2, "lo": 6, "hi": 8, "ranks": 2}})
    assert [d.kind for d in ds] == ["merge"]
    assert ds[0].args == {"into": 1, "frm": 2}
    # hot-but-narrow-p99 shard next to a cold one → migrate a quarter
    ds = struct({0: {"served": 300, "lo": 0, "hi": 5, "ranks": 5,
                     "p99_ms": 0.0},
                 1: {"served": 10, "lo": 5, "hi": 8, "ranks": 3,
                     "p99_ms": 0.0}})
    assert [d.kind for d in ds] == ["migrate"]
    assert ds[0].args == {"frm": 0, "to": 1, "count": 1}


def test_policy_requires_injected_clock():
    with pytest.raises(ValueError):
        AutopilotPolicy(PolicyConfig())


# --------------------------------------------------------- backpressure
def test_backpressure_table_covers_every_typed_refusal():
    bp = BackpressurePolicy()
    for site, ms in DEFAULT_RETRY_MS.items():
        assert bp.retry_ms(site) == ms
    with pytest.raises(KeyError):
        bp.retry_ms("not_a_refusal_site")


def test_backpressure_scale_and_clamps():
    bp = BackpressurePolicy()
    base = bp.retry_ms("standby")
    bp.set_scale(4.0)
    assert bp.retry_ms("standby") == base * 4
    bp.set_scale(1e9)            # clamped to the table's max factor
    assert bp.scale == 256.0
    assert bp.retry_ms("standby") == MAX_RETRY_MS
    bp.set_scale(0.0)            # never below 1: hints only slow down
    assert bp.scale == 1.0
    bp.set("standby", 75)
    assert bp.retry_ms("standby") == 75
    rep = bp.report()
    assert rep["scale"] == 1.0 and rep["retry_ms"]["standby"] == 75


# ------------------------------------------------------ metric windows
def test_histogram_snapshot_delta_windows():
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms")
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    snap = h.snapshot()
    for v in (100.0, 200.0):
        h.observe(v)
    d = h.delta(snap)
    assert d["count"] == 2
    assert d["p99_ms"] >= 100.0  # the window sees only the new samples
    full = histogram_delta(h.snapshot(), None)
    assert full["count"] == 5


def test_registry_snapshot_delta_windows():
    reg = MetricsRegistry()
    reg.inc("served", 5)
    snap = reg.snapshot()
    reg.inc("served", 3)
    reg.histogram("t_ms").observe(7.0)
    d = registry_delta(reg.snapshot(), snap)
    assert d["counters"]["served"] == 3
    assert d["histograms"]["t_ms"]["count"] == 1


# ------------------------------------------------------------ knob arm
def test_knob_rail_is_zero_protocol_bytes_until_a_controller_acts():
    """With no autopilot attached the wire is byte-identical to the
    pre-autopilot build: WELCOME carries no ``batch_hint``, heartbeat
    replies carry no ``knobs`` — until ``set_autopilot_knobs`` flips
    the advertisement on."""
    spec = PartialShuffleSpec.plain(2048, window=128, world=2)
    with IndexServer(spec, port=0) as srv:
        with ServiceIndexClient(srv.address, rank=0, batch=64,
                                spec=spec) as c:
            c.set_epoch(0)
            assert c._batch_hint is None
            assert c.heartbeat() is not None
            assert c._batch_hint is None
            assert c._server_max_inflight == srv.max_inflight
            srv.set_autopilot_knobs(max_inflight=4, batch_hint=128)
            c.heartbeat()
            assert c._server_max_inflight == 4
            assert c._batch_hint == 128


def test_controller_tunes_live_server_and_client_adopts():
    """End-to-end knob loop: the controller observes a hot RPC rate,
    emits a tune, the knobs ride the heartbeat, and an ``auto_batch``
    client adopts the larger batch at the next epoch boundary — the
    folded epoch stays bit-identical."""
    spec = PartialShuffleSpec.plain(4096, window=256, world=2)
    clock = FakeClock()
    with IndexServer(spec, port=0) as srv:
        ap = Autopilot(server=srv, clock=clock,
                       config=PolicyConfig(min_batch=64, target_rpc_per_s=1.0))
        with ServiceIndexClient(srv.address, rank=0, batch=64, spec=spec,
                                auto_batch=True) as c:
            c.set_epoch(0)
            e0 = np.concatenate(list(c.epoch_batches(0)))
            clock.advance(1.0)
            decisions = ap.tick()
            assert [d.kind for d in decisions] == ["tune"]
            assert decisions[0].args["batch_hint"] == 128
            c.heartbeat()
            assert c._batch_hint == 128
            c.set_epoch(1)
            e1 = np.concatenate(list(c.epoch_batches(1)))
            assert c.batch == 128, "client never adopted the tuned batch"
            # a transport-batch change never changes served bits
            assert np.array_equal(e0, np.asarray(spec.rank_indices(0, 0)))
            assert np.array_equal(e1, np.asarray(spec.rank_indices(1, 0)))
        st = ap.status()
        assert st["batch_hint"] == 128
        assert st["policy"]["seq"] == 1
        reg = srv.metrics.registry.report()["counters"]
        assert reg["autopilot_decisions"] == 1
        assert reg["autopilot_tunes"] == 1


def test_controller_shed_scales_backpressure_table():
    """A throttle storm observed by the controller scales every typed
    ``retry_ms`` hint through the shared ``BackpressurePolicy``; the
    tenant engines see the same scaled table (one object, not copies)."""
    spec = PartialShuffleSpec.plain(1024, window=64, world=2)
    clock = FakeClock()
    with IndexServer(spec, port=0) as srv:
        ap = Autopilot(server=srv, clock=clock,
                       config=PolicyConfig(shed_threshold=1))
        base = srv.backpressure.retry_ms("throttle")
        srv.metrics.registry.inc("throttled", 8)
        clock.advance(1.0)
        kinds = [d.kind for d in ap.tick()]
        assert "shed" in kinds
        assert srv.backpressure.retry_ms("throttle") == base * 2
        # calm window decays the scale back toward 1
        clock.advance(1.0)
        ap.tick()
        assert srv.backpressure.retry_ms("throttle") == base


# -------------------------------------------------------- elastic plane
def _epoch(addr, rank, spec, epoch, **kw):
    kw.setdefault("batch", 64)
    kw.setdefault("backoff_base", 0.01)
    with ServiceIndexClient(addr, rank=rank, spec=spec, **kw) as c:
        if rank == 0:
            c.set_epoch(epoch)
        return np.concatenate(list(c.epoch_batches(epoch)))


def _single_server_ref(spec, epochs):
    ref = {}
    with IndexServer(spec, port=0) as srv:
        for e in epochs:
            for r in range(spec.world):
                ref[(e, r)] = _epoch(srv.address, r, spec, e)
    return ref


def test_split_under_hotspot_without_operator_action():
    """Drive a skewed load (only shard 0's ranks stream), let the
    controller observe the hotspot and split it — no operator call —
    then verify the next epoch is still bit-identical to a static
    single server."""
    spec = PartialShuffleSpec.plain(4096, window=256, world=8)
    ref = _single_server_ref(spec, epochs=(0, 1))
    clock = FakeClock()
    with ShardPlane(spec, 2) as plane:
        ap = Autopilot(
            plane=plane, clock=clock,
            config=PolicyConfig(hot_factor=1.5, split_p99_ms=0.0,
                                struct_cooldown_s=0.0,
                                target_rpc_per_s=1e9))
        clock.advance(1.0)
        ap.tick()                       # baseline window (no decision data)
        # hotspot: shard 0 owns ranks 0..3; only those stream epoch 0
        for r in range(4):
            assert np.array_equal(
                _epoch(plane.address, r, spec, 0), ref[(0, r)])
        clock.advance(1.0)
        decisions = ap.tick()
        kinds = [d.kind for d in decisions]
        assert "split" in kinds, f"no split under hotspot: {decisions}"
        assert plane.map.n_shards == 3
        assert plane.map.version >= 2
        # every rank's NEXT epoch is bit-identical on the wider plane
        for r in range(8):
            assert np.array_equal(
                _epoch(plane.address, r, spec, 1), ref[(1, r)])
        reg = plane.shards[0].metrics.registry.report()["counters"]
        assert reg["autopilot_splits"] == 1


def test_merge_and_migrate_streams_bit_identical():
    """Fold a 3-shard plane down to 2 (merge), then shift boundary
    ranks (migrate): every epoch folded across both transforms is
    bit-identical to a static single ``IndexServer``; clients that were
    attached to the merged-out shard re-route themselves."""
    spec = PartialShuffleSpec.plain(4096, window=256, world=6)
    ref = _single_server_ref(spec, epochs=(0, 1, 2))
    with ShardPlane(spec, 3) as plane:
        for r in range(6):
            assert np.array_equal(
                _epoch(plane.address, r, spec, 0), ref[(0, r)])
        plane.merge_shards(1, 2)
        assert plane.map.n_shards == 3  # slot kept, slice emptied
        assert sum(1 for lo, hi in plane.map.slices if hi > lo) == 2
        for r in range(6):
            assert np.array_equal(
                _epoch(plane.address, r, spec, 1), ref[(1, r)])
        plane.migrate_ranks(0, 1, 1)
        for r in range(6):
            assert np.array_equal(
                _epoch(plane.address, r, spec, 2), ref[(2, r)])


def test_migration_moves_live_cursors_mid_epoch():
    """A client streaming THROUGH a migration keeps its exactly-once
    cursor: the WAL-replay handoff moves the cursor to the new owner
    and the ``wrong_shard`` redirect lands the client on it."""
    spec = PartialShuffleSpec.plain(4096, window=256, world=4)
    ref = _single_server_ref(spec, epochs=(0,))
    with ShardPlane(spec, 2) as plane:
        with ServiceIndexClient(plane.address, rank=1, batch=64, spec=spec,
                                backoff_base=0.01) as c:
            c.set_epoch(0)
            it = c.epoch_batches(0)
            got = [next(it), next(it)]
            plane.migrate_ranks(0, 1, 1)    # rank 1 changes owner mid-epoch
            got.extend(it)
            assert np.array_equal(np.concatenate(got), ref[(0, 1)])
            counters = c.metrics.report()["counters"]
            assert counters.get("wrong_shard_redirects", 0) >= 1


# ---------------------------------------------------------- WAL replay
def test_promoted_standby_inherits_controller_state():
    """Tune decisions are WAL-logged with the policy's state; after the
    primary dies and the standby promotes, a controller attached to the
    promoted server RESUMES the trajectory (same seq, same knobs) — the
    replayed decisions are the logged ones, not a restart from zero."""
    spec = PartialShuffleSpec.plain(2048, window=128, world=2)
    primary, standby = replicated_pair(spec)
    clock = FakeClock()
    try:
        ap = Autopilot(server=primary, clock=clock,
                       config=PolicyConfig(min_batch=64,
                                           target_rpc_per_s=1.0))
        with ServiceIndexClient(primary.address, rank=0, batch=64,
                                spec=spec) as c:
            c.set_epoch(0)
            list(c.epoch_batches(0))
            clock.advance(1.0)
            ap.tick()
            clock.advance(1.0)
            list(c.epoch_batches(0))
            ap.tick()
        want = ap.policy.state_dict()
        assert want["seq"] >= 1 and want["batch_hint"] is not None
        wait_synced(primary, standby)
        primary.kill()
        # promote once the feed is observably stale (what a failing-over
        # client's HELLO would trigger)
        wait_for(lambda: standby._try_promote() or
                 standby.role == "primary")
        # the mirror applied the autopilot records: knobs + state both
        assert standby.autopilot_state() == want
        assert standby._batch_hint == want["batch_hint"]
        ap2 = Autopilot(server=standby, clock=clock)
        assert ap2.policy.state_dict() == want
        nxt = ap2.policy._emit("tune")
        assert nxt.seq == want["seq"] + 1   # continues, never restarts
    finally:
        primary.kill()
        standby.stop()


# --------------------------------------------------------------- chaos
@pytest.mark.chaos
def test_chaos_decide_fault_skips_one_tick():
    """An injected ``autopilot.decide`` fault costs exactly one tick:
    counted, no decision, no crash — and the next tick proceeds."""
    spec = PartialShuffleSpec.plain(1024, window=64, world=2)
    clock = FakeClock()
    with IndexServer(spec, port=0) as srv:
        ap = Autopilot(server=srv, clock=clock,
                       config=PolicyConfig(min_batch=64,
                                           target_rpc_per_s=1.0))
        with ServiceIndexClient(srv.address, rank=0, batch=64,
                                spec=spec) as c:
            c.set_epoch(0)
            list(c.epoch_batches(0))
            with F.FaultPlan([F.FaultRule("autopilot.decide",
                                          "error")]) as plan:
                clock.advance(1.0)
                assert ap.tick() == []
                assert plan.fired("autopilot.decide") == 1
            reg = srv.metrics.registry.report()["counters"]
            assert reg["autopilot_decide_errors"] == 1
            list(c.epoch_batches(0))
            clock.advance(1.0)
            assert [d.kind for d in ap.tick()] == ["tune"]


@pytest.mark.chaos
def test_chaos_split_fault_leaves_map_unchanged():
    """A fault at ``shard.split`` aborts the split atomically: the map
    keeps its version, streams keep serving, and a retry succeeds."""
    spec = PartialShuffleSpec.plain(2048, window=128, world=4)
    with ShardPlane(spec, 2) as plane:
        v0, n0 = plane.map.version, plane.map.n_shards
        with F.FaultPlan([F.FaultRule("shard.split", "error")]) as plan:
            with pytest.raises(F.InjectedFault):
                plane.split_shard(0)
            assert plan.fired("shard.split") == 1
        assert (plane.map.version, plane.map.n_shards) == (v0, n0)
        assert _epoch(plane.address, 0, spec, 0).size > 0
        assert plane.split_shard(0) == 2     # clean retry goes through


@pytest.mark.chaos
def test_chaos_migrate_fault_aborts_two_phase_handoff():
    """A fault at ``shard.migrate`` (the router's two-phase remap)
    aborts the handoff: no shard adopts the new map, the frozen ranks
    thaw, and the same migration succeeds on retry."""
    spec = PartialShuffleSpec.plain(2048, window=128, world=4)
    ref = _single_server_ref(spec, epochs=(0,))
    with ShardPlane(spec, 2) as plane:
        v0 = plane.map.version
        with F.FaultPlan([F.FaultRule("shard.migrate", "error")]) as plan:
            with pytest.raises(F.InjectedFault):
                plane.migrate_ranks(0, 1, 1)
            assert plan.fired("shard.migrate") == 1
        assert plane.map.version == v0
        for srv in plane.shards:
            assert srv.shard_map.version == v0
            assert not srv._migrating
        plane.migrate_ranks(0, 1, 1)
        for r in range(4):
            assert np.array_equal(
                _epoch(plane.address, r, spec, 0), ref[(0, r)])


@pytest.mark.chaos
def test_chaos_failed_actuation_not_wal_logged():
    """A decision whose actuation dies (injected ``shard.split`` fault)
    is counted and dropped — never WAL-logged, so a replayed standby
    cannot re-apply a move that never happened."""
    spec = PartialShuffleSpec.plain(4096, window=256, world=8)
    clock = FakeClock()
    with ShardPlane(spec, 2) as plane:
        ap = Autopilot(
            plane=plane, clock=clock,
            config=PolicyConfig(hot_factor=1.5, split_p99_ms=0.0,
                                struct_cooldown_s=0.0,
                                target_rpc_per_s=1e9))
        clock.advance(1.0)
        ap.tick()
        for r in range(4):
            _epoch(plane.address, r, spec, 0)
        with F.FaultPlan([F.FaultRule("shard.split", "error")]) as plan:
            clock.advance(1.0)
            actuated = ap.tick()
            assert plan.fired("shard.split") == 1
        assert all(d.kind != "split" for d in actuated)
        assert plane.map.n_shards == 2
        lead = plane.shards[0]
        assert lead.autopilot_state() is None or \
            lead.metrics.registry.report()["counters"].get(
                "autopilot_splits", 0) == 0
        assert lead.metrics.registry.report()["counters"][
            "autopilot_decide_errors"] >= 1
