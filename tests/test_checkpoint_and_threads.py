"""Checkpoint helpers + concurrency (the 'race defense' of SURVEY.md §5:
determinism plus a thread-safety check on the jit cache)."""

import threading

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu import PartiallyShuffleDistributedSampler
from partiallyshuffledistributedsampler_tpu.ops import cpu
from partiallyshuffledistributedsampler_tpu.utils import (
    load_sampler_state,
    save_sampler_state,
)


def test_state_roundtrip_through_file(tmp_path):
    s = PartiallyShuffleDistributedSampler(
        500, num_replicas=2, rank=0, window=32, seed=11, backend="cpu"
    )
    s.set_epoch(6)
    p = str(tmp_path / "sampler.json")
    save_sampler_state(p, s.state_dict(consumed=42))

    s2 = PartiallyShuffleDistributedSampler(
        500, num_replicas=2, rank=0, window=32, backend="cpu"
    )
    s2.load_state_dict(load_sampler_state(p))
    assert s2.seed == 11 and s2.epoch == 6
    assert list(s2) == cpu.epoch_indices_np(500, 32, 11, 6, 0, 2)[42:].tolist()


def test_save_is_atomic(tmp_path):
    p = str(tmp_path / "s.json")
    save_sampler_state(p, {"spec_version": 1, "seed": 0, "epoch": 0, "offset": 0})
    save_sampler_state(p, {"spec_version": 1, "seed": 9, "epoch": 3, "offset": 1})
    assert load_sampler_state(p)["seed"] == 9
    # no stray tmp files
    leftovers = [f for f in tmp_path.iterdir() if f.suffix == ".tmp"]
    assert not leftovers


def test_concurrent_epoch_generation_threads():
    """Many threads hammering the jitted regen (same + different configs)
    must all get bit-correct results — guards the lru_cache + jit dispatch
    path against races (DataLoader workers / prefetch threads do this)."""
    from partiallyshuffledistributedsampler_tpu.ops.xla import epoch_indices_jax

    errors = []

    def worker(rank, epoch, n):
        try:
            got = np.asarray(epoch_indices_jax(n, 64, 5, epoch, rank, 4))
            ref = cpu.epoch_indices_np(n, 64, 5, epoch, rank, 4)
            np.testing.assert_array_equal(got, ref)
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [
        threading.Thread(target=worker, args=(r, e, n))
        for r in range(4)
        for e in range(3)
        for n in (1000, 2048)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]


def _regen_threads():
    return [t for t in threading.enumerate()
            if t.name == "psds-regen-prefetch" and t.is_alive()]


def test_set_epoch_hammer_does_not_accumulate_threads():
    """Hammering set_epoch (schedulers re-announce the epoch; elastic
    controllers jump around) must keep at most ONE live regen thread —
    each respawn now retires the stale prefetch first, and a same-epoch
    call skips the respawn entirely."""
    s = PartiallyShuffleDistributedSampler(
        200_000, num_replicas=2, rank=0, window=512, seed=3, backend="cpu"
    )
    for i in range(50):
        s.set_epoch(i % 7)
    assert len(_regen_threads()) <= 1
    # same-epoch repeat keeps the in-flight prefetch (no respawn)
    s.set_epoch(99)
    pending = s._pending
    s.set_epoch(99)
    assert s._pending is pending
    # and the stream is still the hammered-to epoch's, bit-correct
    assert list(s) == cpu.epoch_indices_np(200_000, 512, 3, 99, 0, 2)[:len(s)].tolist()


def test_mixture_set_epoch_hammer_does_not_accumulate_threads():
    from partiallyshuffledistributedsampler_tpu.ops import mixture as M
    from partiallyshuffledistributedsampler_tpu.sampler import (
        PartialShuffleMixtureSampler,
    )

    s = PartialShuffleMixtureSampler(
        [40_000, 20_000], [2, 1], num_replicas=2, rank=0, seed=5,
        windows=16, block=100, backend="cpu"
    )
    for i in range(50):
        s.set_epoch(i % 7)
    assert len(_regen_threads()) <= 1
    s.set_epoch(42)
    pending = s._pending
    s.set_epoch(42)
    assert s._pending is pending
    spec = M.MixtureSpec([40_000, 20_000], [2, 1], windows=16, block=100)
    ref = M.mixture_epoch_indices_np(spec, 5, 42, 0, 2)
    assert np.array_equal(np.fromiter(iter(s), dtype=np.int64), ref)
