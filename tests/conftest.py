"""Test bootstrap: force a virtual 8-device CPU platform BEFORE jax imports.

SURVEY.md §4 invariant 8: multi-device semantics are testable without a pod
via ``--xla_force_host_platform_device_count``.  The environment ships
``JAX_PLATFORMS=axon`` (one emulated TPU); tests override to CPU for speed
and parallelism-under-test.  bench.py and __graft_entry__.py do NOT import
this and keep the real device.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Pytest plugins may import jax before this file runs, freezing the config
# defaults from the *original* env — override the live config too.  This must
# happen before the first backend use (device queries in fixtures), which it
# does because conftest precedes all test imports.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
