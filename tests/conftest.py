"""Test bootstrap: force a virtual 8-device CPU platform BEFORE jax imports.

SURVEY.md §4 invariant 8: multi-device semantics are testable without a pod
via ``--xla_force_host_platform_device_count``.  The environment ships
``JAX_PLATFORMS=axon`` (one emulated TPU); tests override to CPU for speed
and parallelism-under-test.  bench.py and __graft_entry__.py do NOT import
this and keep the real device.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Pytest plugins may import jax before this file runs, freezing the config
# defaults from the *original* env — override the live config too.  This must
# happen before the first backend use (device queries in fixtures), which it
# does because conftest precedes all test imports.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

from partiallyshuffledistributedsampler_tpu.analysis import lockorder  # noqa: E402

#: tests in these groups drive the threaded service stack and must not
#: leave non-daemon threads behind (docs/ANALYSIS.md "Thread-leak gate")
_LEAK_CHECKED_MARKS = ("failover", "tenancy", "chaos", "elastic",
                       "telemetry", "durability", "sharding", "capability",
                       "streaming", "autopilot")


@pytest.fixture(autouse=True)
def _concurrency_sanitizer(request):
    """Per-test concurrency gates.

    * Thread leaks: for service/failover/tenancy-style tests (any
      ``_LEAK_CHECKED_MARKS`` marker, or a ``test_service*`` module), any
      non-daemon thread alive after teardown that was not alive before
      the test fails it, with the leaked thread's current stack.
    * Lock order: under ``PSDS_SANITIZE=1`` every test additionally
      fails if it recorded a new lock-order cycle (potential deadlock),
      with both acquisition stacks rendered.
    """
    leak_checked = (
        any(request.node.get_closest_marker(m) is not None
            for m in _LEAK_CHECKED_MARKS)
        or "test_service" in request.node.nodeid
    )
    baseline = lockorder.thread_snapshot() if leak_checked else None
    violations_before = (len(lockorder.violations())
                        if lockorder.is_enabled() else 0)
    yield
    if lockorder.is_enabled():
        new = lockorder.violations()[violations_before:]
        if new:
            pytest.fail(
                "lock-order cycle(s) recorded during this test:\n"
                + lockorder.render_violations(new), pytrace=False)
    if baseline is not None:
        leaked = lockorder.leaked_threads(baseline)
        if leaked:
            stacks = lockorder.thread_stacks(leaked)
            pytest.fail(
                "non-daemon thread(s) leaked by this test:\n" + "\n".join(
                    f"--- {name} ---\n{stack}"
                    for name, stack in stacks.items()), pytrace=False)


def assert_exactly_once(consumed_vals, remainder_vals, stream, old_world,
                        consumed, partition, new_world):
    """SPEC.md §6's exactly-once law, assertable from outputs alone:
    consumed prefix + all new ranks' remainders must equal the full epoch
    stream as a multiset, plus exactly the wrap-pad count of extras, and
    every extra must be a value from the UNCONSUMED portion of the stream
    (an implementation padding with already-consumed indices must fail).
    Shared by test_elastic_and_state.py and test_hypothesis_properties.py;
    lives here so neither test file imports the other."""
    from collections import Counter

    import numpy as np

    total = len(stream)
    ns_old = total // old_world
    R = total - consumed * old_world
    ns_new = -(-R // new_world)
    n_extra = ns_new * new_world - R
    combined = Counter(consumed_vals) + Counter(remainder_vals)
    full = Counter(stream.tolist())
    missing = full - combined
    assert not missing, f"missing epoch values: {list(missing.items())[:5]}"
    extras = combined - full
    assert sum(extras.values()) == n_extra, (sum(extras.values()), n_extra)
    if partition == "strided":
        unconsumed = stream[old_world * consumed:]
    else:  # blocked: each old rank consumed the head of its block
        p = np.arange(total)
        unconsumed = stream[(p % ns_old) >= consumed]
    allowed = Counter(unconsumed.tolist())
    assert not (extras - allowed), "wrap-pad extras not from the remainder"
