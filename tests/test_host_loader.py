"""HostDataLoader: prefetched host-gather → device batches.

Law under test: the served batches are exactly the sampler stream
(epoch_indices_np) cut into batch slices and gathered from the host
arrays — across dict/single-array data, tail handling, resume offsets,
index backends, and early consumer exit (no hung prefetch thread).
"""

import threading

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops.cpu import epoch_indices_np
from partiallyshuffledistributedsampler_tpu.sampler import HostDataLoader

N, WINDOW, BATCH, WORLD = 530, 32, 64, 2


def ref_batches(epoch, rank=0, drop_last_batch=True, start_step=0):
    idx = epoch_indices_np(N, WINDOW, 0, epoch, rank, WORLD)
    whole = len(idx) // BATCH
    steps = whole if drop_last_batch else -(-len(idx) // BATCH)
    return [idx[s * BATCH:(s + 1) * BATCH] for s in range(start_step, steps)]


def make(data=None, **kw):
    if data is None:
        data = {"x": np.arange(N * 3).reshape(N, 3), "y": np.arange(N)}
    kw.setdefault("window", WINDOW)
    kw.setdefault("batch", BATCH)
    kw.setdefault("world", WORLD)
    return HostDataLoader(data, **kw)


@pytest.mark.parametrize("depth", [1, 3])
def test_batches_match_sampler_stream(depth):
    loader = make(depth=depth)
    got = list(loader.epoch(2))
    refs = ref_batches(2)
    assert len(got) == len(refs) == loader.steps_per_epoch
    for b, sl in zip(got, refs):
        assert np.array_equal(np.asarray(b["x"]), np.arange(N * 3).reshape(N, 3)[sl])
        assert np.array_equal(np.asarray(b["y"]), sl)


def test_single_array_mode():
    loader = make(data=np.arange(N))
    got = list(loader.epoch(0))
    for b, sl in zip(got, ref_batches(0)):
        assert np.array_equal(np.asarray(b), sl)


def test_batches_live_on_device():
    import jax

    b = next(iter(make().epoch(0)))
    assert isinstance(b["x"], jax.Array)


def test_tail_batch_served_when_asked():
    loader = make(drop_last_batch=False)
    got = list(loader.epoch(1))
    refs = ref_batches(1, drop_last_batch=False)
    assert len(got) == len(refs)
    assert len(np.asarray(got[-1]["y"])) == len(refs[-1])  # short tail
    assert np.array_equal(np.asarray(got[-1]["y"]), refs[-1])
    # default: tail dropped
    assert len(list(make().epoch(1))) == len(ref_batches(1))


def test_start_step_resume_matches_uninterrupted_tail():
    loader = make()
    full = [np.asarray(b["y"]) for b in loader.epoch(3)]
    resumed = [np.asarray(b["y"]) for b in loader.epoch(3, start_step=2)]
    assert len(resumed) == len(full) - 2
    for a, b in zip(resumed, full[2:]):
        assert np.array_equal(a, b)


def test_epoch_variation_and_rank_partition():
    X = np.arange(N)
    a = np.concatenate([np.asarray(b) for b in
                        make(data=X, drop_last_batch=False).epoch(0)])
    b = np.concatenate([np.asarray(x) for x in
                        make(data=X, drop_last_batch=False).epoch(1)])
    assert not np.array_equal(a, b)  # reseed reshuffles
    r1 = np.concatenate([np.asarray(x) for x in
                         make(data=X, rank=1, drop_last_batch=False).epoch(0)])
    assert sorted(set(a.tolist()) | set(r1.tolist())) == list(range(N))


@pytest.mark.parametrize("backend", ["xla", "native"])
def test_index_backends_bit_identical(backend):
    try:
        got = list(make(index_backend=backend).epoch(2))
    except Exception as exc:  # native toolchain may be absent
        if backend == "native":
            pytest.skip(f"native backend unavailable: {exc!r}")
        raise
    for b, sl in zip(got, ref_batches(2)):
        assert np.array_equal(np.asarray(b["y"]), sl)


def test_index_backend_auto_resolves_and_matches():
    loader = make(index_backend="auto")
    assert loader.index_backend in ("cpu", "native", "xla")
    for b, sl in zip(loader.epoch(2), ref_batches(2)):
        assert np.array_equal(np.asarray(b["y"]), sl)


def test_early_break_retires_prefetch_thread():
    loader = make(depth=2)
    before = {t.name for t in threading.enumerate()}
    it = loader.epoch(0)
    next(it)
    it.close()  # consumer abandons the epoch
    for t in threading.enumerate():
        if t.name == "psds-host-prefetch" and t not in before:
            t.join(timeout=5.0)
            assert not t.is_alive(), "prefetch thread leaked"


def test_gather_error_surfaces_to_consumer():
    class Bad(HostDataLoader):
        def epoch_indices(self, epoch, layers=None):
            return np.full(self.num_samples, N + 999)  # out of bounds

    loader = Bad({"x": np.arange(N)}, window=WINDOW, batch=BATCH, world=WORLD)
    with pytest.raises(IndexError):
        list(loader.epoch(0))


def test_gather_error_keeps_original_traceback_under_full_queue():
    """Regression: the producer hits an error while the queue is FULL
    (consumer asleep, depth=1) — the error must still reach the consumer
    carrying the producer's original traceback, not a re-wrapped one."""
    import time

    from partiallyshuffledistributedsampler_tpu import faults as F

    loader = make(depth=1)
    plan = F.FaultPlan([F.FaultRule(site="loader.prefetch", kind="error",
                                    nth=3)])
    with plan:
        it = loader.epoch(0)
        next(it)  # start the producer
        # producer: batch 2 queued (queue full), then the injected error
        # at step 3 must wait for queue space behind it
        time.sleep(0.3)
        with pytest.raises(F.InjectedFault) as ei:
            for _ in it:
                pass
    assert plan.fired("loader.prefetch") == 1
    names = []
    tb = ei.value.__traceback__
    while tb is not None:
        names.append(tb.tb_frame.f_code.co_name)
        tb = tb.tb_next
    # the producer's frames survived the thread hop
    assert "produce" in names, names
    assert "perform" in names, names


def test_validation_errors():
    with pytest.raises(ValueError, match="leading dims"):
        make(data={"x": np.arange(10), "y": np.arange(11)})
    with pytest.raises(ValueError, match="depth"):
        make(depth=0)
    with pytest.raises(ValueError, match="index_backend"):
        make(index_backend="gpu")
    with pytest.raises(ValueError, match="rank"):
        make(rank=5)
    with pytest.raises(ValueError, match="start_step"):
        next(make().epoch(0, start_step=999))
    with pytest.raises(ValueError, match="at least one"):
        HostDataLoader({}, window=8, batch=4)


# ------------------------------------------------- round-5 stream tiers
def test_mixture_loader_concatenated_matches_sampler():
    """mixture=spec over ONE concatenated pytree: batches must be the §8
    stream gathered from the concatenated id space, bit-equal to
    mixture_epoch_indices_np cut into batch slices."""
    from partiallyshuffledistributedsampler_tpu.ops.mixture import (
        MixtureSpec, mixture_epoch_indices_np,
    )

    spec = MixtureSpec([200, 100, 300], [3, 1, 2], windows=16, block=30)
    total = spec.total_sources_len
    X = np.arange(total * 2).reshape(total, 2)
    loader = HostDataLoader({"x": X}, batch=32, world=2, rank=1,
                            mixture=spec, window=None)
    ref = mixture_epoch_indices_np(spec, 0, 4, 1, 2)
    got = list(loader.epoch(4))
    whole = len(ref) // 32
    assert len(got) == whole == loader.steps_per_epoch
    for s, b in enumerate(got):
        assert np.array_equal(np.asarray(b["x"]), X[ref[s*32:(s+1)*32]])


def test_mixture_loader_per_source_data_matches_concatenated():
    """The per-source data form (one pytree per corpus, gathered via
    spec.decompose) must serve the SAME batches as the concatenated
    form — the C4 multi-corpus shape never concatenates on the host."""
    from partiallyshuffledistributedsampler_tpu.ops.mixture import (
        MixtureSpec,
    )

    spec = MixtureSpec([200, 100, 300], [3, 1, 2], windows=16, block=30)
    total = spec.total_sources_len
    X = np.arange(total * 2).reshape(total, 2)
    parts = np.split(X, np.cumsum(spec.sources)[:-1])
    cat = HostDataLoader({"x": X}, batch=32, world=2, rank=0, mixture=spec)
    per = HostDataLoader([{"x": p} for p in parts], batch=32, world=2,
                         rank=0, mixture=spec)
    for a, b in zip(cat.epoch(1), per.epoch(1)):
        assert np.array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
    # bare per-source arrays serve unwrapped batches
    bare = HostDataLoader([p for p in parts], batch=32, world=2, rank=0,
                          mixture=spec)
    for a, b in zip(cat.epoch(2), bare.epoch(2)):
        assert np.array_equal(np.asarray(a["x"]), np.asarray(b))


def test_mixture_loader_epoch_samples_and_validation():
    from partiallyshuffledistributedsampler_tpu.ops.mixture import (
        MixtureSpec, mixture_epoch_indices_np,
    )

    spec = MixtureSpec([200, 100], [1, 1], windows=16, block=10)
    X = np.arange(300)
    loader = HostDataLoader(X, batch=25, mixture=spec, epoch_samples=700)
    ref = mixture_epoch_indices_np(spec, 0, 0, 0, 1, epoch_samples=700)
    got = np.concatenate([np.asarray(b) for b in loader.epoch(0)])
    assert np.array_equal(got, X[ref[:len(got)]])
    with pytest.raises(ValueError, match="window"):
        HostDataLoader(X, batch=25, mixture=spec, window=64)
    from partiallyshuffledistributedsampler_tpu.ops import native as _nat
    if _nat.available():
        nat = HostDataLoader(X, batch=25, mixture=spec,
                             index_backend="native")
        cpu_l = HostDataLoader(X, batch=25, mixture=spec)
        for a, b in zip(nat.epoch(1), cpu_l.epoch(1)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="sources sum"):
        HostDataLoader(np.arange(299), batch=25, mixture=spec)
    with pytest.raises(ValueError, match="epoch_samples"):
        HostDataLoader(X, batch=25, window=16, epoch_samples=5)
    with pytest.raises(TypeError, match="MixtureSpec"):
        HostDataLoader(X, batch=25, mixture=[200, 100])


def test_shard_mode_loader_matches_expansion():
    """shard_sizes=[...]: the loader serves the rank's shard stream
    EXPANDED to sample indices (SPEC.md §7), bit-equal to
    expand_shard_indices_np over epoch_indices_np(num_shards, ...)."""
    from partiallyshuffledistributedsampler_tpu.sampler.shard_mode import (
        expand_shard_indices_np,
    )

    rng = np.random.default_rng(3)
    sizes = rng.integers(8, 20, 40)
    total = int(sizes.sum())
    X = np.arange(total)
    loader = HostDataLoader(X, batch=16, world=2, rank=1, window=8,
                            shard_sizes=sizes, seed=5)
    assert loader.steps_per_epoch is None  # per-epoch, by design
    sid = epoch_indices_np(40, 8, 5, 2, 1, 2)
    ref = expand_shard_indices_np(sid, sizes, seed=5, epoch=2)
    steps = loader.epoch_steps(2)
    assert steps == len(ref) // 16
    got = np.concatenate([np.asarray(b) for b in loader.epoch(2)])
    assert np.array_equal(got, X[ref[:steps * 16]])
    # resume mid-epoch
    got3 = np.concatenate([np.asarray(b)
                           for b in loader.epoch(2, start_step=3)])
    assert np.array_equal(got3, X[ref[3 * 16:steps * 16]])
    with pytest.raises(ValueError, match="mutually exclusive"):
        from partiallyshuffledistributedsampler_tpu.ops.mixture import (
            MixtureSpec,
        )
        HostDataLoader(X, batch=16, shard_sizes=sizes,
                       mixture=MixtureSpec([total], [1]))


def test_elastic_layers_epoch_matches_reference():
    """epoch(e, layers=...): the §6 remainder stream through the loader,
    for the single-source AND mixture tiers, bit-equal to the elastic
    reference frontends."""
    from partiallyshuffledistributedsampler_tpu.ops.cpu import (
        elastic_indices_np,
    )
    from partiallyshuffledistributedsampler_tpu.ops.mixture import (
        MixtureSpec, mixture_elastic_indices_np,
    )

    X = np.arange(N)
    loader = make({"x": X})
    layers = [(3, 40)]
    ref = elastic_indices_np(N, WINDOW, 0, 1, 0, WORLD, layers)
    got = np.concatenate([np.asarray(b["x"])
                          for b in loader.epoch(1, layers=layers)])
    whole = (len(ref) // BATCH) * BATCH
    assert np.array_equal(got, X[ref[:whole]])
    spec = MixtureSpec([200, 100, 300], [3, 1, 2], windows=16, block=30)
    MX = np.arange(spec.total_sources_len)
    mloader = HostDataLoader(MX, batch=32, world=2, rank=0, mixture=spec)
    mref = mixture_elastic_indices_np(spec, 0, 1, 0, 2, layers)
    mgot = np.concatenate([np.asarray(b)
                           for b in mloader.epoch(1, layers=layers)])
    assert np.array_equal(mgot, MX[mref[:(len(mref) // 32) * 32]])


def test_mixture_loader_xla_backend_matches_cpu():
    from partiallyshuffledistributedsampler_tpu.ops.mixture import (
        MixtureSpec,
    )

    spec = MixtureSpec([200, 100, 300], [3, 1, 2], windows=16, block=30)
    X = np.arange(spec.total_sources_len)
    a = HostDataLoader(X, batch=32, world=2, rank=1, mixture=spec)
    b = HostDataLoader(X, batch=32, world=2, rank=1, mixture=spec,
                       index_backend="xla")
    for ba, bb in zip(a.epoch(3), b.epoch(3)):
        assert np.array_equal(np.asarray(ba), np.asarray(bb))


def test_epoch_index_cache_dropped_on_exhaustion():
    """The one-entry index cache exists so epoch_steps + epoch share one
    regen; it must NOT pin a (potentially huge) epoch array after the
    epoch is fully consumed, and clear_cache() must drop it on demand."""
    loader = make(data=np.arange(N))
    for _ in loader.epoch(1):
        pass
    assert loader._idx_cache is None  # exhaustion reclaimed the array

    idx = loader.epoch_indices(2)
    assert loader._idx_cache is not None
    assert loader.epoch_indices(2) is idx  # cache hit while live
    loader.clear_cache()
    assert loader._idx_cache is None
    assert np.array_equal(loader.epoch_indices(2), idx)  # recompute matches


def test_early_exit_also_reclaims_cache():
    """Abandoning an epoch mid-way closes the prefetch generator, and the
    close path reclaims the cached index array just like exhaustion — a
    resume recomputes the same stream deterministically."""
    loader = make()
    it = iter(loader.epoch(3))
    next(it)
    it.close()
    assert loader._idx_cache is None
    ref = ref_batches(3)[0]
    resumed = next(iter(loader.epoch(3)))
    assert np.array_equal(np.asarray(resumed["x"]),
                          np.arange(N * 3).reshape(N, 3)[ref])
