"""HostDataLoader: prefetched host-gather → device batches.

Law under test: the served batches are exactly the sampler stream
(epoch_indices_np) cut into batch slices and gathered from the host
arrays — across dict/single-array data, tail handling, resume offsets,
index backends, and early consumer exit (no hung prefetch thread).
"""

import threading

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops.cpu import epoch_indices_np
from partiallyshuffledistributedsampler_tpu.sampler import HostDataLoader

N, WINDOW, BATCH, WORLD = 530, 32, 64, 2


def ref_batches(epoch, rank=0, drop_last_batch=True, start_step=0):
    idx = epoch_indices_np(N, WINDOW, 0, epoch, rank, WORLD)
    whole = len(idx) // BATCH
    steps = whole if drop_last_batch else -(-len(idx) // BATCH)
    return [idx[s * BATCH:(s + 1) * BATCH] for s in range(start_step, steps)]


def make(data=None, **kw):
    if data is None:
        data = {"x": np.arange(N * 3).reshape(N, 3), "y": np.arange(N)}
    kw.setdefault("window", WINDOW)
    kw.setdefault("batch", BATCH)
    kw.setdefault("world", WORLD)
    return HostDataLoader(data, **kw)


@pytest.mark.parametrize("depth", [1, 3])
def test_batches_match_sampler_stream(depth):
    loader = make(depth=depth)
    got = list(loader.epoch(2))
    refs = ref_batches(2)
    assert len(got) == len(refs) == loader.steps_per_epoch
    for b, sl in zip(got, refs):
        assert np.array_equal(np.asarray(b["x"]), np.arange(N * 3).reshape(N, 3)[sl])
        assert np.array_equal(np.asarray(b["y"]), sl)


def test_single_array_mode():
    loader = make(data=np.arange(N))
    got = list(loader.epoch(0))
    for b, sl in zip(got, ref_batches(0)):
        assert np.array_equal(np.asarray(b), sl)


def test_batches_live_on_device():
    import jax

    b = next(iter(make().epoch(0)))
    assert isinstance(b["x"], jax.Array)


def test_tail_batch_served_when_asked():
    loader = make(drop_last_batch=False)
    got = list(loader.epoch(1))
    refs = ref_batches(1, drop_last_batch=False)
    assert len(got) == len(refs)
    assert len(np.asarray(got[-1]["y"])) == len(refs[-1])  # short tail
    assert np.array_equal(np.asarray(got[-1]["y"]), refs[-1])
    # default: tail dropped
    assert len(list(make().epoch(1))) == len(ref_batches(1))


def test_start_step_resume_matches_uninterrupted_tail():
    loader = make()
    full = [np.asarray(b["y"]) for b in loader.epoch(3)]
    resumed = [np.asarray(b["y"]) for b in loader.epoch(3, start_step=2)]
    assert len(resumed) == len(full) - 2
    for a, b in zip(resumed, full[2:]):
        assert np.array_equal(a, b)


def test_epoch_variation_and_rank_partition():
    X = np.arange(N)
    a = np.concatenate([np.asarray(b) for b in
                        make(data=X, drop_last_batch=False).epoch(0)])
    b = np.concatenate([np.asarray(x) for x in
                        make(data=X, drop_last_batch=False).epoch(1)])
    assert not np.array_equal(a, b)  # reseed reshuffles
    r1 = np.concatenate([np.asarray(x) for x in
                         make(data=X, rank=1, drop_last_batch=False).epoch(0)])
    assert sorted(set(a.tolist()) | set(r1.tolist())) == list(range(N))


@pytest.mark.parametrize("backend", ["xla", "native"])
def test_index_backends_bit_identical(backend):
    try:
        got = list(make(index_backend=backend).epoch(2))
    except Exception as exc:  # native toolchain may be absent
        if backend == "native":
            pytest.skip(f"native backend unavailable: {exc!r}")
        raise
    for b, sl in zip(got, ref_batches(2)):
        assert np.array_equal(np.asarray(b["y"]), sl)


def test_index_backend_auto_resolves_and_matches():
    loader = make(index_backend="auto")
    assert loader.index_backend in ("cpu", "native", "xla")
    for b, sl in zip(loader.epoch(2), ref_batches(2)):
        assert np.array_equal(np.asarray(b["y"]), sl)


def test_early_break_retires_prefetch_thread():
    loader = make(depth=2)
    before = {t.name for t in threading.enumerate()}
    it = loader.epoch(0)
    next(it)
    it.close()  # consumer abandons the epoch
    for t in threading.enumerate():
        if t.name == "psds-host-prefetch" and t not in before:
            t.join(timeout=5.0)
            assert not t.is_alive(), "prefetch thread leaked"


def test_gather_error_surfaces_to_consumer():
    class Bad(HostDataLoader):
        def epoch_indices(self, epoch):
            return np.full(self.num_samples, N + 999)  # out of bounds

    loader = Bad({"x": np.arange(N)}, window=WINDOW, batch=BATCH, world=WORLD)
    with pytest.raises(IndexError):
        list(loader.epoch(0))


def test_validation_errors():
    with pytest.raises(ValueError, match="leading dims"):
        make(data={"x": np.arange(10), "y": np.arange(11)})
    with pytest.raises(ValueError, match="depth"):
        make(depth=0)
    with pytest.raises(ValueError, match="index_backend"):
        make(index_backend="gpu")
    with pytest.raises(ValueError, match="rank"):
        make(rank=5)
    with pytest.raises(ValueError, match="start_step"):
        next(make().epoch(0, start_step=999))
    with pytest.raises(ValueError, match="at least one"):
        HostDataLoader({}, window=8, batch=4)
