"""Epochless moving-horizon streaming (docs/STREAMING.md).

The contract under test: the index space is append-only and the shuffle
never sees an "epoch end" — the stream is cut into horizons of H
samples, horizon generation ``g`` IS epoch ``g`` everywhere in the
framework, a horizon advance is an ack-gated lightweight barrier (not a
reshard), and the exactly-once law extends to the unbounded stream:
appends landing mid-serve, injected append/advance faults, a mid-stream
elastic reshard and a primary kill at the advance barrier must all
leave the union of every rank's delivered indices equal to the eligible
samples, each exactly once — while server + WAL state stays O(horizon),
not O(stream).

These run inside tier-1 and are the first leg of the
``make streaming-smoke`` gate (``-m streaming``).
"""

from __future__ import annotations

import json
import threading
import time
from collections import Counter

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu import faults as F
from partiallyshuffledistributedsampler_tpu.durability.recover import (
    recover_unstarted,
)
from partiallyshuffledistributedsampler_tpu.ops.mixture import MixtureSpec
from partiallyshuffledistributedsampler_tpu.sampler.host_loader import (
    HostDataLoader,
)
from partiallyshuffledistributedsampler_tpu.sampler.jax_iterator import (
    DeviceEpochIterator,
)
from partiallyshuffledistributedsampler_tpu.service import (
    IndexServer,
    ServiceError,
    ServiceIndexClient,
)
from partiallyshuffledistributedsampler_tpu.streaming import StreamSpec
from partiallyshuffledistributedsampler_tpu.streaming.spec import (
    WEIGHTS_RETAIN,
)

from test_failover import replicated_pair, wait_for, wait_synced

pytestmark = pytest.mark.streaming

SECRET = b"psds-test-deployment-secret"

H = 64  #: default horizon extent for service-level tests


def plain_stream(world=2, horizon=H, **kw):
    kw.setdefault("window", 8)
    kw.setdefault("seed", 7)
    return StreamSpec.plain_stream(horizon, world=world, **kw)


def mixture_stream(world=2, horizon=96, **kw):
    kw.setdefault("seed", 7)
    ms = MixtureSpec([100, 200, 50], [5, 3, 2], block=16)
    return StreamSpec.mixture_stream(horizon, mixture=ms, world=world, **kw)


def feed(address, count, *, weights_delta=None):
    """One-shot feeder: extend the stream by ``count`` samples."""
    c = ServiceIndexClient(address, rank=None, batch=4, attach=True,
                           backoff_base=0.01, reconnect_timeout=10.0)
    try:
        return c.append(count, weights_delta=weights_delta)
    finally:
        c.close()


def stream_union(delivered):
    return Counter(
        np.concatenate(
            [a for got in delivered.values() for a in got]).tolist())


# ------------------------------------------------------------ spec laws
def test_stream_spec_laws():
    """Eligibility, per-horizon union/offset, constant partition sizes
    and wire-identity — the laws the module docstring states."""
    spec = plain_stream(world=2)
    # eligibility: whole horizons only
    assert spec.eligible_horizons(0) == 0
    assert spec.eligible_horizons(H - 1) == 0
    assert spec.eligible_horizons(H) == 1
    assert spec.eligible_horizons(3 * H + 1) == 3
    # per-horizon union: exactly the absolute block [gH, (g+1)H)
    perms = []
    for g in range(3):
        per_rank = [np.asarray(spec.rank_indices(g, r)) for r in range(2)]
        union = np.sort(np.concatenate(per_rank))
        assert np.array_equal(union, np.arange(g * H, (g + 1) * H)), g
        perms.append(np.concatenate(per_rank) - g * H)
    # the epoch already perturbs the permutation: horizons differ
    assert not np.array_equal(perms[0], perms[1])
    assert not np.array_equal(perms[1], perms[2])
    # partition sizes are constant across horizons (advance-barrier math)
    assert spec.num_samples(0) == spec.num_samples(1) == H // 2
    # wire round-trip preserves the stream identity
    back = StreamSpec.from_wire(spec.to_wire())
    assert back.fingerprint() == spec.fingerprint()
    assert back.mode == "stream" and back.horizon == H
    assert np.array_equal(back.rank_indices(2, 1), spec.rank_indices(2, 1))


def test_stream_spec_builder_refusals():
    with pytest.raises(ValueError):
        StreamSpec.plain_stream(0, window=8)
    with pytest.raises(ValueError):
        StreamSpec(horizon=H)  # no base at all
    with pytest.raises(ValueError):
        # per-source windows ride the mixture key
        StreamSpec(horizon=H, window=8,
                   mixture=MixtureSpec([100, 50], [1, 1], block=10))


def test_stream_weights_adoption_and_prune():
    """Per-horizon re-weighting: newest-at-or-below lookup, identity
    stable under adoption, pruning keeps the anchor entry."""
    spec = mixture_stream(world=1)
    base = tuple(int(x) for x in spec.mixture_key[1])
    assert spec.weights_for(0) == base
    w2 = (8, 3, 2)
    spec2 = spec.with_stream_weights({2: w2})
    # the stream identity (fingerprint) is stable under re-weighting
    assert spec2.fingerprint() == spec.fingerprint()
    assert spec2.weights_for(0) == base and spec2.weights_for(1) == base
    assert spec2.weights_for(2) == w2 and spec2.weights_for(9) == w2
    # the re-weighted horizon's stream actually moves
    assert not np.array_equal(spec2.rank_indices(2, 0),
                              spec.rank_indices(2, 0))
    assert np.array_equal(spec2.rank_indices(1, 0), spec.rank_indices(1, 0))
    # pruning drops old entries but keeps the newest below the floor:
    # it still anchors weights_for() for every retained horizon
    spec3 = spec2.with_stream_weights({7: (1, 9, 1)}, prune_below=5)
    assert spec3.weights_for(4) == w2  # anchored by the pruned-survivor
    assert spec3.weights_for(7) == (1, 9, 1)
    assert set(spec3.stream_weights) == {2, 7}
    spec4 = spec3.with_stream_weights({}, prune_below=100)
    assert set(spec4.stream_weights) == {7}
    assert spec4.weights_for(100) == (1, 9, 1)
    # a plain stream has nothing to weight
    assert plain_stream().weights_for(3) is None


# ---------------------------------------------------- append + eligibility
def test_append_idempotent_and_eligibility_gate():
    """An APPEND replay is answered ``duplicate`` without re-counting,
    and a horizon is refused (typed, retryable) until fully appended."""
    spec = plain_stream(world=1)
    with IndexServer(spec) as srv:
        c = ServiceIndexClient(srv.address, rank=None, batch=4, attach=True,
                               backoff_base=0.01, reconnect_timeout=10.0)
        try:
            out = c.append(H // 2)
            assert out["appended"] == H // 2 and out["eligible"] == 0
            # a half-appended horizon is not servable: the typed refusal
            # paces the client until its deadline
            w = ServiceIndexClient(srv.address, rank=0, batch=16,
                                   backoff_base=0.01, reconnect_timeout=0.6)
            try:
                with pytest.raises(ServiceError) as ei:
                    next(iter(w.epoch_batches(0)))
                assert ei.value.code == "horizon_pending"
                assert w.metrics.report()["counters"]["stream_waits"] >= 1
            finally:
                w.close()
            out = c.append(H // 2)
            assert out["appended"] == H and out["eligible"] == 1
            with ServiceIndexClient(srv.address, rank=0, batch=16,
                                    backoff_base=0.01,
                                    reconnect_timeout=10.0) as w:
                got = np.concatenate(list(w.epoch_batches(0)))
            assert np.array_equal(got, spec.rank_indices(0, 0))
        finally:
            c.close()
        counters = srv.metrics.report()["counters"]
        assert counters.get("stream_appends", 0) == 2


def test_append_while_serving_exactly_once():
    """The core law: appends land mid-serve, ranks ride the typed
    backpressure, the advance barrier folds horizons 0->1->2, and the
    union of all delivered indices is every appended sample exactly
    once."""
    spec = plain_stream(world=2)
    delivered = {}
    lock = threading.Lock()
    with IndexServer(spec) as srv:
        addr = srv.address

        def feeder():
            c = ServiceIndexClient(addr, rank=None, batch=4, attach=True,
                                   backoff_base=0.01, reconnect_timeout=10.0)
            try:
                for _ in range(6):
                    c.append(32)
                    time.sleep(0.02)
            finally:
                c.close()

        def worker(r):
            c = ServiceIndexClient(addr, rank=r, batch=16,
                                   backoff_base=0.01, reconnect_timeout=10.0)
            got = []
            try:
                for arr in c.stream_batches(horizons=3):
                    got.append(np.asarray(arr))
            finally:
                with lock:
                    delivered[r] = got
                c.close()

        ths = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
        for t in ths:
            t.start()
        time.sleep(0.05)
        ft = threading.Thread(target=feeder)
        ft.start()
        ft.join(30)
        for t in ths:
            t.join(30)
        assert not any(t.is_alive() for t in ths), "worker hung"
        assert srv.epoch == 2
        counters = srv.metrics.report()["counters"]
        hists = srv.metrics.report()["histograms"]
        assert counters.get("stream_appends", 0) == 6
        assert counters.get("horizon_advances", 0) == 2
        assert "horizon_advance_ms" in hists
        assert "append_visible_ms" in hists
    assert stream_union(delivered) == Counter(range(3 * H))


# --------------------------------------------------- mixture re-weighting
def test_reweight_and_capability_arm_bit_identical():
    """Online mixture re-weighting: a ``weights_delta`` riding an APPEND
    folds in at the next advance, moves the stream, and the signed
    capability carries the horizon's effective weights — the on-device
    regen arm is bit-identical to the served-batch arm."""
    served = {}
    regen = {}
    for arm, sink in (("served", served), ("capability", regen)):
        spec = mixture_stream(world=2)
        hz = spec.horizon
        with IndexServer(spec, capability_secret=SECRET) as srv:
            addr = srv.address
            feed(addr, hz)
            # the delta and the eligibility extension land atomically:
            # the advance into horizon 1 MUST see the folded weights
            feed(addr, hz, weights_delta=[3, 0, 0])
            feed(addr, hz)
            errors = []

            def worker(r):
                kw = dict(backoff_base=0.01, reconnect_timeout=20.0)
                if arm == "capability":
                    kw["capability_secret"] = SECRET
                c = ServiceIndexClient(addr, rank=r, batch=16,
                                       spec=mixture_stream(world=2), **kw)
                got = []
                try:
                    it = (c.capability_stream_batches(horizons=3)
                          if arm == "capability"
                          else c.stream_batches(horizons=3))
                    for arr in it:
                        got.append(np.asarray(arr))
                except Exception as exc:  # surfaced after join
                    errors.append((arm, r, exc))
                finally:
                    sink[r] = got
                    c.close()

            ths = [threading.Thread(target=worker, args=(r,))
                   for r in range(2)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(30)
            assert not errors, errors
            assert srv.epoch == 2
            # the adopted weights live on the server's spec now
            assert srv.spec.weights_for(1) == (8, 3, 2)
    for r in range(2):
        a = np.concatenate(served[r])
        b = np.concatenate(regen[r])
        assert np.array_equal(a, b), f"capability arm diverged for rank {r}"
    # the re-weighted horizon genuinely moved vs. the base weights, and
    # matches the spec-level law for (5,3,2) + (3,0,0)
    base = mixture_stream(world=2)
    ref = base.with_stream_weights({1: (8, 3, 2)})
    for r in range(2):
        per_h = np.split(np.concatenate(served[r]), 3)
        assert not np.array_equal(per_h[1], base.rank_indices(1, r))
        assert np.array_equal(per_h[1], ref.rank_indices(1, r))
        assert np.array_equal(per_h[0], base.rank_indices(0, r))
        assert np.array_equal(per_h[2], ref.rank_indices(2, r))


# ------------------------------------------------------------ chaos matrix
@pytest.mark.chaos
def test_chaos_append_fault_never_skips_or_double_counts():
    """An APPEND lost before the WAL write (refusal or handler death)
    is replayed by the feeder's ``(feeder, stream_seq)`` retry and lands
    exactly once — the served stream neither skips nor double-serves."""
    for kind in ("error", "thread_death"):
        spec = plain_stream(world=1, horizon=32)
        with IndexServer(spec) as srv:
            c = ServiceIndexClient(srv.address, rank=None, batch=4,
                                   attach=True, backoff_base=0.01,
                                   reconnect_timeout=10.0)
            plan = F.FaultPlan([F.FaultRule(site="stream.append",
                                            kind=kind, count=1)])
            try:
                with plan:
                    out = c.append(32)
                assert out["appended"] == 32 and not out.get("duplicate")
                out = c.append(32)
                assert out["appended"] == 64
            finally:
                c.close()
            assert plan.fired("stream.append") == 1, \
                "fault never fired; the test is vacuous"
            with ServiceIndexClient(srv.address, rank=0, batch=8,
                                    backoff_base=0.01,
                                    reconnect_timeout=10.0) as w:
                got = np.concatenate(list(w.stream_batches(horizons=2)))
        assert Counter(got.tolist()) == Counter(range(64)), kind


@pytest.mark.chaos
def test_chaos_advance_abort_rolls_back_cleanly():
    """An injected abort at the advance barrier (pre-mutation) is a
    clean retryable refusal: the horizon generation does not move, the
    client retries, and the stream stays exactly-once."""
    spec = plain_stream(world=1, horizon=32)
    with IndexServer(spec) as srv:
        feed(srv.address, 64)
        plan = F.FaultPlan([F.FaultRule(site="stream.advance",
                                        kind="error", count=1)])
        with plan:
            with ServiceIndexClient(srv.address, rank=0, batch=8,
                                    backoff_base=0.01,
                                    reconnect_timeout=10.0) as w:
                got = np.concatenate(list(w.stream_batches(horizons=2)))
        assert plan.fired("stream.advance") == 1, \
            "fault never fired; the test is vacuous"
        assert srv.epoch == 1
        assert srv.metrics.report()["counters"]["horizon_advances"] == 1
    assert Counter(got.tolist()) == Counter(range(64))


# ------------------------------------------------------- mid-stream reshard
@pytest.mark.elastic
def test_mid_stream_reshard_union_exactly_once():
    """One elastic reshard (2 -> 3) lands mid-horizon-1 while appends
    are already in: the frozen remainder is re-dealt, the joiner picks
    up its share, the advance barrier re-pins per-rank targets under
    the new partition and still commits, and the union law holds over
    the whole stream (wrap-pad extras only)."""
    spec = plain_stream(world=2)
    delivered = {}
    lock = threading.Lock()
    with IndexServer(spec) as srv:
        addr = srv.address
        feed(addr, 3 * H)  # deterministic serve side
        # RESHARD rides its own attach connection: a control RPC on a
        # worker's pipelined connection would race its in-flight replies
        ctl = ServiceIndexClient(addr, rank=None, batch=4, attach=True,
                                 backoff_base=0.01, reconnect_timeout=10.0)
        b_hit = threading.Barrier(3)

        def worker(r):
            c = ServiceIndexClient(addr, rank=r, batch=8,
                                   backoff_base=0.01, reconnect_timeout=10.0)
            got = []
            try:
                it = c.stream_batches(horizons=3)
                # horizon 0 fully, then partway into horizon 1
                for _ in range(H // 2 // 8 + 2):
                    got.append(np.asarray(next(it)))
                b_hit.wait(timeout=30)
                # keep consuming: the freeze barrier commits only once
                # every rank drains to its consumption watermark
                for arr in it:
                    got.append(np.asarray(arr))
            finally:
                with lock:
                    delivered[r] = got
                c.close()

        def joiner():
            c = ServiceIndexClient(addr, rank=None, batch=8,
                                   backoff_base=0.01, reconnect_timeout=10.0)
            got = []
            try:
                # the new rank picks up its re-dealt share of horizon 1,
                # then rides horizon 2 to the stream end
                for arr in c.stream_batches(start_horizon=1, horizons=2):
                    got.append(np.asarray(arr))
            finally:
                with lock:
                    delivered["j"] = got
                c.close()

        ths = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
        for t in ths:
            t.start()
        try:
            b_hit.wait(timeout=30)
            ctl.reshard(3)
            wait_for(lambda: srv.generation == 1, timeout=20.0)
            jt = threading.Thread(target=joiner)
            jt.start()
            for t in ths:
                t.join(30)
            jt.join(30)
            assert not any(t.is_alive() for t in ths + [jt]), "worker hung"
        finally:
            ctl.close()
        assert srv.epoch == 2, f"advance deadlocked at epoch {srv.epoch}"
        assert srv.spec.world == 3
    union = stream_union(delivered)
    full = Counter(range(3 * H))
    missing = full - union
    assert not missing, f"dropped: {sorted(missing)[:8]}"
    extras = union - full
    assert sum(extras.values()) <= 3, f"too many wrap-pad extras: {extras}"
    assert set(extras) <= set(full)


# -------------------------------------------------------- bounded state
@pytest.mark.durability
def test_watermark_gc_keeps_state_o_horizon(tmp_path):
    """The bounded-state guarantee: while appended samples grow without
    bound across >= 10 advances, every advance seals a forced checkpoint
    and the WAL GC truncates below the watermark — segment count and
    server cursor state stay O(horizon), not O(stream)."""
    hz, horizons = 32, 12
    spec = plain_stream(world=1, horizon=hz)
    snap = str(tmp_path / "snap.json")
    wal_dir = str(tmp_path / "wal")
    srv = IndexServer(spec, port=0, snapshot_path=snap, wal_dir=wal_dir,
                      fsync="off")
    host, port = srv.start()
    try:
        # tiny segments so rotation (and therefore GC) actually happens
        srv._wal.segment_bytes = 512
        done = threading.Event()

        def feeder():
            c = ServiceIndexClient((host, port), rank=None, batch=4,
                                   attach=True, backoff_base=0.01,
                                   reconnect_timeout=20.0)
            try:
                for _ in range(horizons * hz // 8):
                    c.append(8)
                    time.sleep(0.002)
            finally:
                done.set()
                c.close()

        ft = threading.Thread(target=feeder)
        ft.start()
        with ServiceIndexClient((host, port), rank=0, batch=16,
                                backoff_base=0.01,
                                reconnect_timeout=30.0) as w:
            got = np.concatenate(list(w.stream_batches(horizons=horizons)))
        ft.join(30)
        assert done.is_set()
        assert Counter(got.tolist()) == Counter(range(horizons * hz))
        assert srv.epoch == horizons - 1
        counters = srv.metrics.report()["counters"]
        assert counters.get("horizon_advances", 0) == horizons - 1
        assert counters.get("stream_gc_truncations", 0) >= 1, \
            "advances never truncated the WAL"
        # O(horizon), not O(stream): the live tail is bounded while the
        # record history (48 appends + every cursor ack) was not
        assert len(srv._wal.segment_paths()) <= 6
        assert json.load(open(snap)).get("wal_lsn", 0) > 0
        # cursor state is O(world), append dedup state O(feeders)
        assert len(srv._cursors) == 1
        assert len(srv._stream_seqs) == 1
    finally:
        srv.stop()


@pytest.mark.durability
def test_mid_stream_crash_recovery_bit_identical(tmp_path):
    """A daemon killed mid-stream recovers from checkpoint + tail replay
    and resumes at the exact horizon generation and ack watermark: the
    full delivered stream across the crash is bit-identical to the
    spec's."""
    hz = 32
    spec = plain_stream(world=1, horizon=hz)
    snap = str(tmp_path / "snap.json")
    wal_dir = str(tmp_path / "wal")
    srv = IndexServer(spec, port=0, snapshot_path=snap, wal_dir=wal_dir,
                      fsync="off")
    host, port = srv.start()
    feed((host, port), 4 * hz)
    with ServiceIndexClient((host, port), rank=0, batch=8,
                            backoff_base=0.01, reconnect_timeout=10.0) as w:
        before = np.concatenate(list(w.stream_batches(horizons=2)))
    assert srv.epoch == 1
    srv.kill()  # no graceful snapshot: recovery rides checkpoint + tail
    fresh = IndexServer(plain_stream(world=1, horizon=hz),
                        snapshot_path=snap, wal_dir=wal_dir, fsync="off")
    stats = recover_unstarted(fresh)
    assert stats is not None
    assert fresh.epoch == 1, "recovery lost the horizon generation"
    assert fresh._stream_appended == 4 * hz, "recovery lost appends"
    host, port = fresh.start()
    try:
        with ServiceIndexClient((host, port), rank=0, batch=8,
                                backoff_base=0.01,
                                reconnect_timeout=10.0) as w:
            after = np.concatenate(list(
                w.stream_batches(start_horizon=2, horizons=2)))
        assert fresh.epoch == 3
    finally:
        fresh.stop()
    ref = np.concatenate([np.asarray(spec.rank_indices(g, 0))
                          for g in range(4)])
    assert np.array_equal(np.concatenate([before, after]), ref)


# ------------------------------------------------------------- failover
@pytest.mark.failover
def test_failover_finishes_advance_at_barrier():
    """Kill the primary AT the advance barrier: every rank has acked
    horizon 0 and is about to name horizon 1.  The promoted standby owns
    the replicated ack cursors, passes the straggler gate, survives an
    injected handler death mid-advance, and commits the advance — the
    folded per-rank streams are bit-identical to the spec's."""
    spec = plain_stream(world=2, horizon=32)
    primary, standby = replicated_pair(spec)
    delivered = {}
    errors = []
    lock = threading.Lock()
    b_done0 = threading.Barrier(3)
    b_go1 = threading.Barrier(3)

    def worker(r):
        c = ServiceIndexClient(primary.address, rank=r, batch=8,
                               backoff_base=0.01, reconnect_timeout=20.0)
        got = []
        try:
            for arr in c.epoch_batches(0):
                got.append(np.asarray(arr))
            b_done0.wait(timeout=30)
            b_go1.wait(timeout=30)
            # the first request naming horizon 1 IS the advance barrier
            for arr in c.epoch_batches(1):
                got.append(np.asarray(arr))
        except Exception as exc:
            errors.append((r, exc))
        finally:
            with lock:
                delivered[r] = got
            c.close()

    plan = F.FaultPlan([F.FaultRule(site="stream.advance",
                                    kind="thread_death", count=1)])
    ths = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    try:
        feed(primary.address, 64)
        for t in ths:
            t.start()
        b_done0.wait(timeout=30)
        # every h0 ack (and the pinned per-rank totals) must be on the
        # standby before the primary dies, or the gate would stall
        wait_synced(primary, standby)
        with plan:
            primary.kill()
            b_go1.wait(timeout=30)
            for t in ths:
                t.join(30)
        assert not any(t.is_alive() for t in ths), "worker hung"
        assert not errors, errors
        assert plan.fired("stream.advance") >= 1, \
            "fault never fired; the test is vacuous"
        assert standby.role == "primary"
        assert standby.epoch == 1, "promoted standby never advanced"
        counters = standby.metrics.report()["counters"]
        assert counters.get("horizon_advances", 0) >= 1
    finally:
        primary.kill()
        standby.stop()
    for r in range(2):
        ref = np.concatenate([np.asarray(spec.rank_indices(g, r))
                              for g in range(2)])
        assert np.array_equal(np.concatenate(delivered[r]), ref), r


# ------------------------------------------------------- loader/iterator
def test_loader_streaming_units():
    """``HostDataLoader(streaming=True)``: per-horizon indices are the
    stream spec's absolute block, and a horizon-generation bump is an
    epoch boundary for the index cache."""
    data = np.arange(256)
    ld = HostDataLoader(data, streaming=True, horizon=64, window=8,
                        batch=16, rank=0, world=1)
    assert ld.stream_spec.mode == "stream"
    for g in range(3):
        idx = ld.epoch_indices(g)
        assert idx.min() >= g * 64 and idx.max() < (g + 1) * 64
        assert np.array_equal(np.sort(idx), np.arange(g * 64, (g + 1) * 64))
        assert np.array_equal(idx, ld.stream_spec.rank_indices(g, 0))
    # one-entry cache within a horizon, dropped on the generation bump
    a = ld.epoch_indices(1)
    assert ld.epoch_indices(1) is a
    ld.epoch_indices(2)
    assert ld._stream_gen == 2
    assert ld._idx_cache[0][0] == 2
    # builder refusals
    with pytest.raises(ValueError):
        HostDataLoader(data, streaming=True, window=8, batch=16)
    with pytest.raises(ValueError):
        HostDataLoader(data, horizon=64, window=8, batch=16)


def test_device_iterator_prunes_stale_horizons():
    """A horizon-generation bump is an epoch boundary for the device
    iterator too: cache and prefetch-ring entries below the generation
    being served are dropped, never served stale."""
    it = DeviceEpochIterator(n=64, window=8, batch=16, seed=3)
    first = [np.asarray(b) for b in it.epoch(0)]
    assert sum(len(b) for b in first) == 64
    # epoch(0) prefetches epoch 1; jumping to 2 must drop everything
    # below it (a moving-horizon stream only advances)
    assert 1 in it._cache
    second = [np.asarray(b) for b in it.epoch(2)]
    assert sum(len(b) for b in second) == 64
    assert all(k >= 2 for k in it._cache), sorted(it._cache)
    assert all(k >= 2 for k in it._ring), sorted(it._ring)
