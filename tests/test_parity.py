"""CPU-vs-XLA bit-identity (SURVEY.md §4 invariant 8 / BASELINE north star).

The XLA backend must reproduce the numpy reference EXACTLY for every driver
config shape.  Because both backends execute the same uint32 program
(ops/core.py), any divergence is a bug in one of the wrappers, not a
tolerance question — hence assert_array_equal, never allclose.
"""

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops import cpu
from partiallyshuffledistributedsampler_tpu.ops.xla import epoch_indices_jax

# Mirrors BASELINE.json "configs" shapes at test scale: CIFAR-ish/window 512,
# ImageNet-ish/window 8192 (scaled), shard-mode-ish small n, awkward remainders.
CONFIGS = [
    dict(n=50_000, window=512, world=2),          # CIFAR-10, 2 ranks
    dict(n=10_000, window=8192, world=8),         # window ~ n/1 regime
    dict(n=12_345, window=512, world=8),          # remainders everywhere
    dict(n=640, window=64, world=8, drop_last=True),
    dict(n=1000, window=1, world=3),
    dict(n=1000, window=2048, world=3),           # W > n
    dict(n=97, window=10, world=3, partition="blocked"),
    dict(n=5000, window=100, world=4, order_windows=False),
    dict(n=777, window=33, world=5, shuffle=False),
]


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: f"n{c['n']}w{c['window']}x{c['world']}")
@pytest.mark.parametrize("seed,epoch", [(0, 0), (1234, 7), ((1 << 40) + 5, 2)])
def test_bit_identical(cfg, seed, epoch):
    cfg = dict(cfg)
    n, w, world = cfg.pop("n"), cfg.pop("window"), cfg.pop("world")
    for rank in range(0, world, max(1, world // 3)):
        ref = cpu.epoch_indices_np(n, w, seed, epoch, rank, world, **cfg)
        got = np.asarray(epoch_indices_jax(n, w, seed, epoch, rank, world, **cfg))
        assert got.dtype == ref.dtype
        np.testing.assert_array_equal(got, ref)


def test_traced_scalars_match_python_ints():
    """(seed, epoch, rank) must be traceable — one executable for all epochs."""
    import jax.numpy as jnp

    ref = cpu.epoch_indices_np(1000, 64, 5, 3, 1, 4)
    got = np.asarray(
        epoch_indices_jax(
            1000, 64, jnp.uint32(5), jnp.uint32(3), jnp.uint32(1), 4
        )
    )
    np.testing.assert_array_equal(got, ref)


def test_no_recompile_across_epochs():
    """set_epoch must not trigger retracing: the jitted fn is cached per
    static config and (seed, epoch, rank) are traced args."""
    from partiallyshuffledistributedsampler_tpu.ops import xla as xla_mod

    f1 = xla_mod._compiled_epoch_indices(2048, 128, 4, True, False, True, "strided", 24, False)
    f2 = xla_mod._compiled_epoch_indices(2048, 128, 4, True, False, True, "strided", 24, False)
    assert f1 is f2
