"""Compiled (non-interpret) Pallas parity gate — VERDICT round 1, weak #1.

The pytest process is pinned to the CPU platform (conftest.py), where the
Pallas kernel runs in interpret mode only — a Mosaic *lowering* regression
would ship green.  This gate spawns a subprocess WITHOUT the CPU override so
it sees the machine's real device, and asserts the compiled kernel is
bit-identical to the numpy reference across representative configs (tail
windows, blocked partition, shuffle off, non-default rounds).  Skips — loudly
— only when the machine truly has no TPU.
"""

import os
import subprocess
import sys

import pytest

_SCRIPT = r"""
import sys
import numpy as np
import jax

if jax.default_backend() != "tpu":
    print("NO_TPU", jax.default_backend())
    sys.exit(0)

from partiallyshuffledistributedsampler_tpu.ops import cpu
from partiallyshuffledistributedsampler_tpu.ops.pallas_kernel import (
    epoch_indices_pallas,
)

CONFIGS = [
    # n, window, world, rank, seed, epoch, order_windows, partition, rounds, shuffle
    (100_003, 512, 4, 1, 0, 3, True, "strided", 24, True),
    (100_003, 512, 4, 3, 9, 0, True, "blocked", 24, True),
    (65_536, 4096, 8, 2, 7, 1, False, "strided", 24, True),
    (999, 64, 3, 0, 1, 2, True, "strided", 8, True),
    (4_000_037, 8192, 256, 17, 0, 5, True, "strided", 24, True),
    (1_000, 128, 2, 1, 0, 0, True, "strided", 24, False),
]
from partiallyshuffledistributedsampler_tpu.ops.xla import epoch_indices_jax

checks = 0
for n, w, world, rank, seed, epoch, ow, part, rounds, shuf in CONFIGS:
    ref = cpu.epoch_indices_np(
        n, w, seed, epoch, rank, world, shuffle=shuf, order_windows=ow,
        partition=part, rounds=rounds,
    )
    got = np.asarray(
        epoch_indices_pallas(
            n, w, seed, epoch, rank, world, shuffle=shuf, order_windows=ow,
            partition=part, rounds=rounds, interpret=False,
        )
    )
    if not np.array_equal(got, ref):
        bad = np.nonzero(got != ref)[0][:5]
        print("MISMATCH general-pallas", (n, w, world, rank), bad.tolist(),
              got[bad].tolist(), ref[bad].tolist())
        sys.exit(1)
    checks += 1
    # the compiled amortized evaluators (pallas hybrid AND fused xla),
    # where applicable — these are the production 'auto' paths
    for up in (True, False):
        got = np.asarray(
            epoch_indices_jax(
                n, w, seed, epoch, rank, world, shuffle=shuf,
                order_windows=ow, partition=part, rounds=rounds,
                use_pallas=up, amortize=True,
            )
        )
        if not np.array_equal(got, ref):
            bad = np.nonzero(got != ref)[0][:5]
            print("MISMATCH amortized", up, (n, w, world, rank), bad.tolist(),
                  got[bad].tolist(), ref[bad].tolist())
            sys.exit(1)
        checks += 1
print("OK", checks)
"""


def test_compiled_pallas_bit_identical_on_real_device():
    env = os.environ.copy()
    # undo the conftest/test-platform overrides: let jax pick the real device
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Bounded backend-discovery probe first: a chipless libtpu install hangs
    # retrying metadata fetches during jax init, which would eat the full
    # 600 s gate budget before the NO_TPU skip could ever print.
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, cwd=repo, capture_output=True, text=True, timeout=30,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("jax backend discovery hung (>30s) without the CPU pin "
                    "(chipless libtpu?); compiled gate needs a real TPU")
    res = subprocess.run(
        [sys.executable, "-c", _SCRIPT], env=env, cwd=repo,
        capture_output=True, text=True, timeout=600,
    )
    out = res.stdout.strip().splitlines()
    last = out[-1] if out else ""
    if last.startswith("NO_TPU"):
        pytest.skip(f"no TPU on this machine ({last}); compiled gate ran "
                    "interpret-only parity elsewhere")
    assert res.returncode == 0 and last.startswith("OK"), (
        f"compiled pallas parity failed:\nstdout: {res.stdout[-2000:]}\n"
        f"stderr: {res.stderr[-2000:]}"
    )
