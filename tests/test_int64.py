"""Big index spaces (n >= 2^31): the 10B-sample Llama-pretrain config [B].

x64 must be enabled process-wide before jit, so the jax-side parity check
runs in a subprocess; the numpy reference path needs no flag (it always uses
uint64 positions).
"""

import subprocess
import sys
import textwrap

import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops import core, cpu

TEN_B = 10_000_000_000


def test_numpy_path_int64():
    # world chosen so the shard is small; indices exceed 2^31
    idx = cpu.epoch_indices_np(TEN_B, 8192, 7, 2, 3, 2_000_000)
    assert idx.dtype == np.int64
    assert len(idx) == 5000
    assert idx.max() > 2**31  # actually reaches the high index space
    assert (idx >= 0).all() and (idx < TEN_B).all()


def test_numpy_int64_determinism_and_epochs():
    a = cpu.epoch_indices_np(TEN_B, 8192, 7, 2, 0, 2_000_000)
    b = cpu.epoch_indices_np(TEN_B, 8192, 7, 2, 0, 2_000_000)
    c = cpu.epoch_indices_np(TEN_B, 8192, 7, 3, 0, 2_000_000)
    np.testing.assert_array_equal(a, b)
    assert (a != c).mean() > 0.5


def test_numpy_int64_partition_small():
    # exhaustive partition check just over the 2^31 boundary
    n = 2**31 + 11
    world = 1 << 20
    shards = [
        cpu.epoch_indices_np(n, 4096, 0, 0, r, world)
        for r in (0, 1, world - 1)
    ]
    for s in shards:
        assert s.dtype == np.int64 and (s < n).all() and (s >= 0).all()
    num_samples, _ = core.shard_sizes(n, world, False)
    assert all(len(s) == num_samples for s in shards)


def test_jax_x64_parity_subprocess():
    code = textwrap.dedent("""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import numpy as np
        import partiallyshuffledistributedsampler_tpu as psds
        import jax
        jax.config.update("jax_platforms", "cpu")
        psds.enable_big_index_space()
        from partiallyshuffledistributedsampler_tpu.ops import cpu
        n, w, world = 10_000_000_000, 8192, 2_000_000
        for rank, epoch in ((0, 0), (3, 5), (1_999_999, 1)):
            ref = cpu.epoch_indices_np(n, w, 42, epoch, rank, world)
            got = np.asarray(psds.epoch_indices_jax(n, w, 42, epoch, rank, world))
            assert got.dtype == np.int64, got.dtype
            np.testing.assert_array_equal(got, ref)
        # the big-n AMORTIZED path (window % world == 0): prove the gate is
        # on for this config, then check bit-parity vs the numpy reference
        from partiallyshuffledistributedsampler_tpu.ops import xla as x
        n2, w2, world2 = 10_000_000_000, 8192, 4096
        assert x._amortized_applicable(n2, w2, world2, True, "strided")
        for rank in (0, 4095):
            ref = cpu.epoch_indices_np(n2, w2, 11, 3, rank, world2)
            got = np.asarray(psds.epoch_indices_jax(n2, w2, 11, 3, rank, world2))
            assert got.dtype == np.int64
            np.testing.assert_array_equal(got, ref)
        # x64 routing: 'auto' must not touch compiled Mosaic (which can't
        # legalize under x64 on this toolchain) even for small n — force
        # the backend check to look like TPU so the x64 condition itself
        # is what's being tested (on this CPU platform it'd be vacuous)
        import jax as _jax
        _orig = _jax.default_backend
        _jax.default_backend = lambda: "tpu"
        try:
            assert not x._resolve_use_pallas("auto", 1000)
        finally:
            _jax.default_backend = _orig
        small = np.asarray(psds.epoch_indices_jax(50_000, 512, 1, 0, 0, 2))
        np.testing.assert_array_equal(
            small, cpu.epoch_indices_np(50_000, 512, 1, 0, 0, 2))
        # ...and an explicit compiled-kernel pin raises a NAMED error
        from partiallyshuffledistributedsampler_tpu.ops import pallas_kernel
        try:
            pallas_kernel.build_call(1000, 64, 2, interpret=False)
            raise SystemExit("missing x64 pallas error")
        except ValueError as e:
            assert "x64" in str(e)
        print("X64_PARITY_OK")
    """)
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300,
    )
    assert "X64_PARITY_OK" in res.stdout, res.stderr[-2000:]


def test_jax_big_n_without_x64_raises():
    from partiallyshuffledistributedsampler_tpu.ops.xla import epoch_indices_jax

    import jax

    if jax.config.read("jax_enable_x64"):
        pytest.skip("x64 already on in this process")
    with pytest.raises(ValueError, match="x64"):
        epoch_indices_jax(TEN_B, 8192, 0, 0, 0, 2_000_000)


def test_device_shard_expansion_big_total_subprocess():
    """Shard-mode device expansion in the >= 2^31 total regime: int64
    output under x64, bit-identical to the host expansion; without x64 it
    must raise the named error, never emit wrapped indices."""
    code = textwrap.dedent("""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import numpy as np
        import partiallyshuffledistributedsampler_tpu as psds
        import jax
        jax.config.update("jax_platforms", "cpu")
        psds.enable_big_index_space()
        from partiallyshuffledistributedsampler_tpu.sampler import (
            expand_shard_indices_jax, expand_shard_indices_np)
        # 3 shards of 1e9 + two small ones of different sizes: the total
        # (3e9+96) exceeds 2^31 so offsets need int64, while expanding
        # only the two small shards keeps the materialized output tiny —
        # and their differing sizes drive the mixed-size-class gather path
        sizes = [1_000_000_000] * 3 + [64, 32]
        dev = np.asarray(
            expand_shard_indices_jax([4, 3], sizes, seed=2, epoch=1))
        host = expand_shard_indices_np([4, 3], sizes, seed=2, epoch=1)
        assert dev.dtype == np.int64, dev.dtype
        assert dev.min() >= 3_000_000_000
        np.testing.assert_array_equal(dev, host)
        print("BIG_EXPAND_OK")
    """)
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300,
    )
    assert "BIG_EXPAND_OK" in res.stdout, res.stderr[-2000:]


def test_device_shard_expansion_big_total_without_x64_raises():
    from partiallyshuffledistributedsampler_tpu.sampler import (
        expand_shard_indices_jax,
    )
    import jax

    if jax.config.read("jax_enable_x64"):  # pragma: no cover
        pytest.skip("x64 already on in this process")
    with pytest.raises(ValueError, match="x64"):
        expand_shard_indices_jax([3], [1_000_000_000] * 3 + [64])


def test_mixture_numpy_path_int64():
    """Mixture over a >2^31 total id space: int64 out, per-source locality
    preserved, high ids actually reached (numpy path needs no flag)."""
    from partiallyshuffledistributedsampler_tpu.ops import mixture as M

    # world coprime to the block so the strided rank samples every pattern
    # slot (see the per-rank balance note in MixtureSpec's docstring)
    spec = M.MixtureSpec([3_000_000_000, 1_000_000_000], [3, 1],
                         windows=8192)
    idx = M.mixture_epoch_indices_np(spec, 7, 1, 5, 1_999_999)
    assert idx.dtype == np.int64
    assert idx.max() > 2**31
    src, loc = spec.decompose(idx)
    assert loc[src == 0].max() < 3_000_000_000
    assert loc[src == 1].max() < 1_000_000_000


def test_mixture_jax_refuses_big_ids_without_x64():
    """Without x64, jnp silently demotes int64 — the frontends must refuse
    loudly for >=2^31 mixtures instead (the §8 counterpart of
    ops.xla._require_x64_for_big_n).  This process has x64 off."""
    import jax

    from partiallyshuffledistributedsampler_tpu.ops import mixture as M
    from partiallyshuffledistributedsampler_tpu.parallel import (
        data_mesh, sharded_mixture_indices,
    )

    assert not jax.config.read("jax_enable_x64")
    spec = M.MixtureSpec([3_000_000_000, 1_000_000_000], [3, 1],
                         windows=8192)
    with pytest.raises(ValueError, match="x64"):
        M.mixture_epoch_indices_jax(spec, 7, 1, 5, 1_999_999)
    with pytest.raises(ValueError, match="x64"):
        M.mixture_elastic_indices_jax(spec, 7, 1, 0, 2, [(2_000_000, 100)])
    with pytest.raises(ValueError, match="x64"):
        sharded_mixture_indices(data_mesh(), spec, 7, 1)
