"""fleetsim/: the deterministic fleet simulator + predictive autopilot.

The acceptance laws (docs/SIMULATOR.md):

* **determinism** — same scenario + same seed → the serialized trace
  AND the WAL-shaped decision log are byte-identical across runs;
* **replayability** — the recorded observation stream fed to a FRESH
  policy reproduces the recorded decision stream exactly;
* **sim/real parity** — the simulator drives the REAL policy /
  backpressure / shard-map code, so replaying a simulated trace's
  snapshots through a live two-shard plane produces the identical
  decision stream and identical on-disk ``autopilot`` WAL records;
* **predictive beats reactive** — on the same replayed workload the
  forecast-driven tune arm reaches the knob fixpoint in measurably
  fewer ticks than the reactive doubling ladder;
* **unattended resolution** — a 5 000-rank simulated hotspot resolves
  through split/migrate with no operator action;
* **warm restarts** — priors learned from a run's WAL records make a
  restarted deployment reproduce the converged knobs in one decision.

Plus chaos coverage for the two simulator fault sites (``sim.event``,
``sim.inject``) and the seeded latency/calibration plumbing.
"""

from __future__ import annotations

import pytest

from partiallyshuffledistributedsampler_tpu import faults as F
from partiallyshuffledistributedsampler_tpu import fleetsim as fs
from partiallyshuffledistributedsampler_tpu.autopilot import (
    Autopilot,
    AutopilotPolicy,
    PolicyConfig,
    learn_priors,
    warm_state,
)
from partiallyshuffledistributedsampler_tpu.durability import (
    read_autopilot_records,
)
from partiallyshuffledistributedsampler_tpu.fleetsim import (
    Calibration,
    DecisionTrace,
    EventLoop,
    FleetSim,
    LatencyModel,
    RegenCostModel,
    SimClock,
    decision_to_dict,
)
from partiallyshuffledistributedsampler_tpu.service import PartialShuffleSpec
from partiallyshuffledistributedsampler_tpu.sharding import ShardPlane
from partiallyshuffledistributedsampler_tpu.utils.metrics import (
    MetricsRegistry,
)

pytestmark = pytest.mark.fleetsim


# ---------------------------------------------------- clock + event loop
def test_sim_clock_is_monotonic_and_injectable():
    clk = SimClock()
    assert clk() == 0.0
    assert clk.advance(1.5) == 1.5
    assert clk.advance_to(4.0) == 4.0
    with pytest.raises(ValueError):
        clk.advance(-0.1)
    with pytest.raises(ValueError):
        clk.advance_to(3.9)
    # the policy accepts it wherever a monotonic callable is expected
    AutopilotPolicy(PolicyConfig(), clock=clk).decide(
        {"now": clk(), "window_s": 1.0, "served": 0, "throttled": 0})


def test_event_loop_dispatch_order_and_horizon():
    """Same-instant events dispatch in admission order (the seq
    tie-break), callbacks can self-reschedule, and ``run_until`` lands
    the clock exactly on the horizon — never past it."""
    clk = SimClock()
    loop = EventLoop(clk)
    order = []
    loop.at(2.0, lambda: order.append("b"))
    loop.at(1.0, lambda: order.append("a1"))
    loop.at(1.0, lambda: order.append("a2"))   # same instant, admitted later
    with pytest.raises(ValueError):
        loop.at(-1.0, lambda: None)            # scheduling into the past
    n = loop.run_until(1.0)
    assert n == 2 and order == ["a1", "a2"] and clk() == 1.0
    loop.run_until(10.0)
    assert order == ["a1", "a2", "b"] and clk() == 10.0

    ticks = []

    def tick():
        ticks.append(clk())
        if len(ticks) < 3:
            loop.after(1.0, tick)

    loop.after(1.0, tick)
    loop.run_until(20.0)
    assert ticks == [11.0, 12.0, 13.0] and clk() == 20.0


# ------------------------------------------------------- latency models
def test_latency_streams_are_seeded_and_channel_independent():
    """Same seed → same per-channel stream; and drawing another channel
    never perturbs a channel's own timeline (independent RNGs)."""
    a, b = LatencyModel(seed=7), LatencyModel(seed=7)
    xs = [a.sample("rpc") for _ in range(8)]
    ys = []
    for _ in range(8):
        b.sample("wal_fsync")          # interleaved draws elsewhere
        ys.append(b.sample("rpc"))
    assert xs == ys
    assert all(x > 0.0 for x in xs)
    assert LatencyModel(seed=8).sample("rpc") != xs[0]
    assert a.p99("regen") > a.p50("regen")
    with pytest.raises(KeyError):
        a.sample("nope")


def test_calibration_from_bench_reads_committed_tails(tmp_path):
    """The committed BENCH_r0*.json tails recalibrate the rpc / regen /
    wal_fsync medians; a directory with no bench files keeps every
    default (the model still runs on a bare checkout)."""
    cal = Calibration.from_bench(".")
    default = Calibration()
    for chan in ("rpc", "regen", "wal_fsync"):
        p50, sigma = getattr(cal, chan)
        assert p50 > 0.0
        assert sigma == getattr(default, chan)[1]   # spread is not scraped
    assert cal.barrier == default.barrier           # no bench source for it
    assert Calibration.from_bench(tmp_path) == default


def test_regen_cost_model_crossover_and_gain():
    """The host line wins small per-rank epochs, the near-flat device
    line wins huge ones, and ``pick`` reports the live probe's info
    shape plus the gain margin the backend arm thresholds on."""
    m = RegenCostModel()
    small, _, info_s = m.pick(1 << 10)
    big, gain_b, info_b = m.pick(10 << 20)
    assert small == m.host_backend and big == "xla"
    assert gain_b > 50.0
    for info in (info_s, info_b):
        assert info["picked"] in (m.host_backend, "xla")
        assert info["est_host_ms"] > 0.0 and info["est_device_ms"] > 0.0


# ------------------------------------------- determinism + replay laws
def _tune_sim(seed: int = 3, predictive: bool = False,
              ticks: int = 14) -> FleetSim:
    sim = FleetSim(world=8, n_shards=2, n=8 << 20,
                   workload=fs.workload.uniform(100_000.0, key="tune-wl"),
                   seed=seed, config=PolicyConfig(predictive=predictive))
    sim.run(ticks)
    return sim


def test_same_scenario_and_seed_is_byte_identical():
    """The determinism law: two fresh runs of the same scenario with
    the same seed serialize to the same bytes — the full trace AND the
    WAL-shaped decision log the acceptance criterion names."""
    a, b = _tune_sim(seed=3), _tune_sim(seed=3)
    assert a.trace.to_jsonl() == b.trace.to_jsonl()
    assert a.trace.decision_log() == b.trace.decision_log()
    assert len(a.trace.decision_log()) > 0
    # a different seed perturbs the sampled latencies, not the laws
    c = _tune_sim(seed=4)
    assert c.trace.to_jsonl() != a.trace.to_jsonl()


def test_trace_replays_through_a_fresh_policy():
    """The replay law: the recorded observations fed to a FRESH policy
    reproduce the recorded decision stream — through a JSONL round
    trip, exactly as an operator would replay a trace file."""
    sim = _tune_sim(seed=3)
    trace = DecisionTrace.from_jsonl(sim.trace.to_jsonl())
    assert len(trace) == len(sim.trace)
    trace.verify_replay(
        lambda: AutopilotPolicy(PolicyConfig(), clock=lambda: 0.0,
                                seed=sim.seed))


def test_wal_records_ride_the_live_record_shape():
    sim = _tune_sim(seed=3)
    recs = sim.trace.wal_records()
    assert recs, "scenario produced no decisions"
    for r in recs:
        assert r["op"] == "autopilot"
        assert set(r) >= {"seq", "kind", "target", "args", "reason",
                          "knobs", "workload", "pstate"}
        assert r["workload"] == "tune-wl"
    assert learn_priors(recs).get("tune-wl", {}).get("batch_hint") \
        == sim.batch


# --------------------------------------------- acceptance: predictive
def _ticks_to_fixpoint(sim: FleetSim) -> int:
    """1-based tick at which the transport batch reached its final
    value and never moved again."""
    hist = []
    for e in sim.trace.entries:
        b = e["obs"]["batch"]
        for d in e["decisions"]:
            if d["kind"] == "tune" and d["args"].get("batch_hint"):
                b = d["args"]["batch_hint"]
        hist.append(b)
    final = hist[-1]
    assert sim.batch == final
    return 1 + next(i for i in range(len(hist))
                    if all(x == final for x in hist[i:]))


def test_predictive_reaches_fixpoint_in_fewer_ticks():
    """The predictive acceptance law: on the same replayed workload the
    forecast-driven tune arm jumps every ladder rung in one decision,
    reaching the knob fixpoint in measurably fewer ticks than the
    reactive doubling ladder — and at the SAME fixpoint."""
    reactive = _tune_sim(seed=3, predictive=False)
    predictive = _tune_sim(seed=3, predictive=True)
    assert predictive.batch == reactive.batch == 16384
    tr, tp = _ticks_to_fixpoint(reactive), _ticks_to_fixpoint(predictive)
    assert tp < tr, f"predictive {tp} ticks vs reactive {tr}"
    assert tr - tp >= 2, f"gain not measurable: {tr} vs {tp}"
    assert predictive.registry.get("sim_tunes") \
        < reactive.registry.get("sim_tunes")


def test_predictive_sheds_before_forecast_saturation():
    """A fleet-wide surge with a rising slope: the predictive shed arm
    acts on the forecast throttle pressure no later than the reactive
    one waits for the observed refusals."""

    def run(predictive):
        cfg = PolicyConfig(min_batch=1024, max_batch=1024,
                           min_inflight=2, max_inflight=2,
                           predictive=predictive)
        sim = FleetSim(
            world=64, n_shards=2, n=64 << 20,
            workload=fs.workload.hotspot(
                2500.0, hot_lo=0, hot_hi=64, factor=40.0, at_s=4.0,
                ramp_s=12.0, key="surge-wl"),
            seed=9, config=cfg,
            latency=LatencyModel(seed=9,
                                 calibration=Calibration(rpc=(8.0, 0.05))))
        sim.run(16)
        for e in sim.trace.entries:
            if any(d["kind"] == "shed" for d in e["decisions"]):
                return e["tick"]
        return None

    t_reactive, t_predictive = run(False), run(True)
    assert t_reactive is not None and t_predictive is not None
    assert t_predictive <= t_reactive


# ------------------------------------- acceptance: unattended hotspot
def test_hotspot_5000_ranks_resolves_via_split_unattended():
    """The 5 000-rank acceptance scenario: one shard's rank band ramps
    to 10x demand against a deliberately tight capacity model; the
    policy splits (and rebalances) the hot shard with no operator
    action, and the fleet ends the run unthrottled with headroom."""
    cfg = PolicyConfig(min_batch=1024, max_batch=1024, min_inflight=2,
                       max_inflight=4, hot_factor=2.0, split_p99_ms=5.0,
                       struct_cooldown_s=3.0, target_rpc_per_s=1e9)
    sim = FleetSim(
        world=5000, n_shards=4, n=5000 << 20,
        workload=fs.workload.hotspot(10.0, hot_lo=0, hot_hi=1250,
                                     factor=10.0, at_s=5.0, ramp_s=5.0),
        seed=7, config=cfg,
        latency=LatencyModel(seed=7,
                             calibration=Calibration(rpc=(40.0, 0.05))))
    sim.run(40)
    assert sim.registry.get("sim_splits") >= 1
    assert len(sim.live_shards()) > 4
    # resolved: the last window throttled nothing and utilization has
    # real headroom on every live shard
    assert sim.trace.entries[-1]["obs"]["throttled"] == 0
    assert sim.max_util() < 0.9
    # the structural moves were decided by the real policy and are in
    # the replayable log
    kinds = {d["kind"] for d in sim.trace.decisions()}
    assert "split" in kinds
    sim.trace.verify_replay(
        lambda: AutopilotPolicy(cfg, clock=lambda: 0.0, seed=sim.seed))


# ------------------------------------------ acceptance: warm restarts
def test_warm_started_priors_reproduce_converged_knobs():
    """Priors learned from a run's WAL records make a RESTARTED
    deployment jump to the converged knobs in one warm-start decision
    and stay there — no re-climb of the doubling ladder."""
    first = _tune_sim(seed=3)
    assert first.policy.state_dict()["priors"], "no prior confirmed"
    priors = learn_priors(first.trace.wal_records())
    assert priors["tune-wl"]["batch_hint"] == first.batch

    second = FleetSim(world=8, n_shards=2, n=8 << 20,
                      workload=fs.workload.uniform(100_000.0,
                                                   key="tune-wl"),
                      seed=3, config=PolicyConfig())
    second.policy.load_state_dict(warm_state(priors))
    second.run(1)
    d0 = second.trace.entries[0]["decisions"]
    assert len(d0) == 1 and d0[0]["kind"] == "tune"
    assert d0[0]["reason"].startswith("warm start from prior")
    assert second.batch == first.batch
    second.run(9)
    # converged immediately: the warm-start tune was the ONLY tune
    assert second.registry.get("sim_tunes") == 1
    assert second.batch == first.batch


# --------------------------------------- satellite: backend_pick arm
def test_backend_pick_agrees_between_sim_and_real_plane():
    """``backend_pick`` is on by default, and on identical workload
    shapes the simulated plane and a REAL two-shard plane (its own
    ``_observe``, the same injected cost probe) emit the identical
    ``pick_backend`` decision."""
    assert PolicyConfig().backend_pick is True
    rcm = RegenCostModel(host_backend="cpu")

    sim = FleetSim(world=4, n_shards=2, n=40 << 20,
                   workload=fs.workload.uniform(5000.0, key="backend-wl"),
                   seed=5, backend="cpu", regen_cost=rcm)
    sim.run(1)
    sim_d = sim.trace.entries[0]["decisions"]
    assert [d["kind"] for d in sim_d] == ["pick_backend"]
    assert sim_d[0]["args"] == {"backend": "xla"}

    spec = PartialShuffleSpec.plain(40 << 20, window=4096, world=4)
    clk = SimClock(100.0)
    with ShardPlane(spec, 2) as plane:
        ap = Autopilot(
            plane=plane, clock=clk,
            policy=AutopilotPolicy(PolicyConfig(), clock=clk, seed=5),
            backend_probe=lambda n: (rcm.pick(n)[0], rcm.pick(n)[2]))
        clk.advance(1.0)
        real_d = [decision_to_dict(d) for d in ap.tick()]
    assert real_d == sim_d


def test_backend_probe_gated_below_min_samples():
    """Tiny specs never pay (or log) a backend probe: the controller's
    size gate keeps the arm silent below BACKEND_PROBE_MIN_SAMPLES per
    rank, so toy deployments stay byte-identical to the reactive
    baseline."""
    spec = PartialShuffleSpec.plain(2048, window=128, world=2)
    clk = SimClock(100.0)
    with ShardPlane(spec, 2) as plane:
        ap = Autopilot(plane=plane, clock=clk)
        clk.advance(1.0)
        obs = ap._observe()
    assert spec.n // spec.world < Autopilot.BACKEND_PROBE_MIN_SAMPLES
    assert "backend_candidate" not in obs


# ------------------------------ satellite: seeded sim/real parity law
@pytest.mark.parametrize("seed", [11, 23])
def test_sim_and_real_plane_decide_identically(seed, tmp_path):
    """The parity law, end to end: a simulated hotspot run's metric
    snapshots replayed through a REAL two-shard plane (real servers,
    real WAL on disk, real split/merge/migrate actuations) produce the
    IDENTICAL decision stream and the identical ``autopilot`` WAL
    records — field for field, including the policy state each record
    carries."""
    cfg = PolicyConfig(min_batch=256, max_batch=256, min_inflight=2,
                       max_inflight=4, hot_factor=1.5, split_p99_ms=0.2,
                       struct_cooldown_s=3.0, target_rpc_per_s=1e9)
    sim = FleetSim(
        world=8, n_shards=2, n=4096,
        workload=fs.workload.hotspot(1000.0, hot_lo=0, hot_hi=4,
                                     factor=10.0, at_s=3.0, ramp_s=4.0,
                                     key="parity-wl"),
        seed=seed, config=cfg, batch0=256, backend="cpu",
        latency=LatencyModel(seed=seed,
                             calibration=Calibration(rpc=(40.0, 0.05))))
    sim.run(12)
    kinds = {d["kind"] for d in sim.trace.decisions()}
    assert "split" in kinds, f"scenario lost its structural move: {kinds}"

    # replay through a trace-file round trip: what an operator replays
    trace = DecisionTrace.from_jsonl(sim.trace.to_jsonl())
    obs_iter = iter([e["obs"] for e in trace.entries])
    rcm = sim.regen_cost
    spec = PartialShuffleSpec.plain(4096, window=256, world=8)
    wal_dir = str(tmp_path / "plane-wal")
    with ShardPlane(spec, 2, wal_dir=wal_dir) as plane:
        ap = Autopilot(
            plane=plane, clock=lambda: 0.0,
            policy=AutopilotPolicy(cfg, clock=lambda: 0.0, seed=seed),
            observe=lambda: next(obs_iter, None),
            backend_probe=lambda n: (rcm.pick(n)[0], rcm.pick(n)[2]))
        real_stream = [[decision_to_dict(d) for d in ap.tick()]
                       for _ in range(len(trace))]
        # the observation stream is exhausted: further ticks are no-ops
        assert ap.tick() == []
        assert plane.map.n_shards > 2    # the split really happened

    sim_stream = [e["decisions"] for e in trace.entries]
    assert real_stream == sim_stream

    recs = read_autopilot_records(f"{wal_dir}/0")
    got = [{k: v for k, v in r.items() if k != "lsn"} for r in recs]
    assert got == trace.wal_records()


# ------------------------------------------------- chaos: fault sites
def test_chaos_sim_event_fault_drops_one_event_only():
    """An injected ``sim.event`` error drops exactly that dispatch —
    counted, never fatal — and every other queued event still fires
    (parity with the live controller surviving one bad tick)."""
    reg = MetricsRegistry()
    clk = SimClock()
    loop = EventLoop(clk, registry=reg)
    fired = []
    for i in range(5):
        loop.at(float(i + 1), lambda i=i: fired.append(i))
    with F.FaultPlan([F.FaultRule(site="sim.event", kind="error",
                                  nth=3)]) as plan:
        loop.run_until(10.0)
        assert plan.fired("sim.event") == 1
    assert fired == [0, 1, 3, 4]         # the third dispatch was eaten
    assert reg.get("sim_event_faults") == 1
    assert reg.get("sim_events") == 4
    assert clk() == 10.0


def test_chaos_sim_inject_fault_suppresses_the_scenario_injection():
    """An injected ``sim.inject`` error eats the scenario injection
    (the surge never lands, the run matches the unperturbed baseline)
    and is counted on the sim registry."""

    def run(faulted):
        sim = _build()
        if faulted:
            with F.FaultPlan([F.FaultRule(site="sim.inject",
                                          kind="error")]) as plan:
                sim.run(8)
                assert plan.fired("sim.inject") == 1
        else:
            sim.run(8)
        return sim

    def _build():
        sim = FleetSim(world=8, n_shards=2, n=8 << 20,
                       workload=fs.workload.uniform(100_000.0,
                                                    key="inj-wl"),
                       seed=3, config=PolicyConfig())
        sim.inject_surge(at_s=2.5, factor=4.0)
        return sim

    baseline = _tune_sim(seed=3, ticks=8)
    surged, eaten = run(False), run(True)
    assert surged.registry.get("sim_injected") == 1
    assert eaten.registry.get("sim_injected") == 0
    assert eaten.registry.get("sim_inject_faults") == 1
    # the eaten injection leaves the run identical to no injection at
    # all (workload key aside, the decision stream matches)
    assert [e["decisions"] for e in eaten.trace.entries] \
        == [e["decisions"] for e in baseline.trace.entries]
    assert surged.trace.decision_log() != baseline.trace.decision_log()


# ------------------------------------- acceptance: cell-kill DR drill
def test_cell_kill_5000_ranks_flips_directory_and_replays():
    """The federated DR drill at fleet scale (docs/FEDERATION.md): a
    5 000-rank fleet loses its entire home cell mid-epoch.  The DR cell
    promotes — the directory flips every tenant in ONE version bump,
    the fleet's next window rides a full failover freeze — and the
    decision/WAL trace stays byte-identical across runs and replays
    deterministically through a fresh policy."""
    def _build():
        sim = FleetSim(
            world=5000, n_shards=4, n=5000 << 20,
            workload=fs.workload.uniform(50_000.0, key="dr-wl"),
            seed=7, config=PolicyConfig(),
            cells=("east", "west"),
            latency=LatencyModel(seed=7))
        sim.inject_cell_kill(at_s=10.0)
        return sim

    a = _build().run(25)
    b = _build().run(25)
    # determinism law: same scenario + seed → identical bytes, overlay
    # keys (cell / directory version+fingerprint) included
    assert a.trace.to_jsonl() == b.trace.to_jsonl()
    assert a.trace.decision_log() == b.trace.decision_log()

    assert a.registry.get("sim_cell_kills") == 1
    assert a.cell == "west"
    assert a.cell_directory.version == 2
    assert a.cell_directory.home("any-tenant") == "west"
    st = a.status()
    assert st["cell"] == "west" and st["directory_version"] == 2

    # the flip happens exactly once, never reverts, and bumps the
    # directory fingerprint with it
    cells = [e["cell"] for e in a.trace.entries]
    assert cells[0] == "east" and cells[-1] == "west"
    flips = [i for i in range(1, len(cells)) if cells[i] != cells[i - 1]]
    assert len(flips) == 1
    versions = [e["directory_version"] for e in a.trace.entries]
    assert sorted(set(versions)) == [1, 2]
    fps = {e["directory_version"]: e["directory_fingerprint"]
           for e in a.trace.entries}
    assert fps[1] != fps[2]
    # the kill's failover barrier froze the post-flip window: observed
    # demand on every live shard collapses for exactly that tick
    k = flips[0]
    pre, post = a.trace.entries[k - 1]["obs"], a.trace.entries[k]["obs"]
    assert post["served"] < pre["served"]

    # replay law: the recorded stream reproduces through a FRESH policy
    trace = DecisionTrace.from_jsonl(a.trace.to_jsonl())
    trace.verify_replay(
        lambda: AutopilotPolicy(PolicyConfig(), clock=lambda: 0.0,
                                seed=a.seed))
