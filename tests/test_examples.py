"""Smoke-run the driver-config examples end to end (subprocesses — the 10B
example enables global x64, and each example manages its own platform).

The older examples (torch_ddp, jax_training, webdataset_shards) are driven
by make-check adjacent tests and their own __main__ guards; the two added
for configs 2 and 5 are gated here so the five BASELINE.json configs all
stay runnable.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(name: str, extra_env=None, timeout=420) -> str:
    env = dict(os.environ)
    # examples choose their own jax platform; drop the conftest forcing
    env.pop("JAX_PLATFORMS", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, (
        f"{name} failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    return proc.stdout


def test_imagenet_resnet_example():
    # force the 8-virtual-device CPU platform so tier 3 (the JAX-native
    # ViT mesh run) executes rather than skipping on the 1-chip device
    out = run_example("imagenet_resnet_example.py", {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    assert "partition + window locality OK" in out
    assert "resumed 8 remaining steps exactly" in out
    assert "tier 3: JAX-native ViT" in out
    assert "ok: config-2 shape end to end" in out


def test_llama3_10b_index_example():
    out = run_example("llama3_10b_index_example.py",
                      {"PSDS_EXAMPLE_FAST": "1"})
    assert "bit-identical to numpy" in out
    assert "rank 0 won" in out
    assert "ok: config-5 shape end to end" in out


def test_multi_tenant_example():
    # same platform pinning as the service example: the tenancy story is
    # pure host/wire behavior
    out = run_example("multi_tenant_example.py", {"JAX_PLATFORMS": "cpu"},
                      timeout=180)
    assert "2 namespaces: both streams bit-identical" in out
    assert "then streamed exactly" in out
    assert "fair-share queue, streams exact" in out
    assert "ok: multi-tenant service end to end" in out


def test_index_service_example():
    # pin the CPU platform: the service/loader parity is platform-free and
    # the emulated-TPU tunnel makes the per-batch device_puts crawl
    out = run_example("index_service_example.py", {"JAX_PLATFORMS": "cpu"},
                      timeout=180)
    assert "bit-identical to the local sampler" in out
    assert "exactly-once, bit-identical" in out
    assert "ok: index service end to end" in out
