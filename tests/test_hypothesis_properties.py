"""Hypothesis property suite — SURVEY.md §4's prescribed randomized
invariant tests over ``(n, window, world, seed, epoch, ...)``.

The fixed-grid tests elsewhere pin known-awkward shapes; this suite lets
hypothesis hunt for unknown-awkward ones.  ``derandomize=True`` keeps CI
deterministic (the corpus is derived from the property's source).

Invariants (SURVEY §4):
 1. partition — ranks' shards are equal-length, in-range, and their union
    is exactly the wrap-padded epoch stream;
 2. determinism — same config, same output;
 3. epoch variation — a different epoch permutes differently;
 4. windowing law — an emitted index's source window is the outer
    bijection's image of its slot (locality: with order_windows=False every
    body index stays inside its own window);
 5. degenerate configs are exercised by the same strategies (window=1,
    window >= n, world=1, n % world != 0, drop_last both ways);
 6. random access (stream_indices_at) agrees with the materialized epoch;
 8. cpu <-> xla bit-identity (smaller space: each distinct config is a
    fresh XLA compile).
"""

import numpy as np
import pytest

# hypothesis is an optional dev dependency this container does not ship;
# importorskip turns what was a tier-1 collection ERROR into one loud,
# reasoned skip.  The fixed-grid suites keep covering the same
# invariants deterministically; install hypothesis to hunt new shapes.
pytest.importorskip(
    "hypothesis",
    reason="hypothesis not installed in this environment; the "
           "randomized property hunt is a dev-box extra (the fixed-grid "
           "suites cover these invariants deterministically)")
from hypothesis import HealthCheck, assume, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from partiallyshuffledistributedsampler_tpu.ops import core, cpu

SETTINGS = dict(
    deadline=None,
    derandomize=True,
    suppress_health_check=[HealthCheck.too_slow],
)

CONFIGS = st.fixed_dictionaries(dict(
    n=st.integers(1, 5000),
    window=st.integers(1, 600),
    world=st.integers(1, 9),
    seed=st.integers(0, 2**63 - 1),
    epoch=st.integers(0, 1000),
    drop_last=st.booleans(),
    order_windows=st.booleans(),
    partition=st.sampled_from(["strided", "blocked"]),
))


def _ranks(cfg):
    return [
        cpu.epoch_indices_np(
            cfg["n"], cfg["window"], cfg["seed"], cfg["epoch"], r,
            cfg["world"], drop_last=cfg["drop_last"],
            order_windows=cfg["order_windows"], partition=cfg["partition"],
        )
        for r in range(cfg["world"])
    ]


@settings(max_examples=120, **SETTINGS)
@given(cfg=CONFIGS)
def test_partition_union_and_lengths(cfg):
    n, world = cfg["n"], cfg["world"]
    num_samples, total = core.shard_sizes(n, world, cfg["drop_last"])
    outs = _ranks(cfg)
    for o in outs:
        assert len(o) == num_samples
        if num_samples:
            assert o.min() >= 0 and o.max() < n
    # union across ranks == the wrap-padded epoch stream as a multiset:
    # value f(q) appears once per stream position p < total with p % n == q
    if num_samples == 0:
        return
    counts = np.bincount(np.concatenate(outs), minlength=n)
    f = cpu.stream_indices_at_np(
        np.arange(min(n, total)), n, cfg["window"], cfg["seed"],
        cfg["epoch"], order_windows=cfg["order_windows"],
    )
    # the first min(n, total) stream entries are distinct (f restricted to
    # one wrap is injective — the permutation law is a bijection)
    assert len(np.unique(f)) == len(f)
    expected = np.zeros(n, dtype=np.int64)
    q = np.arange(min(n, total))
    expected[f[q]] = total // n + (q < total % n) if total >= n else 1
    np.testing.assert_array_equal(counts, expected)


@settings(max_examples=60, **SETTINGS)
@given(cfg=CONFIGS)
def test_determinism_and_random_access(cfg):
    outs = _ranks(cfg)
    again = _ranks(cfg)
    for a, b in zip(outs, again):
        np.testing.assert_array_equal(a, b)
    # invariant 6: random access reproduces the materialized stream
    num_samples, total = core.shard_sizes(
        cfg["n"], cfg["world"], cfg["drop_last"]
    )
    if num_samples == 0 or cfg["partition"] != "strided":
        return
    r = cfg["world"] - 1
    pos = (r + cfg["world"] * np.arange(num_samples)) % cfg["n"]
    via_stream = cpu.stream_indices_at_np(
        pos, cfg["n"], cfg["window"], cfg["seed"], cfg["epoch"],
        order_windows=cfg["order_windows"],
    )
    np.testing.assert_array_equal(outs[r], via_stream)


@settings(max_examples=60, **SETTINGS)
@given(cfg=CONFIGS)
def test_epoch_variation(cfg):
    n, w = cfg["n"], cfg["window"]
    # shuffling must be non-degenerate for epochs to differ: some window
    # has >= 2 elements, or >= 2 whole windows get reordered
    assume(n >= 16)
    assume(min(w, n) >= 2 or (cfg["order_windows"] and n // w >= 2))
    f0 = cpu.full_epoch_stream_np(
        n, w, cfg["seed"], cfg["epoch"], order_windows=cfg["order_windows"]
    )
    f1 = cpu.full_epoch_stream_np(
        n, w, cfg["seed"], cfg["epoch"] + 1,
        order_windows=cfg["order_windows"],
    )
    assert not np.array_equal(f0, f1)


@settings(max_examples=80, **SETTINGS)
@given(cfg=CONFIGS)
def test_windowing_law(cfg):
    """Invariant 4: stream slot k's indices come from exactly one source
    window — the outer bijection's image — and with order_windows=False
    every body index stays inside its own window."""
    n, w = cfg["n"], cfg["window"]
    assume(n >= w)  # at least one whole window
    f = cpu.full_epoch_stream_np(
        n, w, cfg["seed"], cfg["epoch"], order_windows=cfg["order_windows"]
    )
    nw = n // w
    body = nw * w
    slots = np.arange(body) // w
    src = f[:body] // w
    # within a slot, all indices share one source window
    for k in range(nw):
        uniq = np.unique(src[slots == k])
        assert len(uniq) == 1
        if not cfg["order_windows"]:
            assert uniq[0] == k
    # and the slot->source map is a bijection over the whole windows
    slot_src = src[::w][:nw]
    assert len(np.unique(slot_src)) == nw


@settings(max_examples=25, **SETTINGS)
@given(cfg=st.fixed_dictionaries(dict(
    n=st.integers(1, 900),
    window=st.integers(1, 200),
    world=st.integers(1, 5),
    seed=st.integers(0, 2**63 - 1),
    epoch=st.integers(0, 50),
    drop_last=st.booleans(),
    order_windows=st.booleans(),
    partition=st.sampled_from(["strided", "blocked"]),
)))
def test_cpu_xla_parity(cfg):
    """Invariant 8 under hypothesis: every generated config compiles its own
    XLA executable, so the space is kept smaller than the host-only tests."""
    from partiallyshuffledistributedsampler_tpu.ops.xla import (
        epoch_indices_jax,
    )

    rank = cfg["world"] - 1
    ref = cpu.epoch_indices_np(
        cfg["n"], cfg["window"], cfg["seed"], cfg["epoch"], rank,
        cfg["world"], drop_last=cfg["drop_last"],
        order_windows=cfg["order_windows"], partition=cfg["partition"],
    )
    got = np.asarray(epoch_indices_jax(
        cfg["n"], cfg["window"], cfg["seed"], cfg["epoch"], rank,
        cfg["world"], drop_last=cfg["drop_last"],
        order_windows=cfg["order_windows"], partition=cfg["partition"],
    ))
    assert got.dtype == ref.dtype
    np.testing.assert_array_equal(got, ref)


# --------------------------------------------------------------- fold_seed
@settings(max_examples=40, **SETTINGS)
@given(seed=st.integers(-(2**80), 2**80))
def test_fold_seed_wide_and_negative(seed):
    """SPEC §1 folding is airtight for any int: halves land in uint32
    range, bits >= 64 are dropped, negatives wrap two's-complement —
    and the fold, not the raw int, is what indexes."""
    lo, hi = core.fold_seed(seed)
    assert 0 <= lo <= 0xFFFFFFFF and 0 <= hi <= 0xFFFFFFFF
    assert lo == seed & 0xFFFFFFFF
    assert hi == (seed >> 32) & 0xFFFFFFFF
    np.testing.assert_array_equal(
        cpu.epoch_indices_np(64, 8, seed, 0, 0, 1),
        cpu.epoch_indices_np(64, 8, seed % 2**64, 0, 0, 1),
    )


def test_fold_seed_tuple_validation():
    # a hand-split (lo, hi) pair must be shape- and range-checked rather
    # than wrapping silently at the later dtype cast
    assert core.fold_seed((3, 4)) == (3, 4)
    with pytest.raises(ValueError, match="length"):
        core.fold_seed((1, 2, 3))
    with pytest.raises(ValueError, match="uint32"):
        core.fold_seed((2**32, 0))
    with pytest.raises(ValueError, match="uint32"):
        core.fold_seed((0, -1))


def test_fold_seed_traced_scalar_path():
    # a traced uint32 seed flows through (hi = 0) and matches the concrete
    # fold of the same value
    import jax
    import jax.numpy as jnp

    from partiallyshuffledistributedsampler_tpu.ops.xla import (
        epoch_indices_jax,
    )

    @jax.jit
    def f(s):
        return epoch_indices_jax(64, 8, s, 0, 0, 1)

    np.testing.assert_array_equal(
        np.asarray(f(jnp.uint32(1234))),
        cpu.epoch_indices_np(64, 8, 1234, 0, 0, 1),
    )


from conftest import assert_exactly_once  # shared SPEC §6 law assertion


@settings(max_examples=30, **SETTINGS)
@given(cfg=st.fixed_dictionaries(dict(
    n=st.integers(10, 2000),
    window=st.integers(1, 300),
    old_world=st.integers(1, 6),
    new_world=st.integers(1, 6),
    seed=st.integers(0, 2**63 - 1),
    epoch=st.integers(0, 50),
    partition=st.sampled_from(["strided", "blocked"]),
    frac=st.floats(0.0, 1.0),
)))
def test_elastic_exactly_once_property(cfg):
    """SPEC.md §6 under hypothesis: for random (old_world -> new_world)
    reshards at a random mid-epoch offset, consumed prefix + all new
    ranks' remainders == the full epoch stream plus only legal wrap-pad
    extras.  Generalizes the fixed-grid cases in test_elastic_and_state."""
    from partiallyshuffledistributedsampler_tpu import (
        PartiallyShuffleDistributedSampler as S,
    )

    n, w = cfg["n"], cfg["window"]
    ow, nw_ = cfg["old_world"], cfg["new_world"]
    num_samples, _ = core.shard_sizes(n, ow, False)
    assume(num_samples >= 2)
    consumed = min(int(cfg["frac"] * num_samples), num_samples - 1)

    old = [
        S(n, num_replicas=ow, rank=r, window=w, seed=cfg["seed"],
          partition=cfg["partition"], backend="cpu")
        for r in range(ow)
    ]
    consumed_vals = []
    for s in old:
        s.set_epoch(cfg["epoch"])
        it = iter(s)
        consumed_vals += [next(it) for _ in range(consumed)]
        it.close()
    state = old[0].state_dict()
    assert state["offset"] == consumed

    remainder_vals = []
    for r in range(nw_):
        es = S.reshard_from_state_dict(
            state, num_replicas=nw_, rank=r, backend="cpu"
        )
        remainder_vals += list(es)

    stream = cpu.full_epoch_stream_np(
        n, w, cfg["seed"], cfg["epoch"], world=ow
    )
    assert_exactly_once(consumed_vals, remainder_vals, stream, ow,
                        consumed, cfg["partition"], nw_)


# ---------------------------------------------------------------------------
# Mixture stream (SPEC.md §8)
# ---------------------------------------------------------------------------

MIX_CONFIGS = st.fixed_dictionaries(dict(
    sizes=st.lists(st.integers(1, 800), min_size=1, max_size=5),
    weights_seed=st.integers(0, 2**31 - 1),
    block=st.integers(4, 300),
    seed=st.integers(0, 2**63 - 1),
    epoch=st.integers(0, 1000),
    world=st.integers(1, 7),
    partition=st.sampled_from(["strided", "blocked"]),
))


def _mix_spec(cfg):
    from partiallyshuffledistributedsampler_tpu.ops import mixture as M

    rng = np.random.default_rng(cfg["weights_seed"])
    weights = rng.integers(1, 20, size=len(cfg["sizes"])).tolist()
    try:
        return M.MixtureSpec(
            cfg["sizes"], weights,
            windows=int(rng.integers(1, 200)), block=cfg["block"],
        )
    except ValueError:
        return None  # starved source for this (weights, block) draw


@settings(max_examples=60, **SETTINGS)
@given(cfg=MIX_CONFIGS)
def test_mixture_quotas_pattern_and_partition(cfg):
    """§8 invariants under random configs: quotas sum to the block and are
    realized exactly by every aligned block; the rank partition
    reinterleaves to the total stream; per-(epoch, pass) draws from a
    source never repeat."""
    from partiallyshuffledistributedsampler_tpu.ops import mixture as M

    spec = _mix_spec(cfg)
    if spec is None:
        return
    assert sum(spec.quotas) == spec.block
    counts = np.bincount(spec.pattern, minlength=spec.num_sources)
    assert tuple(counts) == spec.quotas

    world = cfg["world"]
    shards = [
        M.mixture_epoch_indices_np(
            spec, cfg["seed"], cfg["epoch"], r, world,
            partition=cfg["partition"],
        )
        for r in range(world)
    ]
    ns = len(shards[0])
    assert all(len(s) == ns for s in shards)
    inter = np.empty(ns * world, dtype=shards[0].dtype)
    for r, x in enumerate(shards):
        if cfg["partition"] == "strided":
            inter[r::world] = x
        else:
            inter[r * ns:(r + 1) * ns] = x
    ref = M.mixture_stream_at_np(
        np.arange(ns * world), spec, cfg["seed"], cfg["epoch"])
    assert np.array_equal(inter, ref)

    # per-(epoch, pass) no-repeat, per source, over the full stream
    src, loc = spec.decompose(ref)
    for s in range(spec.num_sources):
        ls = loc[src == s]
        n_s = spec.sources[s]
        for p0 in range(0, len(ls), n_s):
            chunk = ls[p0:p0 + n_s]
            assert len(np.unique(chunk)) == len(chunk), (s, p0)


@settings(max_examples=40, **SETTINGS)
@given(cfg=MIX_CONFIGS)
def test_mixture_determinism_and_block_proportions(cfg):
    from partiallyshuffledistributedsampler_tpu.ops import mixture as M

    spec = _mix_spec(cfg)
    if spec is None:
        return
    a = M.mixture_epoch_indices_np(spec, cfg["seed"], cfg["epoch"], 0, 1)
    b = M.mixture_epoch_indices_np(spec, cfg["seed"], cfg["epoch"], 0, 1)
    assert np.array_equal(a, b)
    src, _ = spec.decompose(a)
    B = spec.block
    for blk in range(len(a) // B):
        c = np.bincount(src[blk * B:(blk + 1) * B],
                        minlength=spec.num_sources)
        assert tuple(c) == spec.quotas


@settings(max_examples=30, **SETTINGS)
@given(cfg=MIX_CONFIGS, frac=st.floats(0.05, 0.95),
       new_world=st.integers(1, 5))
def test_mixture_elastic_reshard_law(cfg, frac, new_world):
    """Randomized §6-over-§8: resharding a mixture mid-epoch serves, on
    each new rank, exactly the stream values at the composed remainder
    positions; sizes follow the §6 length law."""
    from partiallyshuffledistributedsampler_tpu.ops import mixture as M

    spec = _mix_spec(cfg)
    if spec is None:
        return
    V = cfg["world"]
    T = spec.total_sources_len
    ns_V = -(-T // V)
    if ns_V < 2:
        return  # nothing can be mid-epoch-consumed and still remain
    consumed = max(1, min(int(frac * ns_V), ns_V - 1))
    layers = [(V, consumed)]
    R = (ns_V - consumed) * V
    ns_new = -(-R // new_world)
    for r in range(new_world):
        got = M.mixture_elastic_indices_np(
            spec, cfg["seed"], cfg["epoch"], r, new_world, layers,
            partition=cfg["partition"])
        assert len(got) == ns_new
        q = core.rank_positions(
            np, R, r, new_world, ns_new, cfg["partition"], np.uint32)
        pos = core.remaining_stream_positions(
            np, q, V, ns_V, consumed, cfg["partition"], np.uint32)
        ref = M.mixture_stream_at_np(pos, spec, cfg["seed"], cfg["epoch"])
        assert np.array_equal(got, ref)


@settings(max_examples=50, **SETTINGS)
@given(cfg=MIX_CONFIGS, pv=st.integers(1, 2))
def test_mixture_fused_equals_masked_random_configs(cfg, pv):
    """The fused per-lane evaluator must equal the masked per-source
    reference over RANDOM mixture configs and both pattern versions —
    fuzzing the branch space (packed/two-tiny/chained lane parameters,
    tails, multi-pass sources, tiny windows, rotation wrap) that the
    fixed-case parity tests enumerate by hand."""
    from partiallyshuffledistributedsampler_tpu.ops import mixture as M

    spec = _mix_spec(cfg)
    if spec is None:
        return
    if pv == 1:
        spec = M.MixtureSpec(spec.sources, spec.weights,
                             windows=list(spec.windows), block=spec.block,
                             pattern_version=1)
    rng = np.random.default_rng(cfg["weights_seed"] ^ 0xA5)
    pos = np.concatenate([
        np.arange(min(300, sum(spec.sources))),
        rng.integers(0, 4 * sum(spec.sources) + 1, 100),
    ])
    a = M.mixture_stream_at_generic(np, pos, spec, cfg["seed"],
                                    cfg["epoch"], fused=False,
                                    amortize=False)
    b = M.mixture_stream_at_generic(np, pos, spec, cfg["seed"],
                                    cfg["epoch"], fused=True)
    assert np.array_equal(a, b)


@settings(max_examples=40, **SETTINGS)
@given(cfg=MIX_CONFIGS, pv=st.integers(1, 2))
def test_mixture_native_equals_numpy_random_configs(cfg, pv):
    """The C++ §8 kernel vs the numpy reference over random configs and
    both pattern versions — the executor-matrix counterpart of the fused
    fuzz (pass wrapping, rotation, tails, partitions, epoch lengths)."""
    from partiallyshuffledistributedsampler_tpu.ops import mixture as M
    from partiallyshuffledistributedsampler_tpu.ops import native

    if not native.available():
        return  # toolchain-less env: the dedicated suite skips too
    spec = _mix_spec(cfg)
    if spec is None:
        return
    if pv == 1:
        spec = M.MixtureSpec(spec.sources, spec.weights,
                             windows=list(spec.windows), block=spec.block,
                             pattern_version=1)
    world = cfg["world"]
    rank = cfg["weights_seed"] % world
    kw = dict(partition=cfg["partition"],
              epoch_samples=1 + cfg["block"] * 3)
    a = M.mixture_epoch_indices_np(spec, cfg["seed"], cfg["epoch"], rank,
                                   world, **kw)
    b = native.mixture_epoch_indices_native(spec, cfg["seed"],
                                            cfg["epoch"], rank, world, **kw)
    assert np.array_equal(a, b)
