"""Multi-device regen + ICI seed agreement on the virtual 8-device CPU mesh
(SURVEY.md §4 invariant 8: testable without a pod via
xla_force_host_platform_device_count; conftest.py sets it)."""

import jax
import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops import cpu
from partiallyshuffledistributedsampler_tpu.parallel import (
    data_mesh,
    sharded_epoch_indices,
)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return data_mesh(8)


def test_sharded_matches_cpu_reference(mesh8):
    n, w, seed, epoch = 10_000, 512, 42, 3
    out = np.asarray(sharded_epoch_indices(mesh8, n, w, seed, epoch))
    assert out.shape == (8, 1250)
    for r in range(8):
        ref = cpu.epoch_indices_np(n, w, seed, epoch, r, 8)
        np.testing.assert_array_equal(out[r], ref)


def test_output_is_sharded_over_mesh(mesh8):
    out = sharded_epoch_indices(mesh8, 8000, 128, 0, 0)
    # each row must live on its own device — indices are generated in place,
    # never gathered through the host
    assert len(out.sharding.device_set) == 8
    shard_rows = sorted(
        (s.index[0].start or 0) for s in out.addressable_shards
    )
    assert shard_rows == list(range(8))


def test_seed_agreement_rank0_wins(mesh8):
    # devices disagree wildly; the ICI collective must impose rank 0's triple
    n, w = 5000, 64
    local = np.stack(
        [
            np.asarray([123, 0, 7], np.uint32),          # rank 0: the truth
            *[np.asarray([999 + r, r, 60 + r], np.uint32) for r in range(1, 8)]
        ]
    )
    out = np.asarray(
        sharded_epoch_indices(mesh8, n, w, None, None, local_seeds=local)
    )
    for r in range(8):
        ref = cpu.epoch_indices_np(n, w, 123, 7, r, 8)
        np.testing.assert_array_equal(out[r], ref)


def test_seed_agreement_is_deterministic_collective(mesh8):
    a = np.asarray(sharded_epoch_indices(mesh8, 4096, 256, 5, 1))
    b = np.asarray(sharded_epoch_indices(mesh8, 4096, 256, 5, 1))
    np.testing.assert_array_equal(a, b)


def test_epoch_change_reuses_executable(mesh8):
    from partiallyshuffledistributedsampler_tpu.parallel import sharded

    sharded_epoch_indices(mesh8, 2048, 64, 1, 0)
    before = sharded._compiled_sharded.cache_info().misses
    sharded_epoch_indices(mesh8, 2048, 64, 1, 1)
    sharded_epoch_indices(mesh8, 2048, 64, 2, 2)
    assert sharded._compiled_sharded.cache_info().misses == before


def test_drop_last_and_blocked(mesh8):
    out = np.asarray(
        sharded_epoch_indices(
            mesh8, 10_001, 100, 9, 2, drop_last=True, partition="blocked"
        )
    )
    assert out.shape == (8, 1250)
    flat = out.ravel()
    assert len(np.unique(flat)) == len(flat)  # disjoint under drop_last


def test_smaller_mesh_subset():
    m = data_mesh(4)
    out = np.asarray(sharded_epoch_indices(m, 1000, 32, 0, 0))
    assert out.shape == (4, 250)
    for r in range(4):
        np.testing.assert_array_equal(
            out[r], cpu.epoch_indices_np(1000, 32, 0, 0, r, 4)
        )


def test_bad_local_seeds_shape(mesh8):
    with pytest.raises(ValueError, match="world"):
        sharded_epoch_indices(
            mesh8, 100, 10, None, None,
            local_seeds=np.zeros((4, 3), np.uint32),
        )


# ------------------------------------------------- mesh elastic resharding
def _state(n, old_world, consumed, seed, epoch, window):
    return {
        "spec_version": 1, "seed": seed, "epoch": epoch, "offset": consumed,
        "n": n, "num_replicas": old_world, "window": window, "rounds": 24,
        "order_windows": True, "partition": "strided", "shuffle": True,
        "drop_last": False,
    }


def test_sharded_elastic_matches_cpu_shim(mesh8):
    # VERDICT r3 missing #2: the remainder epoch as ONE shard_map program —
    # every row must equal the torch shim's cpu reshard stream bit-exactly
    from partiallyshuffledistributedsampler_tpu import (
        PartiallyShuffleDistributedSampler as S,
    )
    from partiallyshuffledistributedsampler_tpu.parallel import (
        sharded_elastic_indices,
    )

    n, w, seed, epoch, old_world, consumed = 3000, 64, 11, 4, 3, 101
    out = np.asarray(
        sharded_elastic_indices(mesh8, n, w, seed, epoch,
                                [(old_world, consumed)])
    )
    state = _state(n, old_world, consumed, seed, epoch, w)
    for r in range(8):
        ref = list(S.reshard_from_state_dict(
            state, num_replicas=8, rank=r, backend="cpu"
        ))
        np.testing.assert_array_equal(out[r], ref)


def test_sharded_elastic_exactly_once(mesh8):
    # SPEC §6 law at mesh level: consumed prefix + union of device rows
    # covers the epoch exactly once (modulo legal wrap-pad extras)
    from conftest import assert_exactly_once
    from partiallyshuffledistributedsampler_tpu import (
        PartiallyShuffleDistributedSampler as S,
    )
    from partiallyshuffledistributedsampler_tpu.parallel import (
        sharded_elastic_indices,
    )

    n, w, seed, epoch, old_world, consumed = 1100, 32, 9, 2, 3, 77
    consumed_vals = []
    for r in range(old_world):
        s = S(n, num_replicas=old_world, rank=r, window=w, seed=seed,
              backend="cpu")
        s.set_epoch(epoch)
        it = iter(s)
        consumed_vals += [next(it) for _ in range(consumed)]
        it.close()
    out = np.asarray(
        sharded_elastic_indices(mesh8, n, w, seed, epoch,
                                [(old_world, consumed)])
    )
    stream = cpu.full_epoch_stream_np(n, w, seed, epoch, world=old_world)
    assert_exactly_once(consumed_vals, out.ravel().tolist(), stream,
                        old_world, consumed, "strided", 8)


def test_sharded_elastic_cascade_and_agreement(mesh8):
    # cascading layers (§6.1) + disagreeing local seeds: rank 0's triple
    # wins over ICI and every row matches the numpy chain composition
    from partiallyshuffledistributedsampler_tpu.ops import core
    from partiallyshuffledistributedsampler_tpu.parallel import (
        sharded_elastic_indices,
    )

    n, w = 2000, 32
    layers = [(3, 50), (5, 40)]
    _chain, _remaining, ns = core.elastic_chain(n, layers, 8, False)
    local = np.stack(
        [[7, 0, 9]] + [[1000 + r, r, 77 + r] for r in range(1, 8)]
    ).astype(np.uint32)
    out = np.asarray(
        sharded_elastic_indices(mesh8, n, w, None, None, layers,
                                local_seeds=local)
    )
    assert out.shape == (8, ns)
    for r in range(8):
        # rank 0's (seed=7, epoch=9) must have won the ICI agreement
        np.testing.assert_array_equal(
            out[r], cpu.elastic_indices_np(n, w, 7, 9, r, 8, layers)
        )


def test_sharded_elastic_empty_remainder(mesh8):
    from partiallyshuffledistributedsampler_tpu.ops import core as _core
    from partiallyshuffledistributedsampler_tpu.parallel import (
        sharded_elastic_indices,
    )

    ns0, _ = _core.shard_sizes(80, 4, False)
    out = sharded_elastic_indices(mesh8, 80, 16, 0, 0, [(4, ns0)])
    assert out.shape == (8, 0)


def test_sharded_elastic_drop_last_floors_to_none(mesh8):
    # drop_last with 0 < remaining < world: num_samples floors to 0 and the
    # factory must return fn=None (the documented nothing-to-run contract)
    from partiallyshuffledistributedsampler_tpu.ops import core as _core
    from partiallyshuffledistributedsampler_tpu.parallel import (
        make_elastic_regen_fn,
    )

    ns0, _ = _core.shard_sizes(80, 4, True)  # 20 per rank
    fn, ns = make_elastic_regen_fn(mesh8, 80, 16, [(4, ns0 - 1)],
                                   drop_last=True)
    # remaining = 4, world = 8 -> floor(4/8) = 0 per rank
    assert fn is None and ns == 0
