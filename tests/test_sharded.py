"""Multi-device regen + ICI seed agreement on the virtual 8-device CPU mesh
(SURVEY.md §4 invariant 8: testable without a pod via
xla_force_host_platform_device_count; conftest.py sets it)."""

import jax
import numpy as np
import pytest

from partiallyshuffledistributedsampler_tpu.ops import cpu
from partiallyshuffledistributedsampler_tpu.parallel import (
    data_mesh,
    sharded_epoch_indices,
)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return data_mesh(8)


def test_sharded_matches_cpu_reference(mesh8):
    n, w, seed, epoch = 10_000, 512, 42, 3
    out = np.asarray(sharded_epoch_indices(mesh8, n, w, seed, epoch))
    assert out.shape == (8, 1250)
    for r in range(8):
        ref = cpu.epoch_indices_np(n, w, seed, epoch, r, 8)
        np.testing.assert_array_equal(out[r], ref)


def test_output_is_sharded_over_mesh(mesh8):
    out = sharded_epoch_indices(mesh8, 8000, 128, 0, 0)
    # each row must live on its own device — indices are generated in place,
    # never gathered through the host
    assert len(out.sharding.device_set) == 8
    shard_rows = sorted(
        (s.index[0].start or 0) for s in out.addressable_shards
    )
    assert shard_rows == list(range(8))


def test_seed_agreement_rank0_wins(mesh8):
    # devices disagree wildly; the ICI collective must impose rank 0's triple
    n, w = 5000, 64
    local = np.stack(
        [
            np.asarray([123, 0, 7], np.uint32),          # rank 0: the truth
            *[np.asarray([999 + r, r, 60 + r], np.uint32) for r in range(1, 8)]
        ]
    )
    out = np.asarray(
        sharded_epoch_indices(mesh8, n, w, None, None, local_seeds=local)
    )
    for r in range(8):
        ref = cpu.epoch_indices_np(n, w, 123, 7, r, 8)
        np.testing.assert_array_equal(out[r], ref)


def test_seed_agreement_is_deterministic_collective(mesh8):
    a = np.asarray(sharded_epoch_indices(mesh8, 4096, 256, 5, 1))
    b = np.asarray(sharded_epoch_indices(mesh8, 4096, 256, 5, 1))
    np.testing.assert_array_equal(a, b)


def test_epoch_change_reuses_executable(mesh8):
    from partiallyshuffledistributedsampler_tpu.parallel import sharded

    sharded_epoch_indices(mesh8, 2048, 64, 1, 0)
    before = sharded._compiled_sharded.cache_info().misses
    sharded_epoch_indices(mesh8, 2048, 64, 1, 1)
    sharded_epoch_indices(mesh8, 2048, 64, 2, 2)
    assert sharded._compiled_sharded.cache_info().misses == before


def test_drop_last_and_blocked(mesh8):
    out = np.asarray(
        sharded_epoch_indices(
            mesh8, 10_001, 100, 9, 2, drop_last=True, partition="blocked"
        )
    )
    assert out.shape == (8, 1250)
    flat = out.ravel()
    assert len(np.unique(flat)) == len(flat)  # disjoint under drop_last


def test_smaller_mesh_subset():
    m = data_mesh(4)
    out = np.asarray(sharded_epoch_indices(m, 1000, 32, 0, 0))
    assert out.shape == (4, 250)
    for r in range(4):
        np.testing.assert_array_equal(
            out[r], cpu.epoch_indices_np(1000, 32, 0, 0, r, 4)
        )


def test_bad_local_seeds_shape(mesh8):
    with pytest.raises(ValueError, match="world"):
        sharded_epoch_indices(
            mesh8, 100, 10, None, None,
            local_seeds=np.zeros((4, 3), np.uint32),
        )
