"""docs/API.md (and docs/OBSERVABILITY.md) must match the package
(round-4 verdict: the doc stated DEFAULT_WINDOW=8192 while the code says
4096 — a user sizing windows from the doc got a different permutation
than documented).

The gate scrapes every ``### `Name(signature)` `` heading plus the spec-
defaults table row, imports the named symbols, and asserts each documented
``kwarg=default`` against ``inspect.signature``.  If the docs and the code
diverge again, this file fails.
"""

import ast
import inspect
import re
from pathlib import Path

import pytest

DOCS = Path(__file__).resolve().parent.parent / "docs"
API_MD = DOCS / "API.md"
OBSERVABILITY_MD = DOCS / "OBSERVABILITY.md"

#: where the heading-documented classes/functions live
_NAMESPACES = (
    "partiallyshuffledistributedsampler_tpu",
    "partiallyshuffledistributedsampler_tpu.sampler",
    "partiallyshuffledistributedsampler_tpu.ops",
    "partiallyshuffledistributedsampler_tpu.ops.cpu",
    "partiallyshuffledistributedsampler_tpu.service",
    "partiallyshuffledistributedsampler_tpu.sharding",
    "partiallyshuffledistributedsampler_tpu.federation",
    "partiallyshuffledistributedsampler_tpu.autopilot",
    "partiallyshuffledistributedsampler_tpu.fleetsim",
    "partiallyshuffledistributedsampler_tpu.capability",
    "partiallyshuffledistributedsampler_tpu.streaming",
    "partiallyshuffledistributedsampler_tpu.sampling",
    "partiallyshuffledistributedsampler_tpu.telemetry",
    "partiallyshuffledistributedsampler_tpu.utils",
)


def _resolve(name: str):
    import importlib

    for ns in _NAMESPACES:
        mod = importlib.import_module(ns)
        if hasattr(mod, name):
            return getattr(mod, name)
    raise AssertionError(f"API.md documents {name!r}, not importable from "
                         f"any of {_NAMESPACES}")


def _split_args(argstr: str):
    """Top-level comma split (the documented signatures nest no parens)."""
    return [a.strip() for a in argstr.split(",") if a.strip()]


def _documented_signatures():
    for doc in (API_MD, OBSERVABILITY_MD):
        text = doc.read_text()
        # the ###-heading signatures
        for m in re.finditer(r"^### `(\w+)\((.*)\)`\s*$", text, re.M):
            yield m.group(1), m.group(2)
    # the top-table reference-implementation row
    text = API_MD.read_text()
    m = re.search(r"`epoch_indices_np\(([^`]*)\)`", text)
    assert m, "API.md lost the epoch_indices_np row"
    yield "epoch_indices_np", m.group(1)


def _doc_defaults(argstr: str):
    out = {}
    for tok in _split_args(argstr):
        if tok.startswith("*") or tok in ("...",) or "=" not in tok:
            continue
        k, v = tok.split("=", 1)
        try:
            out[k.strip()] = ast.literal_eval(v.strip())
        except (ValueError, SyntaxError):
            continue  # prose placeholders like ...same...
    return out


@pytest.mark.parametrize("name,argstr", list(_documented_signatures()))
def test_documented_signature_matches_code(name, argstr):
    obj = _resolve(name)
    fn = obj.__init__ if inspect.isclass(obj) else obj
    sig = inspect.signature(fn)
    params = sig.parameters
    for k, doc_default in _doc_defaults(argstr).items():
        assert k in params, (
            f"API.md documents {name}(... {k}=...) but the signature has "
            f"no such parameter: {sig}"
        )
        actual = params[k].default
        assert actual is not inspect.Parameter.empty, (
            f"API.md gives {name}.{k} a default {doc_default!r}; the code "
            "has none"
        )
        assert actual == doc_default, (
            f"API.md says {name}(... {k}={doc_default!r} ...) but the code "
            f"default is {actual!r}"
        )
    # every documented bare (non-defaulted, non-star) name must exist too
    for tok in _split_args(argstr):
        if tok.startswith("*") or "=" in tok or not tok.isidentifier():
            continue
        assert tok in params, (
            f"API.md documents {name}(... {tok} ...) not in {sig}"
        )


def test_spec_defaults_row_matches_constants():
    import partiallyshuffledistributedsampler_tpu as psds

    text = API_MD.read_text()
    m = re.search(
        r"`DEFAULT_WINDOW`, `DEFAULT_ROUNDS` \| spec defaults "
        r"\((\d+), (\d+)\)", text,
    )
    assert m, "API.md lost the spec-defaults row"
    assert int(m.group(1)) == psds.DEFAULT_WINDOW
    assert int(m.group(2)) == psds.DEFAULT_ROUNDS


def test_mixture_iterator_windows_documented_behavior():
    """The API.md claim 'reading window raises' is itself load-bearing —
    pin it here next to the signature checks."""
    text = API_MD.read_text()
    assert "`windows` (property)" in text and "`window` raises" in text


def test_observability_doc_cross_linked():
    """docs/OBSERVABILITY.md exists and the docs that gained telemetry
    behavior point at it — an operator reading about the service, the
    failure model, or the API must be one hop from the tracing story."""
    assert OBSERVABILITY_MD.exists()
    for doc in ("SERVICE.md", "RESILIENCE.md", "API.md"):
        assert "OBSERVABILITY.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/OBSERVABILITY.md"
        )
    readme = DOCS.parent / "README.md"
    assert "docs/OBSERVABILITY.md" in readme.read_text()
    # the protocol table documents the telemetry RPC pair
    svc = (DOCS / "SERVICE.md").read_text()
    assert "TRACE_DUMP" in svc and "TRACE_REPORT" in svc


def test_analysis_doc_cross_linked():
    """docs/ANALYSIS.md exists, the docs whose invariants it enforces
    point at it, and its load-bearing claims (waiver syntax, sanitizer
    env var, the make gate) stay documented."""
    analysis_md = DOCS / "ANALYSIS.md"
    assert analysis_md.exists()
    for doc in ("ARCHITECTURE.md", "RESILIENCE.md"):
        assert "ANALYSIS.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/ANALYSIS.md"
        )
    readme = (DOCS.parent / "README.md").read_text()
    assert "docs/ANALYSIS.md" in readme
    text = analysis_md.read_text()
    for token in ("make analyze", "PSDS_SANITIZE=1", "allow-broad-except",
                  "allow-unguarded", "allow-wallclock",
                  "render_violations", "sanitize_overhead_within_noise"):
        assert token in text, f"docs/ANALYSIS.md lost `{token}`"
    # the documented pass names must be the registered ones
    from partiallyshuffledistributedsampler_tpu.analysis import lint

    for name in lint.PASSES:
        assert f"`{name}`" in text, (
            f"docs/ANALYSIS.md does not document the `{name}` pass"
        )


def test_tenancy_doc_cross_linked():
    """The multi-tenant surface is documented where an operator would
    look: SERVICE.md owns the namespace/quota/fair-share story (with
    both wire codes), API.md documents the knobs, OBSERVABILITY.md the
    per-tenant metric names."""
    svc = (DOCS / "SERVICE.md").read_text()
    assert "## Tenancy" in svc, "docs/SERVICE.md lost its Tenancy section"
    for token in ("spec_mismatch", "tenant_admission", "max_tenants",
                  "tenants_created", "tenancy-smoke"):
        assert token in svc, f"docs/SERVICE.md Tenancy lost `{token}`"
    api = API_MD.read_text()
    for token in ("multi_tenant=False", "TenantQuota", "FairShareScheduler",
                  "SpecMismatchError"):
        assert token in api, f"docs/API.md lost the tenancy surface `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("regen_queue_ms", "tenant_admission_rejects",
                  "admission_waits"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the per-tenant metric `{token}`"
        )


def test_durability_doc_cross_linked():
    """The durability surface is documented where an operator would
    look: RESILIENCE.md owns the WAL/checkpoint/recovery story (fsync
    policies, crash-matrix contract, the make gate), API.md documents
    the knobs, OBSERVABILITY.md the metric names."""
    res = (DOCS / "RESILIENCE.md").read_text()
    assert "## Durability & recovery" in res, (
        "docs/RESILIENCE.md lost its Durability & recovery section")
    for token in ("wal_dir", "group_commit", "per_record", "wal_lsn",
                  "check_invariants", "durability-smoke",
                  "kill-at-any-byte"):
        assert token in res, f"docs/RESILIENCE.md Durability lost `{token}`"
    api = API_MD.read_text()
    for token in ("wal_dir=None", "fsync='group_commit'",
                  "durable=False"):
        assert token in api, f"docs/API.md lost the durability knob `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("wal_torn_tails", "wal_segments_gced", "wal_recoveries",
                  "snapshot_fallbacks", "repl_wal_reads", "wal_fsync_ms",
                  "recovery_replay_ms"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the durability metric `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    for site in ("wal.append", "wal.fsync", "wal.rotate"):
        assert site in F.SITES and site in res


def test_fusion_doc_cross_linked():
    """The serve-path fusion surface is documented where an operator
    would look: SERVICE.md owns the pipelining/piggyback story (the
    protocol fields, the guarded-terminal-ack rule, the make gate),
    API.md documents the knobs, OBSERVABILITY.md the metric names, and
    RESILIENCE.md the fault sites the chaos matrix drives."""
    svc = (DOCS / "SERVICE.md").read_text()
    assert "## Serve-path fusion" in svc, (
        "docs/SERVICE.md lost its Serve-path fusion section")
    for token in ("lookahead", "max_inflight", "hb", "coalesced",
                  "fused-smoke", "slow start"):
        assert token in svc, f"docs/SERVICE.md fusion lost `{token}`"
    api = API_MD.read_text()
    for token in ("lookahead=4", "boundary_prefetch=True",
                  "Serve-path fusion"):
        assert token in api, f"docs/API.md lost the fusion surface `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("step_serve_ms", "rpcs_per_step"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the fusion metric `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    res = (DOCS / "RESILIENCE.md").read_text()
    for site in ("client.pipeline", "loader.boundary"):
        assert site in F.SITES and site in res


def test_capability_doc_cross_linked():
    """Capability mode is documented where an operator would look:
    docs/CAPABILITY.md owns the token/slack/drain/fallback story (and
    the make gate), SERVICE.md carries the protocol frames and a
    section pointing at it, API.md documents the knobs on all three
    surfaces, OBSERVABILITY.md the metric names, and RESILIENCE.md the
    fault sites plus the failure-contract rows."""
    cap_md = DOCS / "CAPABILITY.md"
    assert cap_md.exists()
    text = cap_md.read_text()
    for token in ("EpochCapability", "HMAC", "GET_CAPABILITY",
                  "capability_stale", "capability_unsupported",
                  "capability_secret", "cap_drain", "target_samples",
                  "membership_stream", "replay_trail", "ack + 1",
                  "capability-smoke", "Fallback ladder"):
        assert token in text, f"docs/CAPABILITY.md lost `{token}`"
    for doc in ("SERVICE.md", "RESILIENCE.md", "SHARDING.md", "API.md"):
        assert "CAPABILITY.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/CAPABILITY.md")
    assert "docs/CAPABILITY.md" in (DOCS.parent / "README.md").read_text()
    svc = (DOCS / "SERVICE.md").read_text()
    assert "## Capability mode" in svc, (
        "docs/SERVICE.md lost its Capability mode section")
    for token in ("GET_CAPABILITY", "CAPABILITY"):
        assert token in svc, f"docs/SERVICE.md lost the `{token}` frame"
    api = API_MD.read_text()
    for token in ("capability_secret=None", "capability_heartbeat_s=1.0",
                  "capability_mode=False", "EpochCapability",
                  "membership_stream", "replay_trail", "CapabilityError"):
        assert token in api, f"docs/API.md lost the capability surface `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("capabilities_issued", "capability_rejects",
                  "capability_stale", "capability_fallbacks",
                  "capability_issue_ms"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the capability metric `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    res = (DOCS / "RESILIENCE.md").read_text()
    for site in ("capability.issue", "capability.verify"):
        assert site in F.SITES and site in res


def test_streaming_doc_cross_linked():
    """Streaming mode is documented where an operator would look:
    docs/STREAMING.md owns the horizon/eligibility/advance/re-weighting
    story (and the make gate), SERVICE.md carries the APPEND frame and a
    section pointing at it, API.md documents the knobs on every surface,
    OBSERVABILITY.md the metric names, CAPABILITY.md the per-horizon
    grants, and RESILIENCE.md the fault sites plus the failure-contract
    rows."""
    streaming_md = DOCS / "STREAMING.md"
    assert streaming_md.exists()
    text = streaming_md.read_text()
    for token in ("StreamSpec", "horizon", "APPEND", "horizon_pending",
                  "horizon_advance", "stream_seq", "weights_delta",
                  "stream_weights", "stream_batches",
                  "capability_stream_batches", "streaming=True",
                  "Advance under reshard", "streaming-smoke"):
        assert token in text, f"docs/STREAMING.md lost `{token}`"
    for doc in ("SERVICE.md", "RESILIENCE.md", "CAPABILITY.md", "API.md"):
        assert "STREAMING.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/STREAMING.md")
    assert "docs/STREAMING.md" in (DOCS.parent / "README.md").read_text()
    svc = (DOCS / "SERVICE.md").read_text()
    assert "## Streaming mode" in svc, (
        "docs/SERVICE.md lost its Streaming mode section")
    assert "APPEND" in svc, "docs/SERVICE.md lost the `APPEND` frame"
    api = API_MD.read_text()
    for token in ("StreamSpec", "streaming=False", "horizon=None",
                  "attach=False", "stream_batches", "eligible_horizons",
                  "with_stream_weights"):
        assert token in api, f"docs/API.md lost the streaming surface `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("stream_appends", "horizon_advances",
                  "stream_gc_truncations", "horizon_advance_ms",
                  "append_visible_ms", "stream_waits"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the streaming metric `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    res = (DOCS / "RESILIENCE.md").read_text()
    for site in ("stream.append", "stream.advance"):
        assert site in F.SITES and site in res


def test_sharding_doc_cross_linked():
    """The sharded serving plane is documented where an operator would
    look: docs/SHARDING.md owns the map/redirect/barrier story (and the
    make gate + scaling law the smoke's docstring points at), SERVICE.md
    and ARCHITECTURE.md link to it, API.md documents the four classes,
    OBSERVABILITY.md the metric names, and RESILIENCE.md the fault sites
    plus the failure contract rows."""
    sharding_md = DOCS / "SHARDING.md"
    assert sharding_md.exists()
    text = sharding_md.read_text()
    assert "## Scaling law" in text, (
        "docs/SHARDING.md lost its Scaling law section — "
        "benchmarks/sharding_smoke.py's docstring points at it")
    for token in ("shard_map", "wrong_shard", "fingerprint", "retry_ms",
                  "dead_ranks", "prepare", "commit",
                  "sharding-smoke", "ShardPlane"):
        assert token in text, f"docs/SHARDING.md lost `{token}`"
    for doc in ("SERVICE.md", "ARCHITECTURE.md", "RESILIENCE.md"):
        assert "SHARDING.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/SHARDING.md")
    assert "docs/SHARDING.md" in (DOCS.parent / "README.md").read_text()
    svc = (DOCS / "SERVICE.md").read_text()
    assert "## Scale-out sharding" in svc, (
        "docs/SERVICE.md lost its Scale-out sharding section")
    api = API_MD.read_text()
    for token in ("ShardMap", "ShardServer", "ShardRouter", "ShardPlane",
                  "wrong_shard"):
        assert token in api, f"docs/API.md lost the sharding surface `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("router_hellos", "router_redirects", "router_route_ms",
                  "shard_barriers", "shard_barrier_ms",
                  "wrong_shard_hellos", "wrong_shard_redirects"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the sharding metric `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    res = (DOCS / "RESILIENCE.md").read_text()
    for site in ("router.route", "shard.barrier"):
        assert site in F.SITES and site in res


def test_autopilot_doc_cross_linked():
    """The autopilot is documented where an operator would look:
    docs/AUTOPILOT.md owns the loop/arms/migration story (and the make
    gate), SERVICE.md / SHARDING.md / RESILIENCE.md / OBSERVABILITY.md
    and README.md link to it, API.md documents the public surface, and
    every ``autopilot_*`` metric the controller registers is in the
    OBSERVABILITY.md inventory."""
    autopilot_md = DOCS / "AUTOPILOT.md"
    assert autopilot_md.exists()
    text = autopilot_md.read_text()
    for token in ("Autopilot", "AutopilotPolicy", "PolicyConfig",
                  "BackpressurePolicy", "state_dict", "batch_hint",
                  "max_inflight", "wrong_shard", "prepare", "commit",
                  "moved_spans", "drill_interval_s", "autopilot-smoke",
                  "zero protocol bytes"):
        assert token in text, f"docs/AUTOPILOT.md lost `{token}`"
    for doc in ("SERVICE.md", "SHARDING.md", "RESILIENCE.md",
                "OBSERVABILITY.md", "API.md"):
        assert "AUTOPILOT.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/AUTOPILOT.md")
    assert "docs/AUTOPILOT.md" in (DOCS.parent / "README.md").read_text()
    api = API_MD.read_text()
    for token in ("Autopilot(server=None", "AutopilotPolicy",
                  "PolicyConfig", "BackpressurePolicy",
                  "set_autopilot_knobs", "auto_batch=True",
                  "split_shard", "merge_shards", "migrate_ranks"):
        assert token in api, f"docs/API.md lost the autopilot surface `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("autopilot_decisions", "autopilot_tunes",
                  "autopilot_sheds", "autopilot_splits",
                  "autopilot_merges", "autopilot_migrations",
                  "autopilot_drills", "autopilot_backend_picks",
                  "autopilot_decide_errors", "autopilot_tick_ms",
                  "autopilot_drill_ms", "shard_migrations",
                  "shard_migrate_ms", "migrated_redirects"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the autopilot metric `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    res = (DOCS / "RESILIENCE.md").read_text()
    for site in ("autopilot.decide", "shard.split", "shard.migrate"):
        assert site in F.SITES and site in res


def test_simulator_doc_cross_linked():
    """The fleet simulator is documented where an operator would look:
    docs/SIMULATOR.md owns the event/latency/trace/replay story (and
    the make gate), AUTOPILOT.md / SHARDING.md / ARCHITECTURE.md /
    RESILIENCE.md / OBSERVABILITY.md and README.md link to it, API.md
    documents the public surface, every ``sim_*`` metric the simulator
    counts is in the OBSERVABILITY.md inventory, and the documented
    fault sites are the registered ones."""
    simulator_md = DOCS / "SIMULATOR.md"
    assert simulator_md.exists()
    text = simulator_md.read_text()
    for token in ("FleetSim", "AutopilotPolicy", "BackpressurePolicy",
                  "ShardMap", "SimClock", "EventLoop", "DecisionTrace",
                  "LatencyModel", "Calibration.from_bench",
                  "RegenCostModel", "byte-identical", "wal_records",
                  "verify_replay", "read_autopilot_records",
                  "sim-smoke", "sim.event", "sim.inject",
                  "map_fingerprint"):
        assert token in text, f"docs/SIMULATOR.md lost `{token}`"
    for doc in ("AUTOPILOT.md", "SHARDING.md", "ARCHITECTURE.md",
                "RESILIENCE.md", "OBSERVABILITY.md"):
        assert "SIMULATOR.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/SIMULATOR.md")
    assert "docs/SIMULATOR.md" in (DOCS.parent / "README.md").read_text()
    api = API_MD.read_text()
    for token in ("FleetSim(*, world, n_shards, n, workload",
                  "LatencyModel", "Calibration", "RegenCostModel",
                  "DecisionTrace", "SimClock", "EventLoop", "Workload",
                  "backend_probe", "observe=", "learn_priors",
                  "warm_state"):
        assert token in api, f"docs/API.md lost the fleetsim surface `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("sim_events", "sim_event_faults", "sim_ticks",
                  "sim_decisions", "sim_tunes", "sim_sheds",
                  "sim_backend_picks", "sim_splits", "sim_merges",
                  "sim_migrations", "sim_drills", "sim_injected",
                  "sim_inject_faults", "sim_actuation_errors"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the simulator metric `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    res = (DOCS / "RESILIENCE.md").read_text()
    for site in ("sim.event", "sim.inject"):
        assert site in F.SITES and site in res


def test_sampling_doc_cross_linked():
    """The sampling modes are documented where an operator would look:
    docs/SAMPLING.md owns the alias/weight-update/dedup-lifecycle story
    (and the make gate), SERVICE.md carries the SET_EPOCH weights_delta
    field and a section pointing at it, API.md documents the spec and
    kernel surface, OBSERVABILITY.md the counter plus the degradation
    events, CAPABILITY.md the weights-carrying grants, and
    RESILIENCE.md the fault sites."""
    sampling_md = DOCS / "SAMPLING.md"
    assert sampling_md.exists()
    text = sampling_md.read_text()
    for token in ("SamplingSpec", "weighted", "prioritized", "dedup",
                  "alias", "weights_delta", "with_stream_weights",
                  "stream_weights", "seen-set", "dedup_boundary_wire",
                  "with_dedup_boundary", "UNIFORM",
                  "sampling.alias_build", "sampling.dedup_check",
                  "sampling_reweights", "sampling-smoke"):
        assert token in text, f"docs/SAMPLING.md lost `{token}`"
    for doc in ("SERVICE.md", "RESILIENCE.md", "CAPABILITY.md",
                "STREAMING.md", "API.md"):
        assert "SAMPLING.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/SAMPLING.md")
    assert "docs/SAMPLING.md" in (DOCS.parent / "README.md").read_text()
    svc = (DOCS / "SERVICE.md").read_text()
    assert "## Sampling modes" in svc, (
        "docs/SERVICE.md lost its Sampling modes section")
    assert "weights_delta" in svc, (
        "docs/SERVICE.md lost the SET_EPOCH `weights_delta` field")
    api = API_MD.read_text()
    for token in ("SamplingSpec", "build_alias_table",
                  "weighted_epoch_indices_np", "weighted_epoch_indices_jax",
                  "make_seen", "fold_epoch", "dedup_check",
                  "weights_delta", "dedup_boundary_wire"):
        assert token in api, f"docs/API.md lost the sampling surface `{token}`"
    obs = OBSERVABILITY_MD.read_text()
    for token in ("sampling_reweights", "sampling_alias_fallback",
                  "sampling_dedup_failsafe", "sampling_dedup_saturated"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the sampling token `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    res = (DOCS / "RESILIENCE.md").read_text()
    for site in ("sampling.alias_build", "sampling.dedup_check"):
        assert site in F.SITES and site in res


def test_federation_doc_cross_linked():
    """The multi-cell plane is documented where an operator would
    look: docs/FEDERATION.md owns the directory/shipping/fencing/
    migration story (and the make gate), SERVICE.md / SHARDING.md /
    CAPABILITY.md / RESILIENCE.md / OBSERVABILITY.md / API.md and
    README.md link to it, API.md documents the public surface,
    OBSERVABILITY.md the metric names, and the documented fault sites
    are the registered ones."""
    federation_md = DOCS / "FEDERATION.md"
    assert federation_md.exists()
    text = federation_md.read_text()
    for token in ("Cell", "Federation", "CellDirectory", "DirectoryRef",
                  "wrong_cell", "WalShipper", "CellKeyring", "TrustBundle",
                  "fenced", "flip_cell", "migrate_tenant",
                  "MigrationAborted", "failover_ms", "kill-at-any-byte",
                  "federation-smoke"):
        assert token in text, f"docs/FEDERATION.md lost `{token}`"
    for doc in ("SERVICE.md", "SHARDING.md", "CAPABILITY.md",
                "RESILIENCE.md", "OBSERVABILITY.md", "API.md"):
        assert "FEDERATION.md" in (DOCS / doc).read_text(), (
            f"docs/{doc} lost its cross-link to docs/FEDERATION.md")
    assert "docs/FEDERATION.md" in (DOCS.parent / "README.md").read_text()
    svc = (DOCS / "SERVICE.md").read_text()
    assert "## Multi-cell federation" in svc, (
        "docs/SERVICE.md lost its Multi-cell federation section")
    assert "wrong_cell" in svc, (
        "docs/SERVICE.md lost the `wrong_cell` redirect")
    api = API_MD.read_text()
    for token in ("CellDirectory(cells", "DirectoryRef(directory=None",
                  "CellKeyring", "TrustBundle(keyrings=()", "WalShipper",
                  "Cell(cell_id", "Federation(spec, *, root",
                  "migrate_tenant", "MigrationAborted"):
        assert token in api, (
            f"docs/API.md lost the federation surface `{token}`")
    obs = OBSERVABILITY_MD.read_text()
    for token in ("cell_shipped", "cell_ship_resyncs", "cell_ship_lag_ms",
                  "cell_redirects", "wrong_cell_redirects", "cell_fenced",
                  "cell_fence_faults", "federation_failovers",
                  "federation_migrations", "federation_migrate_aborts",
                  "sim_cell_kills"):
        assert token in obs, (
            f"docs/OBSERVABILITY.md lost the federation metric `{token}`")
    # the documented fault sites must be the registered ones
    from partiallyshuffledistributedsampler_tpu import faults as F

    res = (DOCS / "RESILIENCE.md").read_text()
    for site in ("cell.ship", "cell.fence", "cell.migrate"):
        assert site in F.SITES and site in res
